"""Text classification as one Pipeline: Tokenizer -> HashingTF -> sparse
LogisticRegression (the features column crosses string -> tokens ->
SparseVector, and training runs the padded-CSR path end-to-end).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.builder.pipeline import Pipeline
from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression
from flink_ml_tpu.models.feature.hashing_tf import HashingTF
from flink_ml_tpu.models.feature.tokenizer import Tokenizer


def main():
    rng = np.random.default_rng(0)
    sports = "game team score win goal match play season league cup".split()
    cooking = "bake oven recipe flour sugar stir dough taste dish salt".split()
    texts, labels = [], []
    for words, label in ((sports, 0.0), (cooking, 1.0)):
        for _ in range(40):
            texts.append(" ".join(rng.choice(words, 6)))
            labels.append(label)
    train = DataFrame(["text", "label"], None, [texts, np.asarray(labels)])

    pipeline = Pipeline([
        Tokenizer().set_input_col("text").set_output_col("tokens"),
        HashingTF().set_input_col("tokens").set_output_col("features").set_num_features(1 << 16),
        LogisticRegression().set_features_col("features").set_max_iter(60)
        .set_learning_rate(1.0).set_global_batch_size(32).set_tol(0.0),
    ])
    model = pipeline.fit(train)

    queries = DataFrame(["text"], None, [[
        "the team won the match", "stir the flour and sugar",
    ]])
    for text, pred in zip(queries["text"], model.transform(queries)["prediction"]):
        print(f"{text!r} -> {'sports' if pred == 0.0 else 'cooking'}")


if __name__ == "__main__":
    main()
