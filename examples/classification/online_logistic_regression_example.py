"""Trains an OnlineLogisticRegression model on a stream of batches.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/classification/OnlineLogisticRegressionExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import DenseVector
from flink_ml_tpu.models.classification.online_logistic_regression import (
    OnlineLogisticRegression,
)
from flink_ml_tpu.models.online import QueueBatchStream


def main():
    rng = np.random.default_rng(1)
    stream = QueueBatchStream()
    init = DataFrame(["coefficient"], None, [[DenseVector(np.zeros(2))]])
    model = (
        OnlineLogisticRegression()
        .set_initial_model_data(init)
        .set_global_batch_size(32)
        .fit(stream)
    )
    for step in range(5):
        X = rng.normal(size=(32, 2))
        y = (X[:, 0] - X[:, 1] > 0).astype(np.float64)
        stream.add({"features": X, "label": y})
        model.advance()
        print(f"model version {model.model_version}: coefficient = {model.coefficient}")

    test = DataFrame.from_dict({"features": np.asarray([[2.0, -1.0], [-1.0, 2.0]])})
    out = model.transform(test)
    for features, pred in zip(test["features"], out["prediction"]):
        print(f"Features: {features}\tPrediction: {pred}")


if __name__ == "__main__":
    main()
