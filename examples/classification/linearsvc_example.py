"""Trains a LinearSVC model and uses it for classification.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/classification/LinearSVCExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.classification.linearsvc import LinearSVC


def main():
    X = np.asarray([[1.0, 2.0], [2.0, 2.0], [3.0, 2.0], [11.0, 3.0], [12.0, 4.0], [13.0, 2.0]])
    y = np.asarray([0.0, 0.0, 0.0, 1.0, 1.0, 1.0])
    train = DataFrame.from_dict({"features": X, "label": y})

    model = LinearSVC().set_max_iter(50).fit(train)
    output = model.transform(train)
    for features, label, pred in zip(X, y, output["prediction"]):
        print(f"Features: {features}\tExpected: {label}\tPrediction: {pred}")


if __name__ == "__main__":
    main()
