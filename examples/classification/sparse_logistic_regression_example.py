"""Trains LogisticRegression on wide sparse features (padded-CSR layout).

Parity: the reference's SparseVector training path (SparseVector.java +
BLAS.java sparse branches); here the whole batch stays in [n, K]
index/value arrays so a 2^18-dim model never materializes densified.
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression


def main():
    rng = np.random.default_rng(0)
    n, d, nnz = 512, 1 << 18, 8
    idx = np.stack([rng.choice(d, nnz, replace=False) for _ in range(n)])
    vals = rng.standard_normal((n, nnz)).astype(np.float32)
    w_true = np.zeros(d, np.float32)
    hot = rng.choice(d, 64, replace=False)
    w_true[hot] = rng.standard_normal(64)
    y = (np.sum(vals * w_true[idx], axis=1) > 0).astype(np.float64)
    rows = [SparseVector(d, np.sort(r), v[np.argsort(r)]) for r, v in zip(idx, vals)]
    train = DataFrame.from_dict({"features": rows, "label": y})

    model = (
        LogisticRegression()
        .set_max_iter(100)
        .set_global_batch_size(256)
        .set_learning_rate(1.0)
        .set_tol(0.0)
        .fit(train)
    )
    out = model.transform(train)
    acc = float(np.mean(out["prediction"] == y))
    print(f"coefficient dim: {model.coefficient.shape[0]}, train accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
