"""Kill/resume of online training: checkpointed stream position + state.

Parity: the reference makes unbounded training recoverable by
checkpointing source offsets alongside operator state
(flink-ml-iteration/.../checkpoint/Checkpoints.java:43-143; SGD's
batch-offset state flink-ml-lib/.../common/optimizer/SGD.java:308-347,
exercised by UnboundedStreamIterationITCase). Here the estimator's
set_checkpoint() snapshots (version == stream offset, training state); a
resumed fit() restores the newest snapshot and fast-forwards the replayed
source past the consumed prefix — versions continue with no reuse and no
gap.
"""
import tempfile

import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.checkpoint import CheckpointManager
from flink_ml_tpu.linalg.vectors import DenseVector
from flink_ml_tpu.models.classification.online_logistic_regression import (
    OnlineLogisticRegression,
)
from flink_ml_tpu.models.online import QueueBatchStream


def batch(seed):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(32, 2))
    return {"features": X, "label": (X[:, 0] - X[:, 1] > 0).astype(np.float64)}


def feed(batches):
    stream = QueueBatchStream()
    for b in batches:
        stream.add(b)
    return stream.close()


def estimator(ckpt_dir):
    init = DataFrame(["coefficient"], None, [[DenseVector(np.zeros(2))]])
    return (
        OnlineLogisticRegression()
        .set_initial_model_data(init)
        .set_global_batch_size(32)
        .set_checkpoint(CheckpointManager(ckpt_dir))
    )


def main():
    batches = [batch(seed) for seed in range(8)]
    with tempfile.TemporaryDirectory() as ckpt_dir:
        # first incarnation: trains 5 versions, then the process "dies"
        model = estimator(ckpt_dir).fit(feed(batches[:5]))
        model.advance()
        print("before kill: version", model.model_version)
        del model

        # resume: same params + checkpoint dir; the source replays from the
        # beginning and the driver skips the consumed prefix
        resumed = estimator(ckpt_dir).fit(feed(batches))
        print("restored at version", resumed.model_version)
        resumed.advance()
        print("after resume: version", resumed.model_version,
              "new versions:", resumed.version_history)

        out = resumed.transform(DataFrame.from_dict({"features": batch(99)["features"]}))
        print("serving with version column:", out["version"][:3])


if __name__ == "__main__":
    main()
