"""Wide-sparse SGD with the coefficient sharded over the mesh's model axis
(tensor parallelism; see docs/sparse.md). Falls back to pure data
parallelism when the mesh has no second axis.
"""
import numpy as np

from flink_ml_tpu.ops import SGD, BinaryLogisticLoss
from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context


def main():
    import jax

    devices = jax.devices()
    n_model = 2 if len(devices) >= 2 else 1
    ctx = MeshContext(devices=devices, n_data=len(devices) // n_model, n_model=n_model)
    with mesh_context(ctx):
        rng = np.random.default_rng(0)
        n, d, nnz = 1024, 1 << 16, 8
        idx = np.stack([rng.choice(d, nnz, replace=False) for _ in range(n)]).astype(np.int32)
        vals = rng.standard_normal((n, nnz)).astype(np.float32)
        w_true = np.zeros(d, np.float32)
        hot = rng.choice(d, 64, replace=False)
        w_true[hot] = rng.standard_normal(64)
        y = (np.sum(vals * w_true[idx], axis=1) > 0).astype(np.float32)

        coef = SGD(max_iter=80, global_batch_size=256, tol=0.0, learning_rate=1.0,
                   ctx=ctx).optimize(
            np.zeros(d, np.float32),
            {"indices": idx, "values": vals, "labels": y},
            BinaryLogisticLoss.INSTANCE,
        )
        acc = float(np.mean((np.sum(vals * coef[idx], axis=1) > 0) == (y > 0.5)))
        print(f"mesh {ctx}: {d}-dim sparse model, train accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
