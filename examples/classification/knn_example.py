"""Trains a Knn model and uses it for classification.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/classification/KnnExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.classification.knn import Knn


def main():
    rng = np.random.default_rng(0)
    X = np.concatenate([rng.normal(0, 0.3, (20, 2)), rng.normal(5, 0.3, (20, 2))])
    y = np.concatenate([np.zeros(20), np.ones(20)])
    train = DataFrame.from_dict({"features": X, "label": y})

    model = Knn().set_k(3).fit(train)
    queries = np.asarray([[0.1, -0.2], [4.9, 5.2]])
    output = model.transform(DataFrame.from_dict({"features": queries}))
    for features, pred in zip(queries, output["prediction"]):
        print(f"Features: {features}\tPrediction: {pred}")


if __name__ == "__main__":
    main()
