"""Controls the sparse SGD kernel strategy: one-hot vs scatter, and the
premat (precomputed-one-hot) resident fast path.

Parity: the reference trains SparseVector models one way (BLAS.java's
per-nonzero axpy/dot); here the optimizer picks between a scatter kernel
(narrow models), the one-hot matmul kernel (wide models), and — on the
resident route, when the materialized row one-hots fit the HBM budget —
the premat variant that streams precomputed one-hots into
product+matmul-only kernels (measured 1.6-1.8x the build-form step at the
Criteo shape, bit-identical coefficients; docs/benchmarks.md).
"""
import numpy as np

from flink_ml_tpu.iteration import DeviceDataCache
from flink_ml_tpu.ops import SGD, BinaryLogisticLoss


def main():
    rng = np.random.default_rng(0)
    n, d, K = 1024, 1 << 16, 8
    cols = {
        "indices": rng.integers(0, d, size=(n, K)).astype(np.int32),
        "values": rng.normal(size=(n, K)).astype(np.float32),
        "labels": (rng.random(n) > 0.5).astype(np.float32),
        "weights": np.ones(n, np.float32),
    }
    cache = DeviceDataCache(dict(cols))

    coefs = {}
    for premat in ("on", "off"):
        sgd = SGD(
            max_iter=5,
            global_batch_size=256,
            tol=0.0,
            learning_rate=0.3,
            sparse_kernel="onehot",  # 'auto' picks this for wide models
            onehot_premat=premat,  # 'auto' gates on the HBM storage budget
        )
        coefs[premat] = sgd.optimize(
            np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE
        )
        print(f"onehot_premat={premat}: active={sgd.onehot_premat_active} "
              f"final loss={sgd.loss_history[-1]:.6f}")

    # The premat path is the same SGD step executed faster: identical result.
    np.testing.assert_array_equal(coefs["on"], coefs["off"])
    print("premat and build-form coefficients are identical")


if __name__ == "__main__":
    main()
