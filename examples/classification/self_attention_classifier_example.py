"""SelfAttentionClassifier example — long-document classification with the
sequence axis sharded over the device mesh (ring attention).

The document's tokens are split across devices; KV blocks rotate around the
ring via ppermute while every shard computes, so no [T, T] score matrix ever
materializes. Both fit and transform run this schedule — sequence
parallelism as a library capability, not a primitive you wire yourself.
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.classification.attention_classifier import (
    SelfAttentionClassifier,
)


def main():
    rng = np.random.default_rng(0)
    n, T = 32, 128  # T shards over the mesh's data axis
    tok = rng.integers(0, 4, size=(n, T))
    label = (rng.random(n) > 0.5).astype(np.float64)
    signal = np.where(label[:, None] == 1.0, 7, 5)  # class-bearing tokens
    tok = np.where(rng.random((n, T)) < 0.3, signal, tok)
    train = DataFrame.from_dict({"features": tok.astype(np.float64), "label": label})

    model = (
        SelfAttentionClassifier()
        .set_embedding_dim(16)
        .set_num_heads(2)
        .set_max_iter(25)
        .set_learning_rate(0.01)
        .set_seed(7)
        .fit(train)
    )
    out = model.transform(train)
    acc = (out["prediction"] == label).mean()
    print(f"train accuracy over {n} documents of {T} tokens: {acc:.2f}")


if __name__ == "__main__":
    main()
