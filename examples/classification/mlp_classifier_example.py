"""Trains the MLPClassifier (the framework's deep-model flagship; no
reference analogue — flink-ml has no neural models) on a 3-class problem.
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.classification.mlp_classifier import MLPClassifier


def main():
    rng = np.random.default_rng(0)
    centers = np.asarray([[0.0, 0.0], [4.0, 4.0], [0.0, 4.0]])
    X = np.concatenate([rng.normal(c, 0.4, (30, 2)) for c in centers]).astype(np.float32)
    y = np.repeat([0.0, 1.0, 2.0], 30)
    train = DataFrame.from_dict({"features": X, "label": y})

    model = (
        MLPClassifier()
        .set_hidden_layers(16)
        .set_max_iter(200)
        .set_global_batch_size(32)
        .set_seed(7)
        .fit(train)
    )
    out = model.transform(train)
    acc = float(np.mean(out["prediction"] == y))
    print(f"train accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
