"""Trains a NaiveBayes model and uses it for classification.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/classification/NaiveBayesExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.classification.naive_bayes import NaiveBayes


def main():
    X = np.asarray([[0.0, 0.0], [0.0, 1.0], [1.0, 0.0], [1.0, 1.0], [1.0, 1.0], [1.0, 0.0]])
    y = np.asarray([0.0, 0.0, 1.0, 1.0, 1.0, 1.0])
    train = DataFrame.from_dict({"features": X, "label": y})

    model = NaiveBayes().set_smoothing(1.0).fit(train)
    output = model.transform(train)
    for features, label, pred in zip(X, y, output["prediction"]):
        print(f"Features: {features}\tExpected: {label}\tPrediction: {pred}")


if __name__ == "__main__":
    main()
