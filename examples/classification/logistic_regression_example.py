"""Trains a LogisticRegression model and uses it for classification.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/classification/LogisticRegressionExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression


def main():
    X = np.asarray([[1.0, 2.0], [2.0, 2.0], [3.0, 2.0], [11.0, 3.0], [12.0, 4.0], [13.0, 2.0]])
    y = np.asarray([0.0, 0.0, 0.0, 1.0, 1.0, 1.0])
    train = DataFrame.from_dict({"features": X, "label": y, "weight": np.ones(6)})

    lr = LogisticRegression().set_weight_col("weight")
    model = lr.fit(train)
    output = model.transform(train)
    for features, label, w, pred, raw in zip(X, y, np.ones(6), output["prediction"], output["rawPrediction"]):
        print(f"Features: {features}\tExpected: {label}\tPrediction: {pred}\tRaw: {raw}")


if __name__ == "__main__":
    main()
