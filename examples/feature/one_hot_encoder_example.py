"""One-hot encodes categorical index columns.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/OneHotEncoderExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.one_hot_encoder import OneHotEncoder


def main():
    train = DataFrame.from_dict({"input": np.asarray([0.0, 1.0, 2.0, 0.0])})
    model = OneHotEncoder().set_input_cols("input").set_output_cols("output").fit(train)
    out = model.transform(train)
    for x, v in zip(train["input"], out["output"]):
        print(f"category {x} -> {v}")


if __name__ == "__main__":
    main()
