"""Takes cross products of scalar and vector columns.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/InteractionExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.interaction import Interaction


def main():
    df = DataFrame.from_dict(
        {"f0": np.asarray([1.0, 2.0]), "f1": np.asarray([[1.0, 2.0], [3.0, 4.0]])}
    )
    out = Interaction().set_input_cols("f0", "f1").transform(df)
    for a, v, o in zip(df["f0"], df["f1"], out["output"]):
        print(f"{a} x {v} -> {o}")


if __name__ == "__main__":
    main()
