"""Selects features by univariate statistical tests against the label.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/UnivariateFeatureSelectorExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.univariate_feature_selector import (
    UnivariateFeatureSelector,
)


def main():
    rng = np.random.default_rng(0)
    n = 200
    y = rng.integers(0, 2, n).astype(np.float64)
    informative = y * 2.0 + rng.normal(0, 0.1, n)
    X = np.column_stack([informative, rng.normal(size=(n, 3))])
    df = DataFrame.from_dict({"features": X, "label": y})
    model = (
        UnivariateFeatureSelector()
        .set_feature_type("continuous")
        .set_label_type("categorical")
        .set_selection_threshold(1)
        .fit(df)
    )
    print("selected feature indices:", model.indices)


if __name__ == "__main__":
    main()
