"""Scales each dimension to [-1, 1] by its max absolute value.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/MaxAbsScalerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.scalers import MaxAbsScaler


def main():
    X = np.asarray([[0.0, 3.0], [2.1, 0.0], [4.1, 5.1], [6.1, 8.1], [200.0, 400.0]])
    df = DataFrame.from_dict({"input": X})
    model = MaxAbsScaler().fit(df)
    out = model.transform(df)
    for x, y in zip(X, out["output"]):
        print(f"{x} -> {np.round(y, 4)}")


if __name__ == "__main__":
    main()
