"""Builds n-grams from token sequences.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/NGramExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.ngram import NGram


def main():
    docs = [[], ["a", "b", "c"], ["a", "b", "c", "d"]]
    df = DataFrame(["input"], None, [docs])
    out = NGram().set_n(2).transform(df)
    for doc, grams in zip(docs, out["output"]):
        print(f"{doc} -> {grams}")


if __name__ == "__main__":
    main()
