"""Applies the discrete cosine transform to vectors.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/DCTExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.dct import DCT


def main():
    df = DataFrame.from_dict({"input": np.asarray([[1.0, 1.0, 1.0, 1.0], [1.0, 0.0, -1.0, 0.0]])})
    out = DCT().transform(df)
    for x, y in zip(df["input"], out["output"]):
        print(f"{x} -> {np.round(y, 4)}")


if __name__ == "__main__":
    main()
