"""Indexes categorical dimensions of vectors, leaving continuous ones.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/VectorIndexerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.vector_indexer import VectorIndexer


def main():
    X = np.asarray([[0.0, 1.5], [2.0, 2.5], [0.0, 3.5], [2.0, 4.5], [1.0, 5.5]])
    df = DataFrame.from_dict({"input": X})
    model = VectorIndexer().set_max_categories(3).fit(df)
    print("categorical dim maps:", model.category_maps)
    out = model.transform(df)
    for x, y in zip(X, out["output"]):
        print(f"{x} -> {y}")


if __name__ == "__main__":
    main()
