"""Tokenizes strings by a regex pattern.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/RegexTokenizerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.tokenizer import RegexTokenizer


def main():
    df = DataFrame(["input"], None, [["Test for tokenization.", "Te,st. punct"]])
    out = RegexTokenizer().set_input_col("input").set_pattern(r"\w+").set_gaps(False).transform(df)
    for s, toks in zip(df["input"], out["output"]):
        print(f"{s!r} -> {toks}")


if __name__ == "__main__":
    main()
