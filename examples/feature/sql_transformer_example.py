"""Transforms a table with a SELECT statement.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/SQLTransformerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.sql_transformer import SQLTransformer


def main():
    df = DataFrame.from_dict({"v1": np.asarray([1.0, 4.0]), "v2": np.asarray([2.0, 5.0])})
    out = (
        SQLTransformer()
        .set_statement("SELECT *, (v1 + v2) AS v3, (v1 * v2) AS v4 FROM __THIS__")
        .transform(df)
    )
    print("columns:", out.get_column_names())
    for row in out.collect():
        print(row)

    # aggregates: global (one row) and per group (GROUP BY)
    grouped = DataFrame.from_dict(
        {
            "cat": np.asarray(["a", "b", "a", "b"]),
            "v": np.asarray([1.0, 2.0, 3.0, 4.0]),
        }
    )
    agg = (
        SQLTransformer()
        .set_statement(
            "SELECT cat, COUNT(*) AS n, AVG(v) AS mean_v FROM __THIS__ GROUP BY cat"
        )
        .transform(grouped)
    )
    for row in agg.collect():
        print(row)


if __name__ == "__main__":
    main()
