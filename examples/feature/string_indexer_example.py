"""Indexes string columns by frequency or alphabet order.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/StringIndexerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.string_indexer import StringIndexer


def main():
    train = DataFrame(["input"], None, [["a", "b", "b", "c", "b", "a"]])
    model = (
        StringIndexer()
        .set_input_cols("input")
        .set_output_cols("output")
        .set_string_order_type("frequencyDesc")
        .fit(train)
    )
    print("ordered strings:", model.string_arrays[0])
    out = model.transform(train)
    for s, i in zip(train["input"], out["output"]):
        print(f"{s!r} -> {int(i)}")


if __name__ == "__main__":
    main()
