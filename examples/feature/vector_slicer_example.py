"""Slices vectors down to selected indices.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/VectorSlicerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.vector_slicer import VectorSlicer


def main():
    df = DataFrame.from_dict({"input": np.asarray([[1.0, 2.0, 3.0, 4.0], [5.0, 6.0, 7.0, 8.0]])})
    out = VectorSlicer().set_indices(0, 2).transform(df)
    for x, y in zip(df["input"], out["output"]):
        print(f"{x} -> {y}")


if __name__ == "__main__":
    main()
