"""Hashes mixed categorical/numeric columns into one feature vector.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/FeatureHasherExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.feature_hasher import FeatureHasher


def main():
    df = DataFrame(["id", "c0", "c1", "c2"], None, [[0, 1], ["a", "b"], [1.1, 0.0], [True, False]])
    out = (
        FeatureHasher()
        .set_input_cols("c0", "c1", "c2")
        .set_categorical_cols("c0", "c2")
        .set_num_features(1000)
        .transform(df)
    )
    for i, vec in zip(df["id"], out["output"]):
        print(f"row {i}: {vec}")


if __name__ == "__main__":
    main()
