"""Concatenates scalar and vector columns into one feature vector.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/VectorAssemblerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.vector_assembler import VectorAssembler


def main():
    df = DataFrame.from_dict(
        {"f0": np.asarray([1.0, 2.0]), "f1": np.asarray([[2.0, 3.0], [4.0, 5.0]])}
    )
    out = VectorAssembler().set_input_cols("f0", "f1").set_input_sizes(1, 2).transform(df)
    for a, v, o in zip(df["f0"], df["f1"], out["output"]):
        print(f"({a}, {v}) -> {o}")


if __name__ == "__main__":
    main()
