"""Binarizes columns against per-column thresholds.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/BinarizerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.binarizer import Binarizer


def main():
    df = DataFrame.from_dict(
        {"f0": np.asarray([1.0, 2.0, 3.0]), "f1": np.asarray([[1.0, 2.0], [2.0, 1.0], [0.0, 3.0]])}
    )
    out = (
        Binarizer()
        .set_input_cols("f0", "f1")
        .set_output_cols("of0", "of1")
        .set_thresholds(1.5, 1.5)
        .transform(df)
    )
    for a, b in zip(out["of0"], out["of1"]):
        print(f"scalar -> {a}\tvector -> {b}")


if __name__ == "__main__":
    main()
