"""Lower-cases and whitespace-splits strings into tokens.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/TokenizerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.tokenizer import Tokenizer


def main():
    df = DataFrame(["input"], None, [["Test for tokenization.", "Te,st. punct"]])
    out = Tokenizer().set_input_col("input").transform(df)
    for s, toks in zip(df["input"], out["output"]):
        print(f"{s!r} -> {toks}")


if __name__ == "__main__":
    main()
