"""Drops features whose variance is below a threshold.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/VarianceThresholdSelectorExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.variance_threshold_selector import (
    VarianceThresholdSelector,
)


def main():
    X = np.stack([np.ones(8), np.arange(8.0), np.arange(8.0) * 3], axis=1)
    df = DataFrame.from_dict({"input": X})
    model = VarianceThresholdSelector().set_variance_threshold(8.0).fit(df)
    print("kept feature indices:", model.indices)
    out = model.transform(df)
    print("first transformed row:", out["output"][0])


if __name__ == "__main__":
    main()
