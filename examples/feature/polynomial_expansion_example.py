"""Expands vectors into polynomial feature space.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/PolynomialExpansionExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.polynomial_expansion import PolynomialExpansion


def main():
    df = DataFrame.from_dict({"input": np.asarray([[1.0, 2.0], [2.0, 3.0]])})
    out = PolynomialExpansion().set_degree(2).transform(df)
    for x, y in zip(df["input"], out["output"]):
        print(f"{x} -> {y}")


if __name__ == "__main__":
    main()
