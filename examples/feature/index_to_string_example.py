"""Maps indices back to their original strings.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/IndexToStringModelExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.string_indexer import IndexToStringModel


def main():
    model = IndexToStringModel().set_input_cols("idx").set_output_cols("s")
    model.string_arrays = [["a", "b", "c"]]
    df = DataFrame.from_dict({"idx": np.asarray([0.0, 2.0, 1.0])})
    out = model.transform(df)
    for i, s in zip(df["idx"], out["s"]):
        print(f"{int(i)} -> {s}")


if __name__ == "__main__":
    main()
