"""Multiplies vectors elementwise by a scaling vector.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/ElementwiseProductExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import DenseVector
from flink_ml_tpu.models.feature.elementwise_product import ElementwiseProduct


def main():
    df = DataFrame.from_dict({"input": np.asarray([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])})
    out = ElementwiseProduct().set_scaling_vec(DenseVector([1.1, 1.1, 1.1])).transform(df)
    for x, y in zip(df["input"], out["output"]):
        print(f"{x} -> {y}")


if __name__ == "__main__":
    main()
