"""MinHash LSH: hashing, nearest neighbors and similarity join.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/MinHashLSHExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import Vectors
from flink_ml_tpu.models.feature.lsh import MinHashLSH


def main():
    a = Vectors.sparse(10, [0, 1, 2], [1.0, 1.0, 1.0])
    b = Vectors.sparse(10, [1, 2, 3], [1.0, 1.0, 1.0])
    c = Vectors.sparse(10, [7, 8, 9], [1.0, 1.0, 1.0])
    df = DataFrame(["vec", "id"], None, [[a, b, c], [0, 1, 2]])
    model = (
        MinHashLSH()
        .set_input_col("vec")
        .set_output_col("hashes")
        .set_num_hash_tables(5)
        .set_seed(2022)
        .fit(df)
    )
    print("hash table shape for row 0:", model.transform(df)["hashes"][0].shape)
    nn = model.approx_nearest_neighbors(df, a, k=2)
    print("neighbors of a:", list(nn["id"]))
    join = model.approx_similarity_join(df, df, threshold=0.6, id_col="id")
    print("similar pairs:", sorted({(int(x), int(y)) for x, y in zip(join["idA"], join["idB"])}))


if __name__ == "__main__":
    main()
