"""Scales features by the interquartile range (distributed GK sketch).

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/RobustScalerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.scalers import RobustScaler


def main():
    X = np.arange(1.0, 21.0)[:, None]
    df = DataFrame.from_dict({"input": X})
    model = RobustScaler().set_with_centering(True).fit(df)
    out = model.transform(df)
    for x, y in zip(X, out["output"]):
        print(f"{x[0]:5.1f} -> {y[0]:8.4f}")


if __name__ == "__main__":
    main()
