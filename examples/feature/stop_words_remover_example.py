"""Removes stop words from token sequences.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/StopWordsRemoverExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.stop_words_remover import StopWordsRemover


def main():
    docs = [["test", "test"], ["a", "b", "c", "d"], ["a", "the", "an"], ["A", "The", "AN"]]
    df = DataFrame(["input"], None, [docs])
    out = StopWordsRemover().set_input_cols("input").set_output_cols("output").transform(df)
    for doc, kept in zip(docs, out["output"]):
        print(f"{doc} -> {kept}")


if __name__ == "__main__":
    main()
