"""Standardizes a stream window-by-window with versioned models.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/OnlineStandardScalerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.standard_scaler import OnlineStandardScaler
from flink_ml_tpu.ops.windows import CountTumblingWindows


def main():
    df = DataFrame.from_dict({"input": np.arange(12.0)[:, None]})
    model = OnlineStandardScaler().set_windows(CountTumblingWindows.of(4)).fit(df)
    print("model versions produced:", model.version_history)
    out = model.transform(df)
    for x, y, v in zip(df["input"], out["output"], out["version"]):
        print(f"{x[0]:5.1f} -> {y[0]:8.4f} (model version {int(v)})")


if __name__ == "__main__":
    main()
