"""Normalizes vectors to unit p-norm.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/NormalizerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.normalizer import Normalizer


def main():
    df = DataFrame.from_dict(
        {"input": np.asarray([[2.1, 3.1, 1.2, 2.1], [1.1, 3.3, 4.4, 3.2]])}
    )
    out = Normalizer().set_p(1.5).transform(df)
    for x, y in zip(df["input"], out["output"]):
        print(f"{x} -> {np.round(y, 4)}")


if __name__ == "__main__":
    main()
