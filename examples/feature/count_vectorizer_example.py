"""Fits a vocabulary and vectorizes token documents.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/CountVectorizerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.count_vectorizer import CountVectorizer


def main():
    docs = [["a", "c", "b", "c"], ["c", "d", "e"], ["a", "b", "c"], ["e", "f"], ["a", "c", "a"]]
    df = DataFrame(["input"], None, [docs])
    model = CountVectorizer().fit(df)
    print("vocabulary:", model.vocabulary)
    out = model.transform(df)
    for doc, vec in zip(docs, out["output"]):
        print(f"{doc} -> {vec}")


if __name__ == "__main__":
    main()
