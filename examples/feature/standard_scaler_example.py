"""Standardizes features to zero mean / unit variance.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/StandardScalerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.standard_scaler import StandardScaler


def main():
    X = np.asarray([[-2.5, 9.0, 1.0], [1.4, -1.0, 1.0], [2.0, -3.0, 1.0]])
    df = DataFrame.from_dict({"input": X})
    model = StandardScaler().set_with_mean(True).fit(df)
    out = model.transform(df)
    for x, y in zip(X, out["output"]):
        print(f"{x} -> {np.round(y, 4)}")


if __name__ == "__main__":
    main()
