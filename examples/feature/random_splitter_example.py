"""Splits a table into weighted random partitions.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/RandomSplitterExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.random_splitter import RandomSplitter


def main():
    df = DataFrame.from_dict({"x": np.arange(100.0)})
    train, test = RandomSplitter().set_weights(8.0, 2.0).set_seed(0).transform(df)
    print(f"train rows: {len(train)}, test rows: {len(test)}")


if __name__ == "__main__":
    main()
