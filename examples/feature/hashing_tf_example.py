"""Maps token sequences to term-frequency vectors by hashing.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/HashingTFExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.hashing_tf import HashingTF


def main():
    docs = [
        ["HashingTFTest", "Hashing", "Term", "Frequency", "Test"],
        ["HashingTFTest", "Hashing", "Hashing", "Test", "Test"],
    ]
    df = DataFrame(["input"], None, [docs])
    out = HashingTF().set_num_features(128).transform(df)
    for doc, vec in zip(docs, out["output"]):
        print(f"{doc} -> {vec}")


if __name__ == "__main__":
    main()
