"""Fits inverse document frequency weights and rescales vectors.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/IDFExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.idf import IDF


def main():
    X = np.asarray([[0.0, 1.0, 0.0, 2.0], [0.0, 1.0, 2.0, 3.0], [0.0, 1.0, 4.0, 4.0]])
    df = DataFrame.from_dict({"input": X})
    model = IDF().fit(df)
    print("idf:", np.round(model.idf, 4))
    out = model.transform(df)
    for x, y in zip(X, out["output"]):
        print(f"{x} -> {np.round(y, 4)}")


if __name__ == "__main__":
    main()
