"""Discretizes continuous features into k bins.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/KBinsDiscretizerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.kbins_discretizer import KBinsDiscretizer


def main():
    X = np.asarray([[1.0], [2.0], [3.0], [4.0], [100.0], [101.0]])
    df = DataFrame.from_dict({"input": X})
    model = KBinsDiscretizer().set_num_bins(3).set_strategy("quantile").fit(df)
    out = model.transform(df)
    for x, b in zip(X, out["output"]):
        print(f"{x[0]} -> bin {int(b[0])}")


if __name__ == "__main__":
    main()
