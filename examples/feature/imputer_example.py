"""Fills missing values with a fitted surrogate per column.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/ImputerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.imputer import Imputer


def main():
    df = DataFrame.from_dict(
        {"f1": np.asarray([np.nan, 1.0, 3.0, 4.0]), "f2": np.asarray([9.0, 8.0, np.nan, 7.0])}
    )
    model = (
        Imputer().set_input_cols("f1", "f2").set_output_cols("o1", "o2").set_strategy("mean").fit(df)
    )
    out = model.transform(df)
    for a, b in zip(out["o1"], out["o2"]):
        print(f"imputed row: {a}, {b}")


if __name__ == "__main__":
    main()
