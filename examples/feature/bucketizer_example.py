"""Maps continuous columns into buckets by split points.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/feature/BucketizerExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.feature.bucketizer import Bucketizer


def main():
    df = DataFrame.from_dict({"f0": np.asarray([-0.5, 0.3, 1.5, 2.5])})
    out = (
        Bucketizer()
        .set_input_cols("f0")
        .set_output_cols("b0")
        .set_splits_array([[-1.0, 0.0, 1.0, 2.0, 3.0]])
        .transform(df)
    )
    for x, b in zip(df["f0"], out["b0"]):
        print(f"value {x} -> bucket {int(b)}")


if __name__ == "__main__":
    main()
