"""Trains an OnlineKMeans model on a stream of batches.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/clustering/OnlineKMeansExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.clustering.online_kmeans import OnlineKMeans
from flink_ml_tpu.models.online import QueueBatchStream


def main():
    rng = np.random.default_rng(0)
    stream = QueueBatchStream()
    model = (
        OnlineKMeans()
        .set_k(2)
        .set_seed(1)
        .set_decay_factor(0.5)
        .set_random_initial_model_data(dim=2)
        .fit(stream)
    )
    for step in range(3):
        pts = np.concatenate(
            [rng.normal([0, 0], 0.1, (16, 2)), rng.normal([5, 5], 0.1, (16, 2))]
        )
        stream.add({"features": pts})
        model.advance()
        print(f"after batch {step}: centroids =\n{model.centroids}")

    queries = np.asarray([[0.1, 0.0], [5.2, 4.9]])
    out = model.transform(DataFrame.from_dict({"features": queries}))
    for features, cluster in zip(queries, out["prediction"]):
        print(f"Features: {features}\tCluster ID: {int(cluster)}")


if __name__ == "__main__":
    main()
