"""Runs AgglomerativeClustering and prints the merge hierarchy result.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/clustering/AgglomerativeClusteringExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.clustering.agglomerative_clustering import (
    AgglomerativeClustering,
)


def main():
    X = np.asarray([[1.0, 1.0], [1.0, 4.0], [1.0, 0.0], [4.0, 1.5], [4.0, 4.0], [4.0, 0.0]])
    df = DataFrame.from_dict({"features": X})
    outputs = AgglomerativeClustering().set_num_clusters(2).transform(df)
    clusters = outputs[0]
    for features, cluster in zip(X, clusters["prediction"]):
        print(f"Features: {features}\tCluster ID: {int(cluster)}")


if __name__ == "__main__":
    main()
