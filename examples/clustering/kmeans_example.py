"""Trains a KMeans model and uses it for clustering.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/clustering/KMeansExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.clustering.kmeans import KMeans


def main():
    X = np.asarray(
        [[0.0, 0.0], [0.0, 0.3], [0.3, 0.0], [9.0, 0.0], [9.0, 0.6], [9.6, 0.0]]
    )
    df = DataFrame.from_dict({"features": X})

    model = KMeans().set_k(2).set_seed(1).fit(df)
    output = model.transform(df)
    for features, cluster in zip(X, output["prediction"]):
        print(f"Features: {features}\tCluster ID: {int(cluster)}")


if __name__ == "__main__":
    main()
