"""Runs the chi-square independence test between features and label.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/stats/ChiSqTestExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.stats.tests import ChiSqTest


def main():
    rng = np.random.default_rng(0)
    n = 200
    label = rng.integers(0, 2, n).astype(np.float64)
    dependent = label * 2.0 + rng.integers(0, 2, n)  # depends on label
    independent = rng.integers(0, 3, n).astype(np.float64)
    df = DataFrame.from_dict(
        {"features": np.column_stack([dependent, independent]), "label": label}
    )
    out = ChiSqTest().transform(df)
    print("pValues:", np.asarray(out["pValues"][0]))
    print("degreesOfFreedom:", np.asarray(out["degreesOfFreedom"][0]))
    print("statistics:", np.asarray(out["statistics"][0]))


if __name__ == "__main__":
    main()
