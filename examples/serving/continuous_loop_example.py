"""Continuous learning loop: closed-loop train → publish → serve with drift
detection and automatic rollback (docs/continuous.md).

An online FTRL logistic regression trains on a feedable stream; every second
model version is published as a servable and hot-swapped into an
InferenceServer with pre-flip AOT warmup; labelled tail traffic is scored
through the real serving path into a rolling drift window. Mid-run the
training labels flip — the drifted version's logloss regresses past the
baseline, and the loop quarantines it and rolls serving back to the last
good version automatically.
"""
import os
import tempfile

import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import DenseVector
from flink_ml_tpu.loop import ContinuousLearningLoop, ContinuousTrainer, DriftMonitor
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.models.classification.online_logistic_regression import (
    OnlineLogisticRegression,
)
from flink_ml_tpu.models.online import QueueBatchStream
from flink_ml_tpu.serving import InferenceServer, ServingConfig

D = 8
TRUE_W = np.linspace(1.0, -1.0, D)


def make_batch(n=64, seed=0, drifted=False):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, D))
    y = (X @ TRUE_W > 0).astype(np.float64)
    if drifted:
        y = 1.0 - y  # the world changed: yesterday's model is wrong
    return {"features": X.astype(np.float64), "label": y}


with tempfile.TemporaryDirectory() as tmp:
    publish_dir = os.path.join(tmp, "models")
    stream = QueueBatchStream()
    estimator = (
        OnlineLogisticRegression()
        .set_initial_model_data(
            DataFrame(["coefficient"], None, [[DenseVector(np.zeros(D))]])
        )
        .set_alpha(1.0)
        .set_global_batch_size(64)
    )
    scope = f"{MLMetrics.LOOP_GROUP}[example]"
    trainer = ContinuousTrainer(
        estimator, stream, publish_dir, publish_every_versions=2, scope=scope
    )
    server = InferenceServer(
        name="example-loop",
        serving_config=ServingConfig(max_batch_size=8, max_delay_ms=0.5),
        warmup_template=DataFrame.from_dict(
            {"features": make_batch(1, seed=99)["features"]}
        ),
    )
    loop = ContinuousLearningLoop(
        trainer,
        server,
        eval_source=lambda: DataFrame.from_dict(make_batch(32, seed=7)),
        name="example",
        monitor=DriftMonitor(window=2, rel_threshold=0.2, min_scores=1, scope=scope),
    )

    # healthy traffic: three versions published, warmed, and flipped in
    for i in range(6):
        stream.add(make_batch(seed=i))
    for report in loop.run(publish_target=3, max_steps=10):
        if report.swapped:
            print(
                f"step {report.step}: serving v{report.serving_version} "
                f"(logloss {report.score:.3f})"
            )

    # drift: the stream's labels flip — the next published version regresses
    for i in range(4):
        stream.add(make_batch(seed=50 + i, drifted=True))
    for report in loop.run(publish_target=4, max_steps=10):
        if report.rolled_back_to is not None:
            print(
                f"step {report.step}: v{report.swapped} regressed "
                f"(logloss {report.score:.3f}) -> rolled back to "
                f"v{report.rolled_back_to}"
            )

    scraped = metrics.scope(scope)
    print(
        "published:", scraped[MLMetrics.LOOP_PUBLISHED],
        "swapped:", scraped[MLMetrics.LOOP_SWAPPED],
        "rollbacks:", scraped[MLMetrics.LOOP_ROLLBACKS],
        "quarantined:", scraped[MLMetrics.LOOP_QUARANTINED],
    )
    print(
        "publish->serve p50:",
        round(scraped[MLMetrics.LOOP_PUBLISH_TO_SERVE_MS].quantile(0.5), 2),
        "ms; goodput fraction:",
        round(scraped[MLMetrics.LOOP_GOODPUT_FRACTION], 3),
    )
    print("model dir:", sorted(os.listdir(publish_dir)))
    print(
        "post-warmup serving-path compiles:",
        metrics.get(server.scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0),
    )
    server.close()
