"""Online serving: micro-batched concurrent inference with a hot model swap.

Trains two LogisticRegression versions, publishes them to a model directory,
and serves concurrent single-row traffic through an InferenceServer while the
ModelVersionPoller swaps v2 in mid-run — the train → publish → serve loop of
docs/serving.md in one script.
"""
import tempfile
import threading

import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression
from flink_ml_tpu.serving import InferenceServer, ServingConfig, publish_servable

rng = np.random.default_rng(42)
X = rng.normal(size=(512, 8))
y = (X @ np.linspace(1.0, -1.0, 8) > 0).astype(np.float64)
train = DataFrame.from_dict({"features": X, "label": y})

v1 = LogisticRegression().set_max_iter(5).set_global_batch_size(512).fit(train)
v2 = LogisticRegression().set_max_iter(40).set_global_batch_size(512).fit(train)

with tempfile.TemporaryDirectory() as model_dir:
    publish_servable(v1, model_dir)  # -> v-1
    server = InferenceServer(
        name="example",
        serving_config=ServingConfig(max_batch_size=16, max_delay_ms=2),
        warmup_template=DataFrame.from_dict({"features": X[:1]}),
    )
    poller = server.attach_poller(model_dir, start=False)
    poller.poll_once()

    versions_seen = []
    lock = threading.Lock()

    def client(tid):
        for i in range(25):
            j = (tid * 41 + i) % 512
            resp = server.predict(DataFrame.from_dict({"features": X[j : j + 1]}))
            with lock:
                versions_seen.append(resp.model_version)

    threads = [threading.Thread(target=client, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()

    publish_servable(v2, model_dir)  # -> v-2, mid-traffic
    poller.poll_once()  # hot swap: warm every bucket, then atomic flip

    for t in threads:
        t.join()
    server.close()

print(f"served {len(versions_seen)} requests across versions {sorted(set(versions_seen))}")
print(f"final serving version: {server.model_version}")
assert server.model_version == 2
