"""Serving fast path: a scaler→logistic pipeline fused into one executable.

Builds a two-stage pipeline of runtime-free servables, serves it through an
InferenceServer with the fast path on (the default), and scrapes the
``ml.serving.fastpath.*`` metrics: both stages fuse into ONE AOT-compiled
program per batch bucket, model arrays live on device from warmup, and the
dispatch window pipelines host work against device execution — the fast-path
section of docs/serving.md in one script.
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.servable import (
    LogisticRegressionModelServable,
    PipelineModelServable,
    StandardScalerModelServable,
)
from flink_ml_tpu.serving import InferenceServer, ServingConfig

rng = np.random.default_rng(7)
DIM = 16
X = rng.normal(size=(256, DIM))

scaler = (
    StandardScalerModelServable()
    .set_input_col("features")
    .set_output_col("scaled")
    .set_with_mean(True)
)
scaler.mean = X.mean(axis=0)
scaler.std = X.std(axis=0)

lr = LogisticRegressionModelServable().set_features_col("scaled")
lr.coefficient = rng.normal(size=DIM)

pipeline = PipelineModelServable([scaler, lr])

server = InferenceServer(
    pipeline,
    name="fused-example",
    serving_config=ServingConfig(max_batch_size=16, max_delay_ms=1, pipeline_depth=2),
    warmup_template=DataFrame.from_dict({"features": X[:1]}),
)
with server:
    for i in range(32):
        resp = server.predict(DataFrame.from_dict({"features": X[i : i + 1]}))
    # fused output is bit-exact vs the per-stage transform at the same bucket
    direct = pipeline.transform(DataFrame.from_dict({"features": X[31:32]}))

scope = server.scope
print(f"prediction={resp.dataframe['prediction'][0]} (per-stage: {direct['prediction'][0]})")
print(f"fused stages:        {metrics.get(scope, MLMetrics.SERVING_FUSED_STAGES)}")
print(f"fused batches:       {metrics.get(scope, MLMetrics.SERVING_FUSED_BATCHES)}")
print(f"post-warmup compiles: {metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES) or 0}")
print(f"warmup compile ms:   {metrics.get(scope, MLMetrics.SERVING_WARMUP_COMPILE_MS):.1f}")
assert resp.dataframe["prediction"][0] == direct["prediction"][0]
assert metrics.get(scope, MLMetrics.SERVING_FUSED_STAGES) == 2
assert not metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES)
