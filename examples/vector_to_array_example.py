"""Converts vector cells back to plain arrays.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/VectorToArrayExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import Vectors


def main():
    df = DataFrame(["vector"], None, [[Vectors.dense([0.0, 0.0]), Vectors.dense([0.5, 0.3])]])
    arrays = df.vectors("vector")  # [n, d] numpy array
    print("vectors as arrays:")
    print(np.asarray(arrays))


if __name__ == "__main__":
    main()
