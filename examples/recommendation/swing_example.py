"""Computes Swing item-item similarity from user behavior.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/recommendation/SwingExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.recommendation.swing import Swing


def main():
    users = np.asarray([0, 0, 0, 1, 1, 1, 2, 2, 2, 3, 3, 3], np.int64)
    items = np.asarray([10, 11, 12, 10, 11, 12, 10, 11, 13, 10, 12, 13], np.int64)
    df = DataFrame.from_dict({"user": users, "item": items})
    out = Swing().set_min_user_behavior(1).set_k(3).transform(df)
    for item, sims in zip(out["item"], out["output"]):
        print(f"item {item} -> {sims}")


if __name__ == "__main__":
    main()
