"""Sequence-parallel ring attention over the device mesh (no reference
analogue — the TPU-native long-context primitive; see docs/distributed.md).

On TPU, when the local block tiles and fits VMEM, both the per-step fold
and its backward run as fused Pallas kernels automatically
(docs/kernels.md) — nothing to opt into here.
"""
import numpy as np

from flink_ml_tpu.parallel import ring_attention_sharded
from flink_ml_tpu.parallel.mesh import get_mesh_context


def main():
    ctx = get_mesh_context()
    rng = np.random.default_rng(0)
    B, T, H, D = 1, 64 * ctx.n_data, 2, 16
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    out = np.asarray(ring_attention_sharded(q, q, q, causal=True, ctx=ctx))
    print(f"causal self-attention over {T} tokens on {ctx.n_data} shards")
    print("output shape:", out.shape, "finite:", bool(np.isfinite(out).all()))


if __name__ == "__main__":
    main()
