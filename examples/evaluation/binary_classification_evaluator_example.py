"""Evaluates binary classification results with AUC/AUPR/KS.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/evaluation/BinaryClassificationEvaluatorExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.evaluation.binary_classification_evaluator import (
    BinaryClassificationEvaluator,
)


def main():
    y = np.asarray([1.0, 1.0, 1.0, 0.0, 0.0, 0.0])
    raw = np.asarray([[0.1, 0.9], [0.2, 0.8], [0.7, 0.3], [0.8, 0.2], [0.4, 0.6], [0.9, 0.1]])
    df = DataFrame.from_dict({"label": y, "rawPrediction": raw})
    out = (
        BinaryClassificationEvaluator()
        .set_metrics_names("areaUnderROC", "areaUnderPR", "ks")
        .transform(df)
    )
    for name in ("areaUnderROC", "areaUnderPR", "ks"):
        print(f"{name}: {out[name][0]:.4f}")


if __name__ == "__main__":
    main()
