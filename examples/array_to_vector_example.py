"""Converts array columns to vector objects at the row boundary.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/ArrayToVectorExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame


def main():
    # Columnar storage IS the vector layout: a [n, d] array column serves as
    # the vector column directly; collect() materializes DenseVector cells.
    df = DataFrame.from_dict({"array": np.asarray([[0.0, 0.0], [0.5, 0.3]])})
    for row in df.collect():
        print("array column as vector:", row[0])


if __name__ == "__main__":
    main()
