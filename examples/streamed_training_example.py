"""Trains out of a larger-than-HBM host cache with disk spill.

Parity: the reference caches each subtask's partition in managed memory
segments spilling to disk (ListStateWithCache.java); here the capacity tier
(HostDataCache) streams HBM-sized windows through the fused SGD program
with one-ahead prefetch.
"""
import tempfile

import numpy as np

from flink_ml_tpu.iteration import HostDataCache
from flink_ml_tpu.ops import SGD, BinaryLogisticLoss


def main():
    rng = np.random.default_rng(0)
    n, d = 4096, 16
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d) > 0).astype(np.float32)

    cache = HostDataCache(memory_budget_bytes=64 * 1024, spill_dir=tempfile.mkdtemp())
    for a in range(0, n, 512):
        cache.append({"features": X[a : a + 512], "labels": y[a : a + 512]})
    cache.finish()
    spilled = sum(1 for e in cache._log if "files" in e)
    print(f"cached {cache.num_rows} rows in {len(cache._log)} chunks ({spilled} spilled to disk)")

    sgd = SGD(max_iter=40, global_batch_size=1024, tol=0.0, learning_rate=0.5,
              stream_window_rows=512)
    coef = sgd.optimize(np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE)
    acc = float(np.mean((X @ coef > 0) == (y > 0.5)))
    print(f"train accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
