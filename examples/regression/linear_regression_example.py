"""Trains a LinearRegression model and uses it for regression.

Parity: flink-ml-examples/src/main/java/org/apache/flink/ml/examples/regression/LinearRegressionExample.java
(re-designed for the TPU-native API: columnar DataFrame in, stage out,
print rows — no execution environment or Table plumbing needed).
"""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.regression.linear_regression import LinearRegression


def main():
    X = np.asarray([[1.0, 1.0], [2.0, 1.0], [3.0, 1.0], [4.0, 1.0]])
    y = X @ np.asarray([2.0, 1.0])
    train = DataFrame.from_dict({"features": X, "label": y})

    model = LinearRegression().set_max_iter(200).set_learning_rate(0.05).fit(train)
    output = model.transform(train)
    for features, label, pred in zip(X, y, output["prediction"]):
        print(f"Features: {features}\tExpected: {label}\tPrediction: {pred:.3f}")


if __name__ == "__main__":
    main()
