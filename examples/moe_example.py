"""Expert-parallel mixture-of-experts dispatch over the device mesh (no
reference analogue — completes the dp/tp/sp/ep parallelism vocabulary; see
docs/distributed.md).
"""
import numpy as np

from flink_ml_tpu.parallel import moe_ffn_sharded
from flink_ml_tpu.parallel.mesh import get_mesh_context


def main():
    ctx = get_mesh_context()
    rng = np.random.default_rng(0)
    T, d, h = 64 * ctx.n_data, 16, 32
    E = 2 * ctx.n_data  # two experts per shard
    x = rng.standard_normal((T, d)).astype(np.float32)
    router = rng.standard_normal((d, E)).astype(np.float32)
    w1 = (rng.standard_normal((E, d, h)) * 0.2).astype(np.float32)
    w2 = (rng.standard_normal((E, h, d)) * 0.2).astype(np.float32)

    out = np.asarray(moe_ffn_sharded(x, router, w1, w2, capacity=T, ctx=ctx))
    routed = (x @ router).argmax(axis=1)
    print(f"{T} tokens routed across {E} experts on {ctx.n_data} shards")
    print("tokens per expert:", np.bincount(routed, minlength=E).tolist())
    print("output shape:", out.shape, "finite:", bool(np.isfinite(out).all()))


if __name__ == "__main__":
    main()
