"""Build hook: prebuild the native chunk store into the wheel.

The C++ datacache (`flink_ml_tpu/native/datacache.cpp`) is an ordinary shared
library loaded through ctypes — not a Python extension module — so instead of
`Extension` machinery this compiles it with the system toolchain during
`build_py` and ships the `.so` as package data. Hosts without a toolchain
still work: `flink_ml_tpu.native` falls back to lazy compilation on first use
and, failing that, to the pure-Python cache tier.
"""
import subprocess
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        super().run()
        src = Path(__file__).parent / "flink_ml_tpu" / "native" / "datacache.cpp"
        out = Path(self.build_lib) / "flink_ml_tpu" / "native" / "_datacache.so"
        if not out.parent.exists():
            return
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", str(src), "-o", str(out)]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
            print(f"built native datacache -> {out}")
        except Exception as e:  # toolchain-less host: lazy build remains
            print(f"skipping native datacache prebuild ({e})")


setup(cmdclass={"build_py": BuildWithNative})
