"""Open-loop load generation — offered load, not closed-loop politeness.

Every serving number before this package was closed-loop: each client thread
waits for its last response before sending the next request, so the offered
rate silently adapts to the server's capacity and queueing collapse — the
failure mode that actually kills high-traffic serving — is structurally
invisible. This package drives the serving tier the way real traffic does:
arrivals fire on a **schedule** (seeded Poisson or bursty processes with a
heavy-tailed request-size mix, rampable step by step) regardless of what the
server is doing, and the harness records what overload actually looks like —
p50/p99/p999, sheds, hard rejects, deadline misses, and time-to-first-shed
per load step.

Schedules are **seeded and replayable**: the same seed produces a
byte-identical schedule, schedules serialize to JSON, and a recorded
schedule replays against any target (including a virtual-clock one —
determinism is testable without a wall clock). The generator's own arrival
loop is a registered fault point (``loadgen.tick``), so chaos runs can prove
the measurement rig itself survives injected faults. See docs/serving.md
"Load shedding & adaptive control".
"""
from flink_ml_tpu.loadgen.arrivals import (
    Arrival,
    BurstyArrivals,
    FixedSizes,
    PoissonArrivals,
    Schedule,
    ZipfSizes,
    ramp_schedule,
)
from flink_ml_tpu.loadgen.generator import (
    LoadReport,
    OpenLoopLoadGenerator,
    StepStats,
)
from flink_ml_tpu.loadgen.retry import RetryPolicy

__all__ = [
    "RetryPolicy",
    "Arrival",
    "Schedule",
    "PoissonArrivals",
    "BurstyArrivals",
    "ZipfSizes",
    "FixedSizes",
    "ramp_schedule",
    "OpenLoopLoadGenerator",
    "LoadReport",
    "StepStats",
]
