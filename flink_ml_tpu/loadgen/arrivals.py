"""Seeded arrival processes, request-size mixes, and replayable schedules.

A :class:`Schedule` is the fully materialized traffic plan: one
:class:`Arrival` per request, each carrying its offset from run start, row
count, priority, and load-step index. Everything that involves randomness
happens HERE, at build time, from one ``random.Random(seed)`` — the open-loop
generator (generator.py) just walks the list. That split is what makes runs
replayable: the same seed yields a byte-identical schedule
(``Schedule.to_json`` is canonical), and a recorded schedule replays against
any target without re-rolling a single die.

Processes:

- :class:`PoissonArrivals` — memoryless inter-arrival gaps
  (``Exp(rate)``), the classic open-loop offered-load model;
- :class:`BurstyArrivals` — a two-state modulated Poisson process (a
  burst state at ``burst_factor x`` the base rate alternating with idle
  gaps), the self-similar traffic shape that defeats average-rate capacity
  planning.

Sizes:

- :class:`ZipfSizes` — heavy-tailed request-size mix over a bucket-aligned
  vocabulary (mass ∝ rank^-alpha: single rows dominate, the occasional
  near-max-batch request drags the tail);
- :class:`FixedSizes` — every request the same size (calibration runs).
"""
from __future__ import annotations

import json
import random
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Arrival",
    "PoissonArrivals",
    "BurstyArrivals",
    "ZipfSizes",
    "FixedSizes",
    "Schedule",
    "ramp_schedule",
]


class Arrival:
    """One scheduled request: when (seconds from run start), how many rows,
    at what priority, and which load step it belongs to."""

    __slots__ = ("t", "rows", "priority", "step")

    def __init__(self, t: float, rows: int, priority: int = 0, step: int = 0):
        self.t = float(t)
        self.rows = int(rows)
        self.priority = int(priority)
        self.step = int(step)

    def as_list(self) -> List:
        return [self.t, self.rows, self.priority, self.step]

    def __repr__(self) -> str:
        return f"Arrival(t={self.t:.6f}, rows={self.rows}, priority={self.priority}, step={self.step})"


class PoissonArrivals:
    """Open-loop Poisson process at ``rate`` arrivals/s: inter-arrival gaps
    are iid ``Exp(rate)`` draws from the shared rng."""

    def __init__(self, rate: float):
        if rate <= 0.0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)

    def gaps(self, rng: random.Random, duration_s: float):
        """Yield inter-arrival gaps until their sum exceeds ``duration_s``."""
        t = 0.0
        while True:
            gap = rng.expovariate(self.rate)
            t += gap
            if t > duration_s:
                return
            yield gap


class BurstyArrivals:
    """Two-state modulated Poisson process: bursts at
    ``rate x burst_factor`` of mean length ``mean_burst_s`` alternate with
    idle stretches of mean length ``mean_idle_s`` (both exponentially
    distributed). With the default geometry the long-run average rate stays
    close to ``rate`` while short windows see ``burst_factor x`` — the shape
    that collapses a queue sized for the average."""

    def __init__(
        self,
        rate: float,
        burst_factor: float = 8.0,
        mean_burst_s: float = 0.05,
        mean_idle_s: Optional[float] = None,
    ):
        if rate <= 0.0 or burst_factor <= 1.0:
            raise ValueError("rate must be > 0 and burst_factor > 1")
        self.rate = float(rate)
        self.burst_factor = float(burst_factor)
        self.mean_burst_s = float(mean_burst_s)
        # Idle length that keeps the long-run average at ``rate``: all
        # arrivals land in bursts, so E[arrivals per cycle] =
        # burst_rate*mean_burst must equal rate*(mean_burst+mean_idle).
        self.mean_idle_s = (
            float(mean_idle_s) if mean_idle_s is not None
            else mean_burst_s * (burst_factor - 1.0)
        )

    def gaps(self, rng: random.Random, duration_s: float):
        burst_rate = self.rate * self.burst_factor
        t = 0.0
        prev = 0.0
        while t < duration_s:
            burst_end = t + rng.expovariate(1.0 / self.mean_burst_s)
            while True:
                gap = rng.expovariate(burst_rate)
                if t + gap > burst_end:
                    break
                t += gap
                if t > duration_s:
                    return
                yield t - prev
                prev = t
            t = burst_end + rng.expovariate(1.0 / self.mean_idle_s)


class ZipfSizes:
    """Heavy-tailed request sizes: mass ∝ rank^-alpha over an ascending,
    bucket-aligned vocabulary (default powers of two). alpha=1.5 puts ~70%
    of requests at the smallest size with a real tail at the largest."""

    def __init__(self, sizes: Sequence[int] = (1, 2, 4, 8, 16, 32), alpha: float = 1.5):
        if not sizes or any(s < 1 for s in sizes):
            raise ValueError(f"sizes must be >= 1, got {sizes}")
        self.sizes = tuple(int(s) for s in sizes)
        self.alpha = float(alpha)
        weights = [(rank + 1) ** -self.alpha for rank in range(len(self.sizes))]
        total = sum(weights)
        self._cum: List[float] = []
        acc = 0.0
        for w in weights:
            acc += w / total
            self._cum.append(acc)
        self._cum[-1] = 1.0  # guard fp drift

    @property
    def mean_rows(self) -> float:
        probs = [self._cum[0]] + [
            self._cum[i] - self._cum[i - 1] for i in range(1, len(self._cum))
        ]
        return sum(s * p for s, p in zip(self.sizes, probs))

    def draw(self, rng: random.Random) -> int:
        u = rng.random()
        for size, cum in zip(self.sizes, self._cum):
            if u <= cum:
                return size
        return self.sizes[-1]


class FixedSizes:
    """Every request ``rows`` rows (calibration / microbenchmark mixes)."""

    def __init__(self, rows: int = 1):
        self.rows = int(rows)

    @property
    def mean_rows(self) -> float:
        return float(self.rows)

    def draw(self, rng: random.Random) -> int:
        return self.rows


class Schedule:
    """A materialized, replayable traffic plan.

    ``meta`` records how it was built (seed, steps, process) purely for
    humans; replay uses only ``entries``. Serialization is canonical
    (sorted keys, explicit separators), so determinism is byte-testable:
    building twice from the same seed yields identical ``to_json`` bytes.
    """

    VERSION = 1

    def __init__(self, entries: Sequence[Arrival], meta: Optional[Dict] = None):
        self.entries: List[Arrival] = list(entries)
        self.meta: Dict = dict(meta or {})

    def __len__(self) -> int:
        return len(self.entries)

    def __iter__(self):
        return iter(self.entries)

    @property
    def n_steps(self) -> int:
        return max((e.step for e in self.entries), default=-1) + 1

    @property
    def duration_s(self) -> float:
        return self.entries[-1].t if self.entries else 0.0

    def step_entries(self, step: int) -> List[Arrival]:
        return [e for e in self.entries if e.step == step]

    def offered_rows(self, step: Optional[int] = None) -> int:
        return sum(e.rows for e in self.entries if step is None or e.step == step)

    # -- serialization (canonical → byte-testable determinism) ---------------
    def to_json(self) -> str:
        payload = {
            "version": self.VERSION,
            "meta": self.meta,
            "entries": [e.as_list() for e in self.entries],
        }
        return json.dumps(payload, sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, text: str) -> "Schedule":
        payload = json.loads(text)
        version = payload.get("version")
        if version != cls.VERSION:
            raise ValueError(f"unsupported schedule version {version!r}")
        entries = [Arrival(t, rows, priority, step) for t, rows, priority, step in payload["entries"]]
        return cls(entries, payload.get("meta"))

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "Schedule":
        with open(path, encoding="utf-8") as f:
            return cls.from_json(f.read())

    def __repr__(self) -> str:
        return (
            f"Schedule(arrivals={len(self.entries)}, steps={self.n_steps}, "
            f"duration_s={self.duration_s:.3f})"
        )


def _draw_priority(rng: random.Random, mix: Sequence[Tuple[int, float]]) -> int:
    u = rng.random()
    acc = 0.0
    for priority, weight in mix:
        acc += weight
        if u <= acc:
            return priority
    return mix[-1][0]


def ramp_schedule(
    steps: Sequence[Tuple[float, float]],
    *,
    sizes=None,
    priority_mix: Optional[Dict[int, float]] = None,
    seed: int = 0,
    process: str = "poisson",
    burst_factor: float = 8.0,
    mean_burst_s: float = 0.05,
) -> Schedule:
    """Build an offered-load ramp: one (``rate_rps``, ``duration_s``) pair
    per step, arrivals drawn by the chosen process, sizes by the mix
    (default :class:`ZipfSizes`), priorities by ``priority_mix`` (priority →
    probability, normalized; default all priority 0). One seeded rng drives
    every draw, in schedule order — the whole build is deterministic."""
    if not steps:
        raise ValueError("need at least one (rate_rps, duration_s) step")
    if process not in ("poisson", "bursty"):
        raise ValueError(f"unknown process {process!r} (expected poisson|bursty)")
    sizes = sizes if sizes is not None else ZipfSizes()
    mix: List[Tuple[int, float]] = [(0, 1.0)]
    if priority_mix:
        total = sum(priority_mix.values())
        if total <= 0.0:
            raise ValueError("priority_mix weights must sum > 0")
        mix = [(int(p), w / total) for p, w in sorted(priority_mix.items())]
    rng = random.Random(seed)
    entries: List[Arrival] = []
    t0 = 0.0
    for step_idx, (rate, duration_s) in enumerate(steps):
        proc = (
            PoissonArrivals(rate) if process == "poisson"
            else BurstyArrivals(rate, burst_factor=burst_factor, mean_burst_s=mean_burst_s)
        )
        t = 0.0
        for gap in proc.gaps(rng, duration_s):
            t += gap
            entries.append(
                Arrival(t0 + t, sizes.draw(rng), _draw_priority(rng, mix), step_idx)
            )
        t0 += duration_s
    meta = {
        "seed": seed,
        "process": process,
        "steps": [[float(r), float(d)] for r, d in steps],
        "mean_rows": round(sizes.mean_rows, 6),
        "priority_mix": {str(p): w for p, w in mix},
    }
    return Schedule(entries, meta)
