"""Client-side retry policy for the load harness.

A serving replica's ``ServingOverloadedError`` carries ``retry_after_ms`` —
its own estimate of when the queue will have drained. A well-behaved client
honors it: retrying sooner re-offers the same work to the same full queue,
retrying much later wastes the seat the controller just freed. This policy
object is that behavior as data: bounded attempts, the replica's
``retry_after_ms`` (capped) or exponential backoff when absent, and
positive jitter so a fleet of retrying clients does not re-arrive as one
synchronized wave.

The harness keeps retries honest in the accounting: a retry is **not** a
fresh arrival — ``StepStats`` counts ``retries`` (and router ``hedges``)
separately, so offered load and client-added load never blur
(docs/loadgen.md).
"""
from __future__ import annotations

import random
import threading
from typing import Optional

__all__ = ["RetryPolicy"]


class RetryPolicy:
    """Bounded, jittered, ``retry_after_ms``-honoring resubmission policy.

    ``attempts`` is the number of *re*-submissions allowed per arrival (0
    disables retrying). ``delay_s(attempt, retry_after_ms)`` gives the
    backoff before retry number ``attempt`` (1-based): the server's hint
    when present (and ``honor_retry_after``), else ``backoff_ms`` doubling
    per attempt; capped at ``backoff_max_ms`` before jitter, then stretched
    by up to ``jitter`` (uniform, seeded — deterministic under test).
    """

    def __init__(
        self,
        attempts: int = 3,
        *,
        backoff_ms: float = 10.0,
        backoff_max_ms: float = 1000.0,
        jitter: float = 0.5,
        honor_retry_after: bool = True,
        seed: int = 0,
    ):
        if attempts < 0:
            raise ValueError(f"attempts must be >= 0, got {attempts}")
        self.attempts = int(attempts)
        self.backoff_ms = float(backoff_ms)
        self.backoff_max_ms = float(backoff_max_ms)
        self.jitter = float(jitter)
        self.honor_retry_after = bool(honor_retry_after)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def delay_s(self, attempt: int, retry_after_ms: Optional[float] = None) -> float:
        if self.honor_retry_after and retry_after_ms is not None:
            base_ms = float(retry_after_ms)
        else:
            base_ms = self.backoff_ms * (2.0 ** max(0, attempt - 1))
        base_ms = min(base_ms, self.backoff_max_ms)
        with self._lock:
            base_ms *= 1.0 + self.jitter * self._rng.random()
        return base_ms / 1000.0

    def __repr__(self) -> str:
        return (
            f"RetryPolicy(attempts={self.attempts}, backoff_ms={self.backoff_ms}, "
            f"backoff_max_ms={self.backoff_max_ms}, jitter={self.jitter})"
        )
