"""The open-loop driver: fire a schedule at a serving target, record truth.

``OpenLoopLoadGenerator.run(target)`` walks a :class:`Schedule` on its own
clock — a request fires when its arrival time comes, **not** when the last
response lands. The target only needs the ``InferenceServer`` submit
contract: ``submit(df, timeout_ms=..., priority=...) -> handle`` where
``handle.result()`` blocks for the response or raises a typed serving error.
Submission is non-blocking by design (admission control is synchronous), so
one driver thread holds the schedule on time while a small collector pool
resolves outstanding handles.

Accounting is exhaustive — every arrival ends in exactly one bin per step
(:class:`StepStats`): completed (with latency), shed (controller
priority-shed), rejected (hard queue bound), deadline misses split by the
phase they died in (queued / dispatch), injected faults (the chaos bins:
``loadgen.tick`` dropped the arrival, or ``serving.admit`` /
``serving.dispatch`` failed it), other typed serving errors, and — the bin
chaos suites assert is empty — ``unexpected`` untyped failures. Per load
step the report carries p50/p99/p999 latency, time-to-first-shed, and
per-priority breakdowns.

Clocks are injectable (``clock``/``sleep``), so replay determinism is
provable under a virtual clock with a deterministic target
(tests/test_loadgen.py) — no wall-clock flake in the contract.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional

from flink_ml_tpu.faults import InjectedFault, faults
from flink_ml_tpu.loadgen.arrivals import Schedule
from flink_ml_tpu.serving.errors import (
    ServingDeadlineError,
    ServingError,
    ServingOverloadedError,
)

__all__ = ["StepStats", "LoadReport", "OpenLoopLoadGenerator"]


def _percentile(ordered: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile over an already-sorted list."""
    if not ordered:
        return None
    return ordered[min(int(q * len(ordered)), len(ordered) - 1)]


class StepStats:
    """Everything that happened during one load step. Counter updates are
    lock-guarded — the driver and every collector write concurrently."""

    __slots__ = (
        "step", "offered_rps", "duration_s", "arrivals", "offered_rows",
        "submitted", "completed", "shed", "rejected",
        "deadline_miss_queued", "deadline_miss_dispatch", "injected",
        "typed_errors", "retries", "hedges", "unexpected", "latencies_ms",
        "first_shed_at_s", "max_lag_s", "by_priority", "_lock",
    )

    def __init__(self, step: int, offered_rps: float, duration_s: float):
        self.step = step
        self.offered_rps = offered_rps
        self.duration_s = duration_s
        self.arrivals = 0
        self.offered_rows = 0
        self.submitted = 0
        self.completed = 0
        self.shed = 0  # controller priority-sheds (ServingOverloadedError.shed)
        self.rejected = 0  # hard queue-bound rejections
        self.deadline_miss_queued = 0
        self.deadline_miss_dispatch = 0
        self.injected = 0  # InjectedFault in any seam (tick/admit/dispatch)
        self.typed_errors = 0  # other ServingError (closed, no model, ...)
        # Client-added load, NEVER arrivals: resubmissions under the retry
        # policy, and router-duplicated (hedged) requests. Kept out of
        # ``resolved`` — each arrival still ends in exactly one bin.
        self.retries = 0
        self.hedges = 0
        self.unexpected: List[BaseException] = []  # MUST stay empty in chaos runs
        self.latencies_ms: List[float] = []
        self.first_shed_at_s: Optional[float] = None  # step-relative, shed OR reject
        self.max_lag_s = 0.0  # worst driver lateness against the schedule
        self.by_priority: Dict[int, Dict[str, int]] = {}
        self._lock = threading.Lock()

    # -- concurrent bumps -----------------------------------------------------
    def _prio(self, priority: int) -> Dict[str, int]:
        return self.by_priority.setdefault(
            priority,
            {"arrivals": 0, "completed": 0, "shed": 0, "rejected": 0, "deadline_miss": 0},
        )

    def note_arrival(self, priority: int, rows: int) -> None:
        with self._lock:
            self.arrivals += 1
            self.offered_rows += rows
            self._prio(priority)["arrivals"] += 1

    def note_submitted(self) -> None:
        with self._lock:
            self.submitted += 1

    def note_completed(self, priority: int, latency_ms: float) -> None:
        with self._lock:
            self.completed += 1
            self.latencies_ms.append(latency_ms)
            self._prio(priority)["completed"] += 1

    def note_overload(self, priority: int, err: ServingOverloadedError, at_s: float) -> None:
        with self._lock:
            if err.shed:
                self.shed += 1
                self._prio(priority)["shed"] += 1
            else:
                self.rejected += 1
                self._prio(priority)["rejected"] += 1
            if self.first_shed_at_s is None:
                self.first_shed_at_s = at_s

    def note_deadline(self, priority: int, err: ServingDeadlineError) -> None:
        with self._lock:
            if getattr(err, "phase", "queued") == "dispatch":
                self.deadline_miss_dispatch += 1
            else:
                self.deadline_miss_queued += 1
            self._prio(priority)["deadline_miss"] += 1

    def note_injected(self) -> None:
        with self._lock:
            self.injected += 1

    def note_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def note_hedge(self) -> None:
        with self._lock:
            self.hedges += 1

    def note_typed_error(self) -> None:
        with self._lock:
            self.typed_errors += 1

    def note_unexpected(self, err: BaseException) -> None:
        with self._lock:
            self.unexpected.append(err)

    def note_lag(self, lag_s: float) -> None:
        with self._lock:
            if lag_s > self.max_lag_s:
                self.max_lag_s = lag_s

    # -- reading --------------------------------------------------------------
    # The aggregate reads take the same lock as the counter bumps: the driver
    # (and live dashboards) read these while collectors are still writing,
    # and an unlocked multi-field sum is a torn snapshot — the exact
    # inconsistent-lockset shape graftcheck's shared-state-guard convicts.
    @property
    def deadline_misses(self) -> int:
        with self._lock:
            return self.deadline_miss_queued + self.deadline_miss_dispatch

    @property
    def resolved(self) -> int:
        """Arrivals accounted for — completion, typed rejection, miss, or
        injected fault. Equal to ``arrivals`` once the run is drained (the
        no-deadlock invariant)."""
        with self._lock:
            return (
                self.completed + self.shed + self.rejected
                + self.deadline_miss_queued + self.deadline_miss_dispatch
                + self.injected + self.typed_errors + len(self.unexpected)
            )

    def latency_ms(self, q: float) -> Optional[float]:
        with self._lock:
            ordered = sorted(self.latencies_ms)
        return _percentile(ordered, q)

    def as_dict(self) -> Dict:
        with self._lock:
            ordered = sorted(self.latencies_ms)
            return {
                "step": self.step,
                "offered_rps": self.offered_rps,
                "duration_s": self.duration_s,
                "arrivals": self.arrivals,
                "offered_rows": self.offered_rows,
                "completed": self.completed,
                "shed": self.shed,
                "rejected": self.rejected,
                "deadline_miss_queued": self.deadline_miss_queued,
                "deadline_miss_dispatch": self.deadline_miss_dispatch,
                "injected": self.injected,
                "typed_errors": self.typed_errors,
                "retries": self.retries,
                "hedges": self.hedges,
                "unexpected": len(self.unexpected),
                "latency_p50_ms": _percentile(ordered, 0.5),
                "latency_p99_ms": _percentile(ordered, 0.99),
                "latency_p999_ms": _percentile(ordered, 0.999),
                "time_to_first_shed_s": self.first_shed_at_s,
                "max_lag_s": round(self.max_lag_s, 6),
                "by_priority": {str(p): dict(v) for p, v in sorted(self.by_priority.items())},
            }


class LoadReport:
    """One run's verdict: per-step stats plus whole-run invariant helpers."""

    def __init__(self, steps: List[StepStats], wall_s: float):
        self.steps = steps
        self.wall_s = wall_s

    def step(self, idx: int) -> StepStats:
        return self.steps[idx]

    @property
    def total_arrivals(self) -> int:
        return sum(s.arrivals for s in self.steps)

    @property
    def total_resolved(self) -> int:
        return sum(s.resolved for s in self.steps)

    @property
    def unexpected(self) -> List[BaseException]:
        return [e for s in self.steps for e in s.unexpected]

    def fully_resolved(self) -> bool:
        """Every arrival ended in exactly one bin — the no-deadlock,
        nothing-lost invariant chaos runs assert."""
        return self.total_resolved == self.total_arrivals

    def as_dict(self) -> Dict:
        return {
            "wall_s": round(self.wall_s, 6),
            "arrivals": self.total_arrivals,
            "resolved": self.total_resolved,
            "steps": [s.as_dict() for s in self.steps],
        }

    def __repr__(self) -> str:
        return f"LoadReport(steps={len(self.steps)}, arrivals={self.total_arrivals}, wall_s={self.wall_s:.3f})"


#: Collector-queue sentinel — posted once per collector at drain time.
_DONE = object()


class OpenLoopLoadGenerator:
    """Drive a :class:`Schedule` at a serving target, open-loop.

    ``request_factory(rows) -> DataFrame`` builds each request's payload;
    ``timeout_ms`` is either a number (every request) or a mapping
    ``priority -> ms`` (per-SLO deadlines — tight for best-effort, generous
    for guaranteed traffic). ``clock``/``sleep`` default to the wall clock
    and are injectable for virtual-time replay.

    ``retry`` (a :class:`~flink_ml_tpu.loadgen.retry.RetryPolicy`) makes the
    harness a well-behaved overloaded client: a typed overload is resubmitted
    after the replica's ``retry_after_ms`` (jittered, bounded attempts)
    instead of being binned immediately. Retries run on the collector pool —
    the driver thread never sleeps a backoff, so the schedule stays open-loop
    — and are counted in ``StepStats.retries``, never as fresh arrivals.
    """

    def __init__(
        self,
        schedule: Schedule,
        request_factory: Callable[[int], object],
        *,
        timeout_ms=10_000.0,
        collectors: int = 8,
        retry=None,
        clock: Callable[[], float] = time.perf_counter,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.schedule = schedule
        self.request_factory = request_factory
        self._timeout_ms = timeout_ms
        self.collectors = max(1, int(collectors))
        self.retry = retry
        self._clock = clock
        self._sleep = sleep

    def timeout_ms_for(self, priority: int) -> float:
        if isinstance(self._timeout_ms, dict):
            if priority in self._timeout_ms:
                return float(self._timeout_ms[priority])
            return float(max(self._timeout_ms.values()))
        return float(self._timeout_ms)

    def _steps_from_schedule(self) -> List[StepStats]:
        meta_steps = self.schedule.meta.get("steps") or []
        stats: List[StepStats] = []
        for idx in range(max(self.schedule.n_steps, len(meta_steps))):
            rate, duration = (
                meta_steps[idx] if idx < len(meta_steps) else (0.0, 0.0)
            )
            stats.append(StepStats(idx, float(rate), float(duration)))
        return stats

    def run(self, target) -> LoadReport:
        """Fire the whole schedule; block until every outstanding handle is
        resolved; return the per-step report."""
        steps = self._steps_from_schedule()
        if not steps:
            return LoadReport([], 0.0)
        pending: "queue.Queue" = queue.Queue()

        def resolve(arrival, df, handle, attempt, rel_s, last_overload) -> None:
            """Drive one arrival to its single bin, resubmitting overloads
            under the retry policy (collector-side, so backoff sleeps never
            touch the driver's schedule)."""
            stats: StepStats = steps[arrival.step]
            while True:
                if handle is None:
                    # Retry entry: back off per the replica's hint, resubmit.
                    self._sleep(
                        self.retry.delay_s(
                            attempt, getattr(last_overload, "retry_after_ms", None)
                        )
                    )
                    try:
                        handle = target.submit(
                            df,
                            timeout_ms=self.timeout_ms_for(arrival.priority),
                            priority=arrival.priority,
                        )
                    except ServingOverloadedError as e:
                        if attempt < self.retry.attempts:
                            attempt += 1
                            stats.note_retry()
                            last_overload = e
                            handle = None
                            continue
                        stats.note_overload(arrival.priority, e, rel_s)
                        return
                    except InjectedFault:
                        stats.note_injected()
                        return
                    except ServingError:
                        stats.note_typed_error()
                        return
                    except BaseException as e:  # noqa: BLE001 — the chaos bin
                        stats.note_unexpected(e)
                        return
                    else:
                        stats.note_submitted()
                try:
                    try:
                        response = handle.result()
                    finally:
                        # The router flips ``hedged`` during result() when it
                        # duplicates the request — count each handle once,
                        # whatever bin it lands in.
                        if getattr(handle, "hedged", False):
                            stats.note_hedge()
                except ServingOverloadedError as e:
                    if self.retry is not None and attempt < self.retry.attempts:
                        attempt += 1
                        stats.note_retry()
                        last_overload = e
                        handle = None
                        continue
                    stats.note_overload(arrival.priority, e, rel_s)
                    return
                except ServingDeadlineError as e:
                    stats.note_deadline(arrival.priority, e)
                    return
                except InjectedFault:
                    stats.note_injected()
                    return
                except ServingError:
                    stats.note_typed_error()
                    return
                except BaseException as e:  # noqa: BLE001 — the chaos bin
                    stats.note_unexpected(e)
                    return
                else:
                    stats.note_completed(arrival.priority, response.latency_ms)
                    return

        def collect() -> None:
            while True:
                item = pending.get()
                if item is _DONE:
                    return
                resolve(*item)

        threads = [
            threading.Thread(target=collect, name=f"loadgen-collector-{i}", daemon=True)
            for i in range(self.collectors)
        ]
        for t in threads:
            t.start()

        t_start = self._clock()
        step_starts: Dict[int, float] = {}
        for i, arrival in enumerate(self.schedule):
            due = t_start + arrival.t
            now = self._clock()
            if due > now:
                self._sleep(due - now)
            else:
                steps[arrival.step].note_lag(now - due)
            step_starts.setdefault(arrival.step, arrival.t)
            stats: StepStats = steps[arrival.step]
            stats.note_arrival(arrival.priority, arrival.rows)
            step_rel_s = arrival.t - step_starts[arrival.step]
            try:
                # The harness's own chaos seam: an armed tick fault drops
                # THIS arrival (recorded as injected) and the schedule
                # stays on time — the rig survives its own faults.
                faults.trip("loadgen.tick", arrival=i, step=arrival.step)
            except InjectedFault:
                stats.note_injected()
                continue
            df = self.request_factory(arrival.rows)
            try:
                handle = target.submit(
                    df,
                    timeout_ms=self.timeout_ms_for(arrival.priority),
                    priority=arrival.priority,
                )
            except ServingOverloadedError as e:
                if self.retry is not None and self.retry.attempts > 0:
                    # Hand the arrival to the collector pool for backoff +
                    # resubmit — the driver must not sleep a backoff, or the
                    # schedule stops being open-loop.
                    stats.note_retry()
                    pending.put((arrival, df, None, 1, step_rel_s, e))
                else:
                    stats.note_overload(arrival.priority, e, step_rel_s)
            except InjectedFault:
                stats.note_injected()
            except ServingError:
                stats.note_typed_error()
            except BaseException as e:  # noqa: BLE001 — the chaos bin
                stats.note_unexpected(e)
            else:
                stats.note_submitted()
                pending.put((arrival, df, handle, 0, step_rel_s, None))

        for _ in threads:
            pending.put(_DONE)
        for t in threads:
            t.join()
        return LoadReport(steps, self._clock() - t_start)
