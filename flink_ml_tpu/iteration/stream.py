"""Mini-batch streams: the unbounded-input surface.

Reference: ``DataStreamUtils.generateBatchData:734`` (online minibatching: each subtask
collects globalBatchSize/parallelism records then emits a batch) and the
``HasWindows``/``Windows`` descriptors (``common/window/Windows.java``) that slice an
unbounded stream into training windows; ``EndOfStreamWindows.java:36`` = one window.

TPU mapping (SURVEY.md §5.7): **a window is one device step.** A ``BatchStream`` is any
iterator of columnar batches (dict name → host array). ``window_stream`` applies a
``Windows`` descriptor to a source iterator; ``batch_stream_from_dataframe`` adapts a
bounded DataFrame. Online estimators consume these through ``iterate_unbounded``.
"""
from __future__ import annotations

from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.ops.windows import (
    CountTumblingWindows,
    EventTimeSessionWindows,
    EventTimeTumblingWindows,
    GlobalWindows,
    ProcessingTimeSessionWindows,
    ProcessingTimeTumblingWindows,
    Windows,
)

__all__ = [
    "Batch",
    "batch_stream_from_dataframe",
    "window_stream",
    "rebatch",
]

Batch = Dict[str, np.ndarray]


def _df_to_columns(df: DataFrame, columns: Optional[Sequence[str]] = None) -> Batch:
    names = columns if columns is not None else df.get_column_names()
    out: Batch = {}
    for n in names:
        col = df.column(n)
        out[n] = col if isinstance(col, np.ndarray) else np.asarray(col, dtype=object)
    return out


def _batch_len(batch: Batch) -> int:
    return next(iter(batch.values())).shape[0]


def _slice(batch: Batch, lo: int, hi: int) -> Batch:
    return {k: v[lo:hi] for k, v in batch.items()}


def batch_stream_from_dataframe(
    df: DataFrame,
    batch_size: Optional[int] = None,
    columns: Optional[Sequence[str]] = None,
) -> Iterator[Batch]:
    """Bounded DataFrame → stream of columnar batches (whole frame if no size)."""
    cols = _df_to_columns(df, columns)
    n = _batch_len(cols) if cols else 0
    if batch_size is None or batch_size >= n:
        if n:
            yield cols
        return
    for lo in range(0, n, batch_size):
        yield _slice(cols, lo, min(lo + batch_size, n))


def rebatch(stream: Iterable[Batch], batch_size: int, drop_last: bool = False) -> Iterator[Batch]:
    """Re-chunk an arbitrary batch stream to fixed ``batch_size`` rows.

    The ``generateBatchData:734`` analogue: accumulate until a full global batch is
    available, then emit exactly one window.
    """
    pending: List[Batch] = []
    pending_rows = 0
    for batch in stream:
        pending.append(batch)
        pending_rows += _batch_len(batch)
        while pending_rows >= batch_size:
            taken: Dict[str, List[np.ndarray]] = {}
            need = batch_size
            rest: List[Batch] = []
            for chunk in pending:
                n = _batch_len(chunk)
                if need == 0:
                    rest.append(chunk)
                    continue
                use = min(need, n)
                for k, v in chunk.items():
                    taken.setdefault(k, []).append(v[:use])
                if use < n:
                    rest.append(_slice(chunk, use, n))
                need -= use
            pending = rest
            pending_rows -= batch_size
            yield {k: np.concatenate(v) if len(v) > 1 else v[0] for k, v in taken.items()}
    if pending_rows and not drop_last:
        taken = {}
        for chunk in pending:
            for k, v in chunk.items():
                taken.setdefault(k, []).append(v)
        yield {k: np.concatenate(v) if len(v) > 1 else v[0] for k, v in taken.items()}


def window_stream(
    stream: Iterable[Batch],
    windows: Windows,
    timestamp_column: Optional[str] = None,
    now: Optional[Callable[[], float]] = None,
) -> Iterator[Batch]:
    """Apply a ``Windows`` descriptor to a batch stream.

    - GlobalWindows: one window at end of stream.
    - CountTumblingWindows(size): ``rebatch(stream, size, drop_last=True)`` — the
      reference's count window drops the trailing partial window.
    - EventTimeTumblingWindows(size_ms): group rows by timestamp_column // size_ms;
      windows emit in order as their boundary passes (stream assumed time-ordered,
      as the reference assumes watermarked order).
    - ProcessingTime windows: same mechanics using arrival time (``now()``).
    - Session windows: a gap > gap_ms between consecutive timestamps closes a window.
    """
    if isinstance(windows, GlobalWindows):
        chunks: List[Batch] = [b for b in stream if _batch_len(b)]
        if chunks:
            keys = chunks[0].keys()
            yield {k: np.concatenate([c[k] for c in chunks]) for k in keys}
        return

    if isinstance(windows, CountTumblingWindows):
        yield from rebatch(stream, windows.size, drop_last=True)
        return

    if isinstance(windows, (EventTimeTumblingWindows, ProcessingTimeTumblingWindows)):
        get_ts = _timestamp_getter(windows, timestamp_column, now)
        current_id: Optional[int] = None
        pending: List[Batch] = []
        for batch in stream:
            for wid, part in split_by_tumbling_window(batch, windows.size_ms, get_ts(batch)):
                if current_id is None:
                    current_id = wid
                if wid != current_id:
                    yield _concat(pending)
                    pending = []
                    current_id = wid
                pending.append(part)
        if pending:
            yield _concat(pending)
        return

    if isinstance(windows, (EventTimeSessionWindows, ProcessingTimeSessionWindows)):
        gap = windows.gap_ms
        get_ts = _timestamp_getter(windows, timestamp_column, now)
        pending = []
        last_ts: Optional[float] = None
        for batch in stream:
            ts = get_ts(batch)
            start = 0
            for i in range(len(ts)):
                if last_ts is not None and ts[i] - last_ts > gap:
                    part = _slice(batch, start, i)
                    if _batch_len(part):
                        pending.append(part)
                    if pending:
                        yield _concat(pending)
                    pending = []
                    start = i
                last_ts = float(ts[i])
            part = _slice(batch, start, len(ts))
            if _batch_len(part):
                pending.append(part)
        if pending:
            yield _concat(pending)
        return

    raise ValueError(f"Unsupported windows descriptor: {windows!r}")


def split_by_tumbling_window(batch: Batch, size_ms: float, ts) -> Iterator[tuple]:
    """Yield ``(window_id, sub-batch)`` per tumbling window present in one
    batch, in window order — the single source for window-id assignment
    (used by ``window_stream`` and the online estimators' batch splitters)."""
    ids = (np.asarray(ts) // size_ms).astype(np.int64)
    for wid in np.unique(ids):
        sel = ids == wid
        yield int(wid), {k: np.asarray(v)[sel] for k, v in batch.items()}


def _timestamp_getter(windows, timestamp_column, now):
    if isinstance(windows, (EventTimeTumblingWindows, EventTimeSessionWindows)):
        if not timestamp_column:
            raise ValueError("event-time windows need a timestamp_column")
        return lambda batch: np.asarray(batch[timestamp_column], np.float64)
    import time as _time

    clock = now or (lambda: _time.time() * 1000.0)
    return lambda batch: np.full(_batch_len(batch), clock(), np.float64)


def _concat(chunks: List[Batch]) -> Batch:
    keys = chunks[0].keys()
    return {k: np.concatenate([c[k] for c in chunks]) for k in keys}
