"""L2 — the iteration runtime.

Reference: ``flink-ml-iteration`` (~19.6k LoC, SURVEY.md §2.3) — epoch-tracked feedback
edges grafted onto a streaming DAG: head/tail operators, a JobManager-side
SharedProgressAligner, per-operator epoch-watermark trackers, wrapped operators, draft
graph compilation, feedback-channel checkpointing.

TPU-native collapse (SURVEY.md §7.3): a single-controller host loop driving jit-compiled
SPMD programs **is already globally aligned** — every device finishes epoch N before the
controller starts epoch N+1, so the entire alignment/watermark/coordinator machinery
reduces to a ``for`` loop. What survives, because it is real semantics rather than
plumbing:

  - ``IterationBody`` / ``IterationBodyResult`` — the user contract (feedback variables,
    outputs, termination criteria).
  - ``IterationListener`` — per-epoch / termination callbacks (epoch watermarks).
  - ``iterate_bounded_until_termination`` / ``iterate_unbounded`` — the two public
    entry points (Iterations.java:123,149).
  - Replay semantics (``ReplayableDataStreamList``) — whether the body sees the data
    every epoch or only at epoch 0.
  - The feedback edge — device arrays handed from one epoch to the next **without
    leaving HBM** (the statefun FeedbackChannel becomes a variable rebind; zero-copy).
  - Termination helpers ``TerminateOnMaxIter`` / ``TerminateOnMaxIterOrTol``.
  - Checkpointing of iteration state (epoch counter + variables) for resume.
"""
from flink_ml_tpu.iteration.iteration import (
    IterationBodyResult,
    IterationConfig,
    IterationListener,
    Iterations,
    OperatorLifeCycle,
    ReplayableDataStreamList,
    iterate_bounded_until_termination,
    iterate_unbounded,
)
from flink_ml_tpu.iteration.termination import (
    TerminateOnMaxIter,
    TerminateOnMaxIterOrTol,
)
from flink_ml_tpu.iteration.datacache import (
    DeviceDataCache,
    HostDataCache,
    create_capacity_cache,
)
from flink_ml_tpu.iteration.streaming import WindowedStream, WindowSchedule

__all__ = [
    "IterationBodyResult",
    "IterationConfig",
    "IterationListener",
    "Iterations",
    "OperatorLifeCycle",
    "ReplayableDataStreamList",
    "iterate_bounded_until_termination",
    "iterate_unbounded",
    "TerminateOnMaxIter",
    "TerminateOnMaxIterOrTol",
    "DeviceDataCache",
    "HostDataCache",
    "create_capacity_cache",
    "WindowedStream",
    "WindowSchedule",
]
