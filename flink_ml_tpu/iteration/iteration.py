"""Iteration drivers: bounded-until-termination and unbounded.

Reference: ``Iterations.java:109-526`` builds the cyclic graph (input/head/tail/output/
replay operators, co-located head+tail per feedback edge, criteria stream); the runtime
then aligns epochs across subtasks via SubtaskAlignedEvent → SharedProgressAligner →
GloballyAlignedEvent (HeadOperator.java:325-357, SharedProgressAligner.java:127).

Here the controller is the aligner. An epoch is one turn of the host loop; the feedback
edge is the rebinding of ``variables`` to the body's returned feedback (device arrays
stay in HBM — the analogue of the co-located in-memory FeedbackChannel,
TailOperator.java:81-87); termination mirrors SharedProgressAligner.decide: stop when
the criteria is exhausted or when the body produces no feedback.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, List, Optional, Sequence

import jax

from flink_ml_tpu.faults import faults
from flink_ml_tpu.trace import CAT_PRODUCTIVE, CAT_RECOVERY, CAT_SWAP, tracer

__all__ = [
    "OperatorLifeCycle",
    "IterationConfig",
    "IterationBodyResult",
    "IterationListener",
    "Iterations",
    "iterate_bounded_until_termination",
    "iterate_unbounded",
]


class OperatorLifeCycle(enum.Enum):
    """Ref IterationConfig.OperatorLifeCycle — ALL_ROUND keeps one operator instance
    across epochs; PER_ROUND builds fresh per epoch (forEachRound).

    Host-loop mapping: ALL_ROUND passes the SAME body callable every epoch, so
    closure/attribute state carries across rounds exactly like a long-lived
    operator instance. PER_ROUND treats ``body`` as a zero-arg FACTORY — the
    ``forEachRound`` subgraph builder — invoked once per epoch; the returned
    epoch body starts from fresh state every round and is discarded at the
    round boundary (cross-round state must flow through ``variables``, the
    feedback edge, which is the reference's per-round contract:
    IterationBody.java:73)."""

    ALL_ROUND = "ALL_ROUND"
    PER_ROUND = "PER_ROUND"


@dataclasses.dataclass
class IterationConfig:
    """Ref IterationConfig.java.

    ``pipeline_depth``: max epochs dispatched to the devices ahead of completion.
    ``None`` = auto: 1 on the CPU backend, 8 otherwise; ``0`` = unbounded (no
    throttling). On TPU, dispatching ahead
    keeps the device busy while the host runs the next loop turn; on the
    virtual-device CPU backend, concurrently in-flight programs that contain
    collectives starve each other's all-reduce rendezvous (XLA CPU runs one
    thread per virtual device from a shared pool — observed 40s rendezvous
    timeout aborts with 8 devices on 1 core), so dispatch must be serialized.
    """

    operator_life_cycle: OperatorLifeCycle = OperatorLifeCycle.ALL_ROUND
    max_epochs: Optional[int] = None  # hard safety bound on top of criteria
    checkpoint_interval: int = 0  # epochs between state snapshots; 0 = off
    #: ``flink_ml_tpu.checkpoint.CheckpointManager`` — or its
    #: ``ShardedCheckpointManager`` subclass when the variables are train-mesh
    #: resident (same save/restore_latest contract, per-shard leaf layout);
    #: the drivers never inspect which.
    checkpoint_manager: Any = None
    pipeline_depth: Optional[int] = None


class ReplayableDataStreamList:
    """Ref ``ReplayableDataStreamList.java`` — which data streams the iteration
    body re-reads every epoch vs sees only in epoch 0.

    A *replayed* source is re-materialized per round (the reference replays it
    from the data cache through the Replayer operator): here each epoch gets a
    fresh iterator — from a zero-arg factory, a capacity-tier cache
    (``iter_rows`` re-reads RAM + spill files), or an in-memory
    DataFrame/array (trivially rewindable). A *non-replayed* source delivers
    its data in epoch 0 and is empty afterwards, exactly the reference's
    semantics for un-replayed bounded inputs.

        data = ReplayableDataStreamList(
            replay={"train": cache}, no_replay={"init": init_df})
        iterate_bounded_until_termination(vars, body, data=data)
        # body(variables, epoch, streams): streams["train"] -> fresh iterator
    """

    def __init__(self, replay: Optional[dict] = None, no_replay: Optional[dict] = None):
        self._replay = dict(replay or {})
        self._no_replay = dict(no_replay or {})
        overlap = set(self._replay) & set(self._no_replay)
        if overlap:
            raise ValueError(f"streams marked both replay and no_replay: {overlap}")

    @staticmethod
    def _fresh_iterator(source, replayed: bool = True):
        if callable(source):
            return source()
        if hasattr(source, "iter_rows"):  # capacity-tier caches
            return source.iter_rows()
        if hasattr(source, "collect") and hasattr(source, "column"):  # DataFrame
            cols = {n: source.column(n) for n in source.get_column_names()}
            return iter([cols])
        if hasattr(source, "__next__"):
            if replayed:
                # A raw iterator/generator cannot be re-materialized per epoch
                # — accepting it would silently violate the replay contract
                # (empty from epoch 1 on). Demand a rewindable source.
                raise TypeError(
                    "a one-shot iterator/generator is not replayable; pass a "
                    "zero-arg factory, a capacity-tier cache, or a DataFrame"
                )
            return source  # non-replayed: consumed once in epoch 0 — fine
        if isinstance(source, (list, tuple)):  # rewindable sequence of chunks
            return iter(source)
        return iter([source])  # a plain array/batch: one-chunk stream

    def epoch_view(self, epoch: int) -> dict:
        """name → iterator for this epoch (non-replayed: empty past epoch 0)."""
        view = {name: self._fresh_iterator(src) for name, src in self._replay.items()}
        for name, src in self._no_replay.items():
            view[name] = (
                self._fresh_iterator(src, replayed=False) if epoch == 0 else iter(())
            )
        return view


class _NoCriteria:
    """Sentinel: the body declared no criteria stream."""

    def __repr__(self):  # pragma: no cover - debug aid
        return "NO_CRITERIA"


NO_CRITERIA = _NoCriteria()


@dataclasses.dataclass
class IterationBodyResult:
    """Ref IterationBodyResult.java — feedback streams + output streams + criteria.

    ``feedback_variables``: new values for the iteration variables (same structure as
    the body's input variables); ``None`` means "no feedback produced" which, like an
    empty feedback stream in the reference, terminates the iteration.

    ``termination_criteria``: anything truthy continues the iteration, anything falsy
    stops it; leave at the default ``NO_CRITERIA`` for "no criteria stream" (terminate
    only on empty feedback / max_epochs). A device scalar is allowed and fetched
    lazily by the driver.
    """

    feedback_variables: Optional[Sequence[Any]]
    outputs: Sequence[Any] = ()
    termination_criteria: Any = NO_CRITERIA

    @property
    def has_criteria(self) -> bool:
        return self.termination_criteria is not NO_CRITERIA


class IterationListener:
    """Ref IterationListener.java — epoch watermark callbacks.

    Subclasses override either hook. ``epoch_watermark`` is the epoch that just
    completed globally (0-based, same numbering as the reference's epoch watermarks).
    """

    def on_epoch_watermark_incremented(self, epoch_watermark: int, context: "IterationContext") -> None:
        pass

    def on_iteration_terminated(self, context: "IterationContext") -> None:
        pass


class IterationContext:
    """Collector handed to listeners; ``output`` appends to the iteration outputs."""

    def __init__(self):
        self.collected: List[Any] = []

    def output(self, value: Any) -> None:
        self.collected.append(value)


class _PipelineThrottle:
    """Bounds the number of epochs in flight on the devices (see IterationConfig)."""

    def __init__(self, depth: Optional[int]):
        if depth is None:
            depth = 1 if jax.default_backend() == "cpu" else 8
        self.depth = depth  # 0 = unbounded
        self._inflight: List[Any] = []

    def admit(self, variables) -> None:
        if self.depth <= 0:
            return
        self._inflight.append(variables)
        if len(self._inflight) >= self.depth:
            jax.block_until_ready(self._inflight.pop(0))


def _epoch_body(body: Callable, config: IterationConfig) -> Callable:
    """Resolve the callable to run THIS epoch under the configured lifecycle:
    ALL_ROUND returns ``body`` itself (one operator instance across rounds);
    PER_ROUND invokes ``body`` as the per-round factory and returns the fresh
    epoch body it built."""
    if config.operator_life_cycle is not OperatorLifeCycle.PER_ROUND:
        return body
    fresh = body()
    if not callable(fresh):
        raise TypeError(
            "PER_ROUND lifecycle: the body must be a zero-arg factory "
            "returning the epoch body (the forEachRound builder), got "
            f"{type(fresh).__name__} from {body!r}"
        )
    return fresh


def _criteria_continues(criteria: Any) -> bool:
    """Evaluate a termination criteria 'stream': truthy = keep iterating."""
    if criteria is None:
        return False
    if isinstance(criteria, jax.Array):
        criteria = jax.device_get(criteria)
    return bool(criteria)


def iterate_bounded_until_termination(
    initial_variables: Sequence[Any],
    body: Callable[..., IterationBodyResult],
    config: Optional[IterationConfig] = None,
    listeners: Sequence[IterationListener] = (),
    data: Optional[ReplayableDataStreamList] = None,
) -> List[Any]:
    """Run ``body`` until termination; returns the final outputs.

    Ref ``Iterations.iterateBoundedStreamsUntilTermination`` (Iterations.java:149):
    terminates when the criteria stream is empty for an epoch, when no feedback is
    produced, or at ``config.max_epochs``.

    ``body(variables, epoch) -> IterationBodyResult``. Variables are pytrees (usually
    device arrays); the driver rebinds them each epoch without copying off-device.
    With ``data`` (a ReplayableDataStreamList) the body is called as
    ``body(variables, epoch, streams)`` where replayed streams re-materialize
    per epoch and non-replayed ones are empty after epoch 0.
    """
    config = config or IterationConfig()
    context = IterationContext()
    throttle = _PipelineThrottle(config.pipeline_depth)
    variables = list(initial_variables)
    outputs: List[Any] = []
    epoch = 0

    restored = _maybe_restore(config)
    if restored is not None:
        epoch, variables = restored

    while True:
        if config.max_epochs is not None and epoch >= config.max_epochs:
            break
        faults.trip("iteration.epoch", epoch=epoch)
        with tracer.span("iteration.epoch", CAT_PRODUCTIVE, scope="ml.iteration[bounded]") as sp:
            sp.set_attr("epoch", epoch)
            epoch_body = _epoch_body(body, config)
            if data is not None:
                result = epoch_body(variables, epoch, data.epoch_view(epoch))
            else:
                result = epoch_body(variables, epoch)
        if result.outputs:
            outputs = list(result.outputs)
        for listener in listeners:
            listener.on_epoch_watermark_incremented(epoch, context)
        epoch += 1
        if result.feedback_variables is None:
            break
        variables = list(result.feedback_variables)
        throttle.admit(variables)
        if result.has_criteria and not _criteria_continues(result.termination_criteria):
            break
        _maybe_checkpoint(config, epoch, variables)

    for listener in listeners:
        listener.on_iteration_terminated(context)
    return outputs + context.collected if context.collected else outputs


def iterate_unbounded(
    initial_variables: Sequence[Any],
    stream,
    body: Callable[..., IterationBodyResult],
    config: Optional[IterationConfig] = None,
    listeners: Sequence[IterationListener] = (),
):
    """Unbounded iteration: one epoch per arriving mini-batch, yielding outputs.

    Ref ``Iterations.iterateUnboundedStreams`` (Iterations.java:123) — no termination
    criteria; the iteration lives as long as the input stream. ``stream`` is any
    iterator of batches (see ``flink_ml_tpu.iteration.stream``); ``body(variables,
    batch, epoch)`` returns feedback + outputs, and outputs are yielded per epoch —
    the model-as-stream semantics online algorithms need (OnlineLogisticRegression's
    versioned model stream).

    Kill/resume: with a ``checkpoint_manager`` the snapshot is ``(epoch,
    variables)`` where the epoch *is* the stream position (one batch per
    epoch) — the analogue of the reference checkpointing source offsets with
    operator state (Checkpoints.java:43-143, SGD.java:308-347). On restore the
    driver skips the already-consumed prefix: via ``stream.skip(n)`` when the
    source is seekable, else by discarding ``n`` batches. The resume contract
    is therefore: pass a source that replays from the beginning (or seeks).
    """
    config = config or IterationConfig()
    context = IterationContext()
    throttle = _PipelineThrottle(config.pipeline_depth)
    variables = list(initial_variables)
    epoch = 0
    restored = _maybe_restore(config)
    if restored is not None:
        epoch, variables = restored
        if epoch:
            if hasattr(stream, "skip"):
                stream.skip(epoch)
            else:
                stream = _drop_batches(stream, epoch)

    for batch in stream:
        faults.trip("iteration.epoch", epoch=epoch)
        with tracer.span("iteration.epoch", CAT_PRODUCTIVE, scope="ml.iteration[unbounded]") as sp:
            sp.set_attr("epoch", epoch)
            result = _epoch_body(body, config)(variables, batch, epoch)
        for listener in listeners:
            listener.on_epoch_watermark_incremented(epoch, context)
        epoch += 1
        # Snapshot BEFORE yielding this epoch's outputs: once the consumer has
        # seen an epoch, a resume must never re-emit it (at interval=1 the
        # re-execution window is exactly zero, matching SnapshotDriver).
        done = result.feedback_variables is None
        if not done:
            variables = list(result.feedback_variables)
            throttle.admit(variables)
            _maybe_checkpoint(config, epoch, variables)
        for out in result.outputs:
            yield out
        while context.collected:
            yield context.collected.pop(0)
        if done:
            break

    for listener in listeners:
        listener.on_iteration_terminated(context)
    while context.collected:
        yield context.collected.pop(0)


def _drop_batches(stream, n: int):
    """Fast-forward a replayed source past its already-consumed prefix.

    A source that ends inside the consumed prefix violates the resume
    contract (replay from the beginning); terminating silently there would be
    indistinguishable from a clean run, so it raises instead.
    """
    it = iter(stream)
    for i in range(n):
        try:
            next(it)
        except StopIteration:
            raise ValueError(
                f"replayed source ended {n - i} batch(es) before the checkpointed "
                f"offset {n}; on resume the source must replay the stream from "
                "the beginning"
            ) from None
    return it


def _maybe_checkpoint(config: IterationConfig, epoch: int, variables) -> None:
    mgr = config.checkpoint_manager
    if mgr is None or not config.checkpoint_interval:
        return
    if epoch % config.checkpoint_interval == 0:
        with tracer.span("iteration.checkpoint", CAT_SWAP, scope="ml.iteration") as sp:
            sp.set_attr("epoch", epoch)
            mgr.save(epoch, variables)


def _maybe_restore(config: IterationConfig):
    mgr = config.checkpoint_manager
    if mgr is None:
        return None
    with tracer.span("iteration.restore", CAT_RECOVERY, scope="ml.iteration"):
        return mgr.restore_latest()


class Iterations:
    """Namespace parity with ``Iterations.java`` static API."""

    iterate_bounded_streams_until_termination = staticmethod(iterate_bounded_until_termination)
    iterate_unbounded_streams = staticmethod(iterate_unbounded)
