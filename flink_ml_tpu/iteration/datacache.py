"""Per-partition training-data caches.

Reference: ``flink-ml-iteration/.../datacache/nonkeyed/`` — ``DataCacheWriter.java:37``
(MemorySegment pool spilling to file segments), ``DataCacheReader``,
``DataCacheSnapshot.java:52`` and ``ListStateWithCache.java:43``, the drop-in ListState
used by SGD/KMeans to cache each subtask's slice of the training data across epochs.

TPU-native: two tiers.

``DeviceDataCache`` — the hot tier. The dataset is placed **once** on the mesh, sharded
over the ``data`` axis, and lives in HBM across all epochs. The reference re-reads its
cache every epoch through a serializer; here epoch N+1 reuses the same device buffers —
zero host↔device traffic after load. Per-step minibatch selection happens *inside* the
jit'd step (wraparound gather on the local shard), mirroring the reference's per-subtask
batch-offset cycling (SGD.java:246-285).

``HostDataCache`` — the capacity tier for datasets larger than HBM: appended columnar
chunks in host RAM with optional disk spill (npy memmap), iterated as device-sized
minibatches with one-batch prefetch (jax async dispatch gives the overlap).
Snapshot/restore mirror ``DataCacheSnapshot.writeTo:95/recover:164``.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Iterator, List, Optional

import jax
import numpy as np

from flink_ml_tpu.faults import faults
from flink_ml_tpu.parallel.mesh import MeshContext, get_mesh_context

__all__ = ["DeviceDataCache", "HostDataCache", "create_capacity_cache"]


def create_capacity_cache(memory_budget_bytes=None, spill_dir=None):
    """Capacity-tier cache factory honoring the runtime config tier.

    Returns the C++-backed ``NativeDataCache`` when
    ``native.datacache.enabled`` is set and the toolchain builds, else the
    pure-Python ``HostDataCache`` (identical contract; snapshots are
    interchangeable on disk).
    """
    from flink_ml_tpu.config import Options, config

    if config.get(Options.NATIVE_DATACACHE_ENABLED):
        from flink_ml_tpu.native import native_available

        if native_available():
            from flink_ml_tpu.native.cache import NativeDataCache

            return NativeDataCache(memory_budget_bytes, spill_dir)
    return HostDataCache(memory_budget_bytes, spill_dir)


def _gather_rows(chunk_rows, chunk_at, start: int, stop: int) -> Dict[str, np.ndarray]:
    """Concatenate rows [start, stop) out of an append-ordered chunk log.

    Shared by the Python and native cache tiers; ``chunk_at(i)`` materializes
    (or memory-maps) chunk ``i``'s columns.
    """
    total = sum(chunk_rows)
    if not 0 <= start <= stop <= total:
        raise IndexError(f"rows [{start}, {stop}) out of range [0, {total})")
    parts: List[Dict[str, np.ndarray]] = []
    pos = 0
    for i, n in enumerate(chunk_rows):
        if pos >= stop:
            break
        end = pos + n
        if end > start:
            a, b = max(start - pos, 0), min(stop - pos, n)
            chunk = chunk_at(i)
            parts.append({k: np.asarray(v[a:b]) for k, v in chunk.items()})
        pos = end
    if not parts:  # empty range: zero-row arrays with the right dtypes/shapes
        if not chunk_rows:
            return {}
        proto = chunk_at(0)
        return {k: np.asarray(v[:0]) for k, v in proto.items()}
    if len(parts) == 1:
        # Copy so the caller never holds a live (or read-only) view into cache
        # internals — multi-chunk ranges copy via concatenate anyway.
        return {k: np.array(v) for k, v in parts[0].items()}
    return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}


class DeviceDataCache:
    """Columnar dataset resident in HBM, sharded over the mesh's data axis.

    ``columns`` maps name → host array of shape [n, ...]. All columns are padded to a
    common multiple of the data-axis size; ``n_valid`` is the true row count and
    ``padding_mask`` (float, 1.0 valid / 0.0 pad) lets weighted computations ignore
    padding — the analogue of the reference's per-subtask record counts.
    """

    def __init__(
        self,
        columns: Dict[str, np.ndarray],
        ctx: Optional[MeshContext] = None,
        column_specs: Optional[Dict[str, tuple]] = None,
    ):
        """``column_specs`` optionally maps a column name to a PartitionSpec
        tuple (e.g. ``("data", "model")``) so wide columns land on the mesh in
        their training layout at ingest — dense tensor parallelism shards the
        feature matrix over both axes this way and never holds a row-only
        duplicate in HBM. Trailing dims named by a mesh axis are zero-padded
        to that axis size."""
        self.ctx = ctx or get_mesh_context()
        column_specs = column_specs or {}
        lengths = {np.asarray(c).shape[0] for c in columns.values()}
        if len(lengths) != 1:
            raise ValueError(f"inconsistent column lengths {lengths}")
        (n,) = lengths
        self.n_valid = n
        self.arrays: Dict[str, jax.Array] = {}
        # Host references are kept for the sparse columns only — zero-copy
        # for ndarray inputs (the caller's arrays would stay alive anyway):
        # host-side sparse layout construction (bucketing the static sparsity
        # pattern once per dataset, rebuilt per batch size in sweeps) reads
        # them back without a device->host round trip. Dense columns are not
        # retained — nothing reads them back, and pinning e.g. a 250k x 256
        # feature matrix would waste a quarter GB of host RAM.
        self.host_columns: Dict[str, np.ndarray] = {}
        from flink_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        axis_sizes = {DATA_AXIS: self.ctx.n_data, MODEL_AXIS: self.ctx.n_model}
        for name, col in columns.items():
            col = np.asarray(col)
            if name in ("indices", "values"):
                self.host_columns[name] = col
            spec = column_specs.get(name)
            if spec is None:
                arr, _ = self.ctx.shard_batch(col)
            else:
                pads = [(0, self.ctx.pad_batch(col.shape[0]))]
                for d, axis in enumerate(spec[1:], start=1):
                    size = axis_sizes.get(axis, 1) if axis else 1
                    pads.append((0, (-col.shape[d]) % size))
                pads += [(0, 0)] * (col.ndim - len(pads))
                if any(p for _, p in pads):
                    col = np.pad(col, pads)
                arr = jax.device_put(col, self.ctx.sharding(*spec))
            self.arrays[name] = arr
        mask = np.ones(n, np.float32)
        self.arrays["__mask__"], _ = self.ctx.shard_batch(mask)
        self.n_padded = self.arrays["__mask__"].shape[0]

    @property
    def local_rows(self) -> int:
        """Rows per device shard (padded)."""
        return self.n_padded // self.ctx.n_data

    def __getitem__(self, name: str) -> jax.Array:
        return self.arrays[name]

    @property
    def mask(self) -> jax.Array:
        return self.arrays["__mask__"]


class HostDataCache:
    """Append-only columnar cache in host RAM with disk spill.

    ``append`` adds a chunk (dict of equally-long arrays); once ``memory_budget_bytes``
    is exceeded, subsequent chunks are written as .npy files under ``spill_dir`` and
    memory-mapped on read. ``iter_minibatches`` yields device-ready batches of
    ``batch_size`` rows (trailing partial batch emitted unless ``drop_last``),
    cycling epoch after epoch like the reference's DataCacheReader replay.
    """

    def __init__(
        self,
        memory_budget_bytes: Optional[int] = None,
        spill_dir: Optional[str] = None,
    ):
        # Constructor args win; otherwise the runtime config tier decides
        # (ref iteration.data-cache.path — deployments set spill locations
        # without code changes).
        from flink_ml_tpu.config import resolve_cache_config

        self.memory_budget, self.spill_dir = resolve_cache_config(
            memory_budget_bytes, spill_dir
        )
        # Append-ordered log; each entry is either {"mem": chunk} or {"files": paths}.
        self._log: List[Dict[str, object]] = []
        self._chunk_rows: List[int] = []
        self._mem_bytes = 0
        self._n_rows = 0
        self._spill_count = 0
        self._finished = False

    # --- write side (DataCacheWriter.addRecord/finish) -----------------------
    def append(self, chunk: Dict[str, np.ndarray]) -> None:
        if self._finished:
            raise RuntimeError("cache already finished")
        chunk = {k: np.asarray(v) for k, v in chunk.items()}
        lengths = {v.shape[0] for v in chunk.values()}
        if len(lengths) != 1:
            raise ValueError(f"inconsistent column lengths {lengths}")
        (n,) = lengths
        nbytes = sum(v.nbytes for v in chunk.values())
        if self._mem_bytes + nbytes > self.memory_budget and self.spill_dir:
            faults.trip("datacache.spill.write", chunk=self._spill_count)
            os.makedirs(self.spill_dir, exist_ok=True)
            files = {}
            for k, v in chunk.items():
                path = os.path.join(self.spill_dir, f"chunk{self._spill_count}_{k}.npy")
                np.save(path, v)
                files[k] = path
            self._log.append({"files": files})
            self._spill_count += 1
        else:
            self._log.append({"mem": chunk})
            self._mem_bytes += nbytes
        self._chunk_rows.append(n)
        self._n_rows += n

    def finish(self) -> None:
        self._finished = True

    @property
    def num_rows(self) -> int:
        return self._n_rows

    # --- read side (DataCacheReader) -----------------------------------------
    def _chunks(self) -> Iterator[Dict[str, np.ndarray]]:
        """Chunks in append order (memory and spilled tiers interleaved as written)."""
        for i in range(len(self._log)):
            yield self._chunk_at(i)

    def iter_rows(self) -> Iterator[Dict[str, np.ndarray]]:
        yield from self._chunks()

    def _chunk_at(self, idx: int) -> Dict[str, np.ndarray]:
        entry = self._log[idx]
        if "mem" in entry:
            return entry["mem"]  # type: ignore[return-value]
        faults.trip("datacache.spill.read", chunk=idx)
        return {
            k: np.load(path, mmap_mode="r")
            for k, path in entry["files"].items()  # type: ignore[union-attr]
        }

    def rows(self, start: int, stop: int) -> Dict[str, np.ndarray]:
        """Random-access gather of rows ``[start, stop)`` across the chunk log.

        Spilled chunks are memory-mapped and sliced, so only the requested rows
        materialize — this is what lets training stream HBM-sized windows out of
        a larger-than-memory cache (the ``DataCacheReader`` random-access role).
        Requires ``0 <= start <= stop <= num_rows``.
        """
        return _gather_rows(self._chunk_rows, self._chunk_at, start, stop)

    def iter_minibatches(
        self, batch_size: int, drop_last: bool = False
    ) -> Iterator[Dict[str, np.ndarray]]:
        """One pass over the cache in fixed-size batches (re-chunking across chunk
        boundaries; a trailing partial batch is emitted unless ``drop_last``)."""
        from flink_ml_tpu.iteration.stream import rebatch

        yield from rebatch(
            ({k: np.asarray(v) for k, v in c.items()} for c in self._chunks()),
            batch_size,
            drop_last=drop_last,
        )

    # --- snapshot (DataCacheSnapshot.writeTo/recover) ------------------------
    def snapshot(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        count = 0
        for i, chunk in enumerate(self._chunks()):
            np.savez(os.path.join(path, f"chunk{i}.npz"), **chunk)
            count = i + 1
        # Manifest guards against stale chunk files from an earlier, larger snapshot
        # in the same directory.
        with open(os.path.join(path, "MANIFEST.json"), "w") as f:
            json.dump({"num_chunks": count, "num_rows": self._n_rows}, f)

    @classmethod
    def recover(cls, path: str, **kwargs) -> "HostDataCache":
        cache = cls(**kwargs)
        with open(os.path.join(path, "MANIFEST.json")) as f:
            manifest = json.load(f)
        for i in range(manifest["num_chunks"]):
            with np.load(os.path.join(path, f"chunk{i}.npz")) as z:
                cache.append({k: z[k] for k in z.files})
        cache.finish()
        return cache
