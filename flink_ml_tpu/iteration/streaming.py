"""Streamed (larger-than-HBM) training windows over host-tier caches.

Reference: ``ListStateWithCache.java:43`` — each SGD/KMeans subtask caches its
training partition in managed memory segments spilling to disk
(``DataCacheWriter.java:37``) and re-reads it through a serializer every epoch.

TPU-native: the capacity tier (``HostDataCache`` / ``NativeDataCache``) holds
the dataset on the host (RAM + spill files); training streams fixed-size
per-shard *windows* into HBM, runs every minibatch epoch that falls inside the
resident window as ONE fused device program, and prefetches the next window
while the device computes (jax async dispatch provides the overlap — the
program on window j is enqueued, then the host gathers and device_puts window
j+1 before blocking on j's results).

Window layout reproduces the resident ``DeviceDataCache`` sharding exactly:
with ``m = ceil(n / n_data)`` rows per shard, shard ``k``'s window ``j`` holds
global rows ``[k*m + j*W, k*m + min((j+1)*W, m))`` padded to ``W`` with
zero-mask rows. Streamed training therefore follows the same per-shard
batch-offset cycling as the resident path (SGD.java:246-285): when the local
batch divides the shard evenly every epoch consumes exactly the resident rows
and weights (equal results up to XLA fusion-order ULPs); at a ragged tail the
contributing rows and weights are still identical — the short tail batch is
realized by masking the window padding instead of the resident path's clamped
re-read, same weighted sums in a different summation order.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from flink_ml_tpu.faults import faults
from flink_ml_tpu.parallel.mesh import MeshContext

__all__ = ["WindowSchedule", "WindowedStream", "is_host_cache", "plan_windows", "run_windows"]


def is_host_cache(obj) -> bool:
    """Duck-typed check for the capacity-tier cache contract (HostDataCache /
    NativeDataCache / anything exposing num_rows + rows(start, stop))."""
    return hasattr(obj, "num_rows") and hasattr(obj, "rows")


class WindowSchedule:
    """Epoch → window assignment for per-shard batch-offset cycling.

    ``runs`` is a list of ``(window_idx, local_starts)`` with ``local_starts``
    the slice starts *relative to the window*; consecutive epochs that fall in
    the same window form one run, capped at ``chunk_len`` epochs — the lesser
    of ``window // batch`` and the ``fused_chunk_len`` dispatch-length
    watchdog — so every run fits one fixed-width fused program.
    """

    def __init__(
        self,
        local_rows: int,
        local_batch: int,
        window_rows: int,
        max_iter: int,
        serial_elems_per_epoch: int = 0,
        check_loss: bool = False,
        flops_per_epoch: float = 0.0,
    ):
        # The cycling rule is offset_schedule's — the single source of truth the
        # resident fused path also consumes, so the two paths cannot drift.
        from flink_ml_tpu.ops.optimizer import fused_chunk_len
        from flink_ml_tpu.ops.schedule import offset_schedule

        b = local_batch
        W = max(b, min(int(window_rows), local_rows))
        W = -(-W // b) * b  # round up to a whole number of batches
        self.window = W
        self.n_windows = -(-local_rows // W)
        # Capped by max_iter (a short training over a large window must not pad
        # its one dispatch to a mostly-inactive full-width scan) and by the
        # dispatch-length policy shared with the resident trainers (watchdog
        # budgets + the tol sync cadence).
        self.chunk_len = min(
            max(1, W // b),
            fused_chunk_len(max_iter, check_loss, serial_elems_per_epoch, flops_per_epoch),
        )
        _, offsets = offset_schedule(local_rows, b, max_iter)
        runs: List[Tuple[int, List[int]]] = []
        for off in offsets:
            j = int(off) // W
            if runs and runs[-1][0] == j and len(runs[-1][1]) < self.chunk_len:
                runs[-1][1].append(int(off) - j * W)
            else:
                runs.append((j, [int(off) - j * W]))
        self.runs = [(j, np.asarray(starts, np.int32)) for j, starts in runs]

    def padded(self, starts: np.ndarray) -> Tuple[np.ndarray, np.ndarray, int]:
        """(starts, active, n_active) padded to the fixed chunk width — the
        same padding contract as every chunked fused trainer."""
        from flink_ml_tpu.ops.schedule import chunked_schedule

        starts_c, _, active, n_active = next(
            chunked_schedule(starts, starts, len(starts), self.chunk_len)
        )
        return starts_c, active, n_active


class WindowedStream:
    """Loads per-shard windows of a host-tier cache onto the mesh.

    ``columns`` maps output name → cache column name; every loaded window is a
    dict of device arrays ``[n_data * W, ...]`` sharded over the data axis,
    plus ``"__mask__"`` (1.0 on real rows, 0.0 on window/global padding).
    A column named in ``optional`` may be absent from the cache and fills with
    ones (the weights default); any other missing column raises at construction
    — a misnamed labels column must not silently train on constant targets.
    ``dtypes`` overrides the default dtype per output column (e.g. int32 for
    padded-CSR ``indices``, which must not round-trip through float).

    ``window`` must be the batch-aligned width from the matching
    ``WindowSchedule`` — construct both through ``plan_windows`` so they cannot
    drift apart.
    """

    def __init__(
        self,
        cache,
        columns: Dict[str, str],
        ctx: MeshContext,
        window: int,
        dtype=np.float32,
        transforms: Optional[Dict[str, object]] = None,
        dtypes: Optional[Dict[str, object]] = None,
        optional: Sequence[str] = ("weights", "w"),
    ):
        self.cache = cache
        self.columns = columns
        self.ctx = ctx
        self.dtype = np.dtype(dtype)
        self.dtypes = {k: np.dtype(v) for k, v in (dtypes or {}).items()}
        self.transforms = transforms or {}
        self.optional = set(optional)
        self.n = int(cache.num_rows)
        if self.n == 0:
            raise ValueError("cannot stream an empty cache")
        self.m = -(-self.n // ctx.n_data)  # per-shard rows (same as shard_batch pad)
        self.window = int(window)
        peek = cache.rows(0, 1)
        missing = [
            col
            for out, col in columns.items()
            if col not in peek and out not in self.optional
        ]
        if missing:
            raise KeyError(
                f"cache columns {missing} not found (cache has {sorted(peek)})"
            )
        self._shapes = {}
        self._present = {}
        for out, col in columns.items():
            self._present[out] = col in peek
            self._shapes[out] = peek[col].shape[1:] if col in peek else ()

    def load(self, j: int) -> Dict[str, jax.Array]:
        """Assemble window ``j`` for every shard and place it on the mesh."""
        W, m, n, nd = self.window, self.m, self.n, self.ctx.n_data
        host: Dict[str, np.ndarray] = {
            out: np.zeros((nd * W,) + self._shapes[out], self.dtypes.get(out, self.dtype))
            for out in self.columns
        }
        mask = np.zeros(nd * W, self.dtype)
        for k in range(nd):
            lo = k * m + j * W
            hi = min(k * m + min((j + 1) * W, m), n)
            if hi <= lo:
                continue
            got = self.cache.rows(lo, hi)
            sl = slice(k * W, k * W + (hi - lo))
            for out, col in self.columns.items():
                if self._present[out]:
                    val = got[col]
                    tf = self.transforms.get(out)
                    if tf is not None:
                        val = tf(np.asarray(val))
                    host[out][sl] = np.asarray(val, self.dtypes.get(out, self.dtype))
                else:
                    host[out][sl] = 1.0
            mask[sl] = 1.0
        out = {
            name: jax.device_put(arr, self.ctx.batch) for name, arr in host.items()
        }
        out["__mask__"] = jax.device_put(mask, self.ctx.batch)
        return out


def plan_windows(
    cache,
    columns: Dict[str, str],
    ctx: MeshContext,
    window_rows: int,
    local_batch: int,
    max_iter: int,
    dtype=np.float32,
    transforms: Optional[Dict[str, object]] = None,
    dtypes: Optional[Dict[str, object]] = None,
    serial_elems_per_epoch: int = 0,
    check_loss: bool = False,
    flops_per_epoch: float = 0.0,
) -> Tuple["WindowedStream", "WindowSchedule"]:
    """Build a (stream, schedule) pair with a consistent batch-aligned width."""
    n = int(cache.num_rows)
    if n == 0:
        raise ValueError("cannot stream an empty cache")
    m = -(-n // ctx.n_data)
    sched = WindowSchedule(
        m, local_batch, window_rows, max_iter,
        serial_elems_per_epoch, check_loss, flops_per_epoch,
    )
    stream = WindowedStream(cache, columns, ctx, sched.window, dtype, transforms, dtypes)
    return stream, sched


def run_windows(
    stream: "WindowedStream", sched: "WindowSchedule", dispatch, start_run: int = 0
) -> None:
    """Drive the window runs with one-ahead prefetch and lazy eviction.

    ``dispatch(run_index, window_buffers, starts, active, n_active)`` must
    *enqueue* the device program (async) and may return an ``observe``
    callable; the driver calls it **after** prefetching the next window, so the
    host gather + device_put overlaps the device compute, and stops the run
    loop when it returns True (the streamed analogue of the host loop's
    termination-criteria check). A window revisited by the very next run stays
    resident; buffers are evicted as soon as the run sequence leaves them, so
    at most two windows occupy HBM.
    """
    runs = sched.runs
    if start_run >= len(runs):
        return
    bufs: Dict[int, Dict[str, jax.Array]] = {
        runs[start_run][0]: stream.load(runs[start_run][0])
    }
    for i in range(start_run, len(runs)):
        j, starts_local = runs[i]
        faults.trip("streaming.window", run=i, window=j)
        starts_c, active_c, n_active = sched.padded(starts_local)
        observe = dispatch(i, bufs[j], starts_c, active_c, n_active)
        next_j = runs[i + 1][0] if i + 1 < len(runs) else None
        if next_j is not None and next_j not in bufs:
            bufs[next_j] = stream.load(next_j)  # overlaps the async dispatch
        if next_j != j:
            bufs.pop(j, None)
        if observe is not None and observe():
            break
