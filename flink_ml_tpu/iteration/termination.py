"""Termination-criteria helpers.

Reference: ``flink-ml-core/.../common/iteration/`` — ``TerminateOnMaxIter.java:34``
(emit a record for rounds 0..maxIter-1; empty stream thereafter terminates),
``TerminateOnMaxIterOrTol.java:34`` (also stop when loss < tol),
``ForwardInputsOfLastRound.java:34`` (buffer inputs, emit at termination — in the
host-loop world this is simply "return the final variables as outputs", so it needs no
class here).

These are callables producing the ``termination_criteria`` value for an
``IterationBodyResult``: truthy = continue.
"""
from __future__ import annotations

import math
from typing import Any, Optional

import jax

__all__ = ["TerminateOnMaxIter", "TerminateOnMaxIterOrTol"]


class TerminateOnMaxIter:
    """Continue while ``epoch + 1 < max_iter`` (reference emits for rounds < maxIter;
    the round that consumes the last record is the final one)."""

    def __init__(self, max_iter: int):
        self.max_iter = max_iter

    def __call__(self, epoch: int, loss: Any = None) -> bool:
        return epoch + 1 < self.max_iter


class TerminateOnMaxIterOrTol:
    """Continue while epoch budget remains AND loss >= tol.

    ``loss`` may be a device scalar; it is fetched only when tol is finite so the
    fast path (tol = -inf/None) never synchronizes the device pipeline.
    """

    def __init__(self, max_iter: Optional[int] = None, tol: Optional[float] = None):
        self.max_iter = math.inf if max_iter is None else max_iter
        self.tol = -math.inf if tol is None else tol

    def __call__(self, epoch: int, loss: Any = None) -> bool:
        if epoch + 1 >= self.max_iter:
            return False
        if loss is not None and self.tol > -math.inf:
            if isinstance(loss, jax.Array):
                loss = float(jax.device_get(loss))
            if loss < self.tol:
                return False
        return True
