"""RetrievalClient — the request-side wrapper around a served candidate index.

The serving heads speak candidate ROWS and ladder-rung-wide result slabs
(``servable/retrieval.py``); callers speak item ids and exact per-request K.
This wrapper owns the translation in both directions:

- **swing** queries are ``(item_id, weight)`` histories; the client maps item
  ids onto the index's candidate rows (unknown ids are dropped — they can
  neither contribute signal nor be recommended) and packs a
  ``SparseVector(C, rows, weights)`` per request.
- **lsh** queries are feature vectors, passed through unchanged.
- Requests carry their true K in the ``kCol`` scalar column; the batch
  compiles at the max-K ladder rung, and the client trims each reply back to
  its request's K, drops the typed-empty slots (row −1) and translates rows
  to item ids against the index's ``item_ids``.
- When the backend's ``predict`` takes a ``shape_key`` parameter
  (``InferenceServer`` does), the client passes ``"k<rung>"`` so the batcher
  only coalesces requests headed for the same compiled rung. The fleet
  router doesn't take one — that's fine, the key is purely an optimization
  (a mixed batch still answers correctly at the wider rung).

The module imports only L0/L1 — it runs in a pure serving process.
"""
from __future__ import annotations

import inspect
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.servable.shapes import k_rung

__all__ = ["RetrievalClient"]

#: One request's answer: (item ids best-first, scores) — Swing similarity
#: descending or LSH 1 − Jaccard distance ascending.
Result = Tuple[np.ndarray, np.ndarray]


class RetrievalClient:
    """Query a served :class:`~flink_ml_tpu.retrieval.index.CandidateIndex`.

    ``backend`` is anything with ``predict(df, ...)`` (``InferenceServer``,
    ``FleetRouter``) or, failing that, ``transform(df)`` (a bare servable —
    tests, offline scoring). ``index`` is duck-typed: it provides the kind,
    the column params and ``item_ids`` (a ``CandidateIndex`` or either
    servable head works)."""

    def __init__(self, backend, index):
        self._backend = backend
        self._kind = (
            index.get_index_kind()
            if hasattr(index, "get_index_kind")
            else ("swing" if hasattr(index, "get_history_col") else "lsh")
        )
        self._item_ids = np.asarray(index.item_ids, np.int64)
        self._row_of = {int(v): r for r, v in enumerate(self._item_ids)}
        self._k_col = index.get_k_col()
        out = index.get_output_col()
        self._rows_col, self._scores_col = f"{out}_rows", f"{out}_scores"
        if self._kind == "swing":
            self._query_col = index.get_history_col()
        else:
            self._query_col = index.get_input_col()
        predict = getattr(backend, "predict", None)
        self._predict = predict if callable(predict) else None
        # Explicit-parameter check, not **kwargs acceptance: the fleet
        # router's predict(**kw) forwards into submit(), which would
        # TypeError on an unknown shape_key.
        self._accepts_shape_key = self._predict is not None and (
            "shape_key" in inspect.signature(self._predict).parameters
        )

    @property
    def candidate_count(self) -> int:
        return int(self._item_ids.shape[0])

    # --- query building -------------------------------------------------------
    def history_vector(self, history) -> SparseVector:
        """One swing query: ``(item_id, weight)`` pairs (or a mapping) →
        ``SparseVector`` over candidate rows, weights summed per row,
        unknown item ids dropped."""
        pairs = history.items() if hasattr(history, "items") else history
        weights: dict = {}
        for item, w in pairs:
            row = self._row_of.get(int(item))
            if row is not None:
                weights[row] = weights.get(row, 0.0) + float(w)
        rows = np.asarray(sorted(weights), np.int64)
        vals = np.asarray([weights[int(r)] for r in rows], np.float64)
        return SparseVector(self.candidate_count, rows, vals)

    def _request_frame(self, queries: Sequence, ks: np.ndarray) -> DataFrame:
        if self._kind == "swing":
            col = [
                q if isinstance(q, SparseVector) else self.history_vector(q)
                for q in queries
            ]
        else:
            col = list(queries)
        return DataFrame(
            [self._query_col, self._k_col], None, [col, ks.astype(np.int64)]
        )

    # --- the round trip -------------------------------------------------------
    def query(
        self,
        queries: Sequence,
        k: Union[int, Sequence[int]],
        **predict_kwargs,
    ) -> List[Result]:
        """Answer a batch of retrieval queries: swing histories or LSH feature
        vectors per the index kind. ``k`` is one int for all requests or one
        per request. Extra kwargs (``timeout_ms``, ``priority``) pass through
        to the backend's ``predict``. Returns per request ``(item_ids,
        scores)`` best-first, each exactly ``min(k, hits)`` long."""
        n = len(queries)
        ks = np.broadcast_to(np.asarray(k, np.int64), (n,)).copy()
        if n and int(ks.min()) < 1:
            raise ValueError("k must be >= 1")
        df = self._request_frame(queries, ks)
        if self._predict is not None:
            if self._accepts_shape_key and n:
                predict_kwargs.setdefault(
                    "shape_key", f"k{k_rung(int(ks.max()))}"
                )
            out = self._predict(df, **predict_kwargs)
        else:
            out = self._backend.transform(df)
        # InferenceServer/FleetRouter wrap the frame in a ServingResponse.
        out = getattr(out, "dataframe", out)
        return self._trim(out, ks)

    def _trim(self, out: DataFrame, ks: np.ndarray) -> List[Result]:
        rows_mat = np.asarray(out.column(self._rows_col), np.int64)
        score_mat = np.asarray(out.column(self._scores_col), np.float64)
        results: List[Result] = []
        for rows, scores, k in zip(rows_mat, score_mat, ks):
            head = rows[: int(k)]
            keep = head >= 0  # typed-empty slots carry row −1
            results.append(
                (self._item_ids[head[keep]], scores[: int(k)][keep])
            )
        return results
