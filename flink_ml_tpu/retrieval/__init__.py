"""Retrieval tier — device-resident top-K serving for the recommendation
workload family (docs/retrieval.md).

- :class:`~flink_ml_tpu.retrieval.index.CandidateIndex` — the publishable,
  versioned index artifact: candidate score/neighbor matrices (Swing) or LSH
  hash tables + index sets, hot-swapped through the same registry/poller
  machinery model versions use.
- :class:`~flink_ml_tpu.retrieval.client.RetrievalClient` — the request-side
  wrapper: item-id ↔ candidate-row translation, per-request K, rung trimming.

The package imports only L0/L1 (api, linalg, servable, utils) — a serving
process loads a published index without the training stack.
"""
from flink_ml_tpu.retrieval.client import RetrievalClient
from flink_ml_tpu.retrieval.index import CandidateIndex

__all__ = ["CandidateIndex", "RetrievalClient"]
