"""CandidateIndex — the publishable, versioned retrieval index artifact.

An index is model data with a different provenance: instead of fitted
coefficients it holds a candidate catalog (``item_ids``) plus the
device-resident structures one of the two retrieval heads scores against —

- **swing** — the ELL neighbor table (``sim_ids``/``sim_values [C, M]``,
  padding slots id 0 / value 0) distilled from a Swing run's item-item
  similarity output; served by
  :class:`~flink_ml_tpu.servable.retrieval.SwingTopKServable`.
- **lsh** — MinHash hash-table lanes (``cand_lanes [C, 2·T·F]``), exact
  candidate index sets (``cand_ids``/``cand_nnz``) and the hash family's
  coefficients; served by
  :class:`~flink_ml_tpu.servable.retrieval.LSHTopKServable`.

Because the artifact rides the framework's stage persistence (metadata JSON +
``data/model_data.npz``), everything built for model versions works on
indices unchanged: ``publish_servable`` writes ``v-<N>`` atomically, the
``ModelVersionPoller`` discovers + loads + WARMS a new index off the serving
path, ``ModelRegistry.swap`` flips it in atomically, rollback quarantines it
— an index version and a model version are the same lifecycle
(docs/retrieval.md, docs/serving.md).

The module is L3 but imports only L0/L1 — a published index loads in a
serving process with no training stack present. In particular the builders
take the *output DataFrame* of a Swing run (string or structured encoding)
and a duck-typed fitted MinHashLSH model, never the model classes.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from flink_ml_tpu.api.core import Stage
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.params.param import (
    IntParam,
    ParamValidators,
    StringParam,
    update_existing_params,
)
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.servable.retrieval import (
    HasKCol,
    LSHTopKServable,
    SwingTopKServable,
    index_sets,
    minhash_lanes,
)
from flink_ml_tpu.utils import read_write as rw

__all__ = ["CandidateIndex", "KIND_LSH", "KIND_SWING"]

KIND_SWING = "swing"
KIND_LSH = "lsh"

#: Which servable class serves each index kind (the ``load_servable``
#: dispatch table).
_SERVABLES = {KIND_SWING: SwingTopKServable, KIND_LSH: LSHTopKServable}

#: Model-array names each kind must carry (validated at save).
_ARRAY_NAMES = {
    KIND_SWING: ("item_ids", "sim_values", "sim_ids"),
    KIND_LSH: ("item_ids", "cand_lanes", "cand_ids", "cand_nnz", "coeff_a", "coeff_b"),
}


class CandidateIndex(Stage, HasInputCol, HasOutputCol, HasKCol):
    """Device-resident candidate index; see module docstring.

    The params mirror the serving head's params by NAME (``historyCol``,
    ``kCol``, ``outputCol``, ``inputCol``, ``numHashTables``, …) so a
    published index's metadata configures the loaded servable directly —
    ``load_servable`` is a pure class dispatch on ``indexKind``, no param
    translation layer."""

    INDEX_KIND = StringParam(
        "indexKind",
        "Which retrieval head serves this index.",
        KIND_SWING,
        ParamValidators.in_array([KIND_SWING, KIND_LSH]),
    )
    HISTORY_COL = StringParam(
        "historyCol",
        "Sparse request column of consumed-candidate weights over the "
        "candidate-row space (swing kind).",
        "history",
        ParamValidators.not_null(),
    )
    NUM_HASH_TABLES = IntParam(
        "numHashTables", "Number of hash tables (lsh kind).", 1, ParamValidators.gt_eq(1)
    )
    NUM_HASH_FUNCTIONS_PER_TABLE = IntParam(
        "numHashFunctionsPerTable",
        "Number of hash functions per hash table (lsh kind).",
        1,
        ParamValidators.gt_eq(1),
    )

    def __init__(self, arrays: Optional[Dict[str, np.ndarray]] = None):
        super().__init__()
        self.arrays: Dict[str, np.ndarray] = {
            k: np.asarray(v) for k, v in (arrays or {}).items()
        }

    # --- params ---------------------------------------------------------------
    def get_index_kind(self) -> str:
        return self.get(self.INDEX_KIND)

    def set_index_kind(self, value: str):
        return self.set(self.INDEX_KIND, value)

    def get_history_col(self) -> str:
        return self.get(self.HISTORY_COL)

    def set_history_col(self, value: str):
        return self.set(self.HISTORY_COL, value)

    def get_num_hash_tables(self) -> int:
        return self.get(self.NUM_HASH_TABLES)

    def set_num_hash_tables(self, value: int):
        return self.set(self.NUM_HASH_TABLES, value)

    def get_num_hash_functions_per_table(self) -> int:
        return self.get(self.NUM_HASH_FUNCTIONS_PER_TABLE)

    def set_num_hash_functions_per_table(self, value: int):
        return self.set(self.NUM_HASH_FUNCTIONS_PER_TABLE, value)

    # --- introspection --------------------------------------------------------
    @property
    def item_ids(self) -> np.ndarray:
        return np.asarray(self.arrays["item_ids"], np.int64)

    @property
    def candidate_count(self) -> int:
        return int(self.item_ids.shape[0])

    def _check_arrays(self) -> None:
        required = _ARRAY_NAMES[self.get_index_kind()]
        missing = [n for n in required if n not in self.arrays]
        if missing:
            raise RuntimeError(
                f"{self.get_index_kind()!r} index has no data yet (missing {missing}); "
                "build it with from_swing_output/from_lsh_model first"
            )

    # --- persistence (the model-version save layout, utils/read_write.py) ----
    def save(self, path: str) -> None:
        self._check_arrays()
        rw.save_metadata(self, path)
        rw.save_model_arrays(path, self.arrays)

    @classmethod
    def load(cls, path: str) -> "CandidateIndex":
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        index = cls()
        index.load_param_map_from_json(metadata["paramMap"])
        index.arrays = rw.load_model_arrays(path)
        return index

    @classmethod
    def load_servable(cls, path: str):
        """The serving-side loader (``servable.api.load_servable`` dispatches
        here via the saved className): returns the runtime-free top-K head
        for the saved kind, params restored from the index metadata, arrays
        from its npz — the training stack is never imported."""
        metadata = rw.load_metadata(path)
        probe = cls()
        known = {p.name for p in probe.get_param_map()}
        probe.load_param_map_from_json(
            {k: v for k, v in metadata["paramMap"].items() if k in known}
        )
        return _SERVABLES[probe.get_index_kind()].load_servable(path)

    def servable(self):
        """The in-process servable of this index (no save/load round trip —
        tests and single-process serving)."""
        self._check_arrays()
        head = _SERVABLES[self.get_index_kind()]()
        update_existing_params(head, self)
        head._apply_model_arrays(self.arrays)
        return head

    # --- builders -------------------------------------------------------------
    @classmethod
    def from_swing_output(
        cls,
        df: DataFrame,
        *,
        item_col: str = "item",
        output_col: str = "output",
        **params,
    ) -> "CandidateIndex":
        """Distill a Swing run's item-item similarity output into a swing
        index. Accepts either encoding Swing emits: the reference's
        ``"item,score;…"`` strings in ``output_col``, or the structured
        ``<output_col>_ids`` / ``<output_col>_scores`` columns when present
        (``Swing.structuredOutput``). The candidate space is the sorted
        unique union of source items and their neighbors, so every id a
        history can mention has a candidate row; neighbor lists land in the
        ELL layout with per-row ids sorted ascending (the no-collision
        scatter invariant ``swing_score_fn`` relies on) and padding slots
        id 0 / value 0 (exact-identity adds)."""
        items = np.asarray(df.column(item_col), np.int64)
        ids_col, scores_col = f"{output_col}_ids", f"{output_col}_scores"
        names = set(df.column_names)
        neighbors = []  # per source item: (neighbor ids int64, scores f64)
        if ids_col in names and scores_col in names:
            nid_mat = np.asarray(df.column(ids_col), np.int64)
            sc_mat = np.asarray(df.column(scores_col), np.float64)
            for nid, sc in zip(nid_mat, sc_mat):
                keep = (nid >= 0) & (sc > 0.0)
                neighbors.append((nid[keep], sc[keep]))
        else:
            for enc in df.column(output_col):
                pairs = [p.split(",") for p in str(enc).split(";") if p]
                neighbors.append(
                    (
                        np.asarray([int(i) for i, _ in pairs], np.int64),
                        np.asarray([float(s) for _, s in pairs], np.float64),
                    )
                )
        vocab = np.unique(
            np.concatenate([items] + [nid for nid, _ in neighbors])
            if len(items)
            else np.empty(0, np.int64)
        )
        if vocab.size == 0:
            raise ValueError("empty Swing output — nothing to index")
        C = int(vocab.size)
        M = max(1, max((len(nid) for nid, _ in neighbors), default=1))
        sim_ids = np.zeros((C, M), np.int32)
        sim_values = np.zeros((C, M), np.float32)
        row_of = {int(v): r for r, v in enumerate(vocab)}
        for item, (nid, sc) in zip(items, neighbors):
            r = row_of[int(item)]
            rows = np.asarray([row_of[int(i)] for i in nid], np.int32)
            order = np.argsort(rows, kind="stable")  # sorted-unique per slot
            sim_ids[r, : len(rows)] = rows[order]
            sim_values[r, : len(rows)] = sc[order]
        index = cls(
            {"item_ids": vocab, "sim_values": sim_values, "sim_ids": sim_ids}
        )
        index.set_index_kind(KIND_SWING)
        for name, value in params.items():
            index.set(index.get_param(name), value)
        return index

    @classmethod
    def from_lsh_model(
        cls,
        model,
        df: DataFrame,
        *,
        id_col: str,
        vector_col: Optional[str] = None,
        **params,
    ) -> "CandidateIndex":
        """Index a candidate dataset under a fitted MinHashLSH model's hash
        family. ``model`` is duck-typed (``coeff_a``/``coeff_b`` +
        ``get_num_hash_tables``/``get_num_hash_functions_per_table``/
        ``get_input_col``) so this module never imports the training stack.
        Candidate hash values are computed host-exact (int64) and stored as
        the hi/lo f32 lane split alongside each candidate's exact index set
        (the two phases of ``lsh_topk_fn``)."""
        vector_col = vector_col or model.get_input_col()
        sets = index_sets(df.column(vector_col))
        coeff_a = np.asarray(model.coeff_a, np.int64)
        coeff_b = np.asarray(model.coeff_b, np.int64)
        cand_lanes = minhash_lanes(sets, coeff_a, coeff_b)
        C = len(sets)
        if C == 0:
            raise ValueError("empty candidate dataset — nothing to index")
        M = max(1, max((len(s) for s in sets), default=1))
        cand_ids = np.zeros((C, M), np.int32)
        cand_nnz = np.zeros(C, np.int32)
        for r, s in enumerate(sets):
            cand_ids[r, : len(s)] = s
            cand_nnz[r] = len(s)
        index = cls(
            {
                "item_ids": np.asarray(df.column(id_col), np.int64),
                "cand_lanes": cand_lanes,
                "cand_ids": cand_ids,
                "cand_nnz": cand_nnz,
                "coeff_a": coeff_a,
                "coeff_b": coeff_b,
            }
        )
        index.set_index_kind(KIND_LSH)
        index.set_input_col(vector_col)
        index.set_num_hash_tables(model.get_num_hash_tables())
        index.set_num_hash_functions_per_table(
            model.get_num_hash_functions_per_table()
        )
        for name, value in params.items():
            index.set(index.get_param(name), value)
        return index
