"""Param / WithParams / ParamValidators.

Reference semantics preserved (flink-ml-servable-core/.../param/):
  - ``Param`` is a typed descriptor {name, description, default, validator} that can
    JSON-encode/decode its value (Param.java).
  - ``WithParams`` stages hold a param_map; ``get`` falls back to the default;
    ``set`` validates (WithParams.java default methods).
  - Params are declared as *class attributes* on stages/mixins; ``get_param_map``
    discovers them by walking the MRO (the analogue of the reference's reflection
    over public static Param fields, ParamUtils.java).
  - Validators mirror ParamValidators.java (gt, gtEq, lt, ltEq, inRange, inArray,
    notNull, nonEmptyArray, isSubSet).
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Generic, List, Optional, Sequence, TypeVar

import numpy as np

from flink_ml_tpu.linalg.vectors import DenseVector, SparseVector, Vector

T = TypeVar("T")

__all__ = [
    "Param",
    "ParamValidators",
    "WithParams",
    "BoolParam",
    "IntParam",
    "FloatParam",
    "StringParam",
    "ArrayParam",
    "IntArrayParam",
    "FloatArrayParam",
    "StringArrayParam",
    "VectorParam",
]


class ParamValidators:
    """Factory of validation predicates. Ref ParamValidators.java."""

    @staticmethod
    def always_true() -> Callable[[Any], bool]:
        return lambda v: True

    @staticmethod
    def gt(lower) -> Callable[[Any], bool]:
        return lambda v: v is not None and v > lower

    @staticmethod
    def gt_eq(lower) -> Callable[[Any], bool]:
        return lambda v: v is not None and v >= lower

    @staticmethod
    def lt(upper) -> Callable[[Any], bool]:
        return lambda v: v is not None and v < upper

    @staticmethod
    def lt_eq(upper) -> Callable[[Any], bool]:
        return lambda v: v is not None and v <= upper

    @staticmethod
    def in_range(lower, upper, lower_inclusive=True, upper_inclusive=True) -> Callable[[Any], bool]:
        def check(v):
            if v is None:
                return False
            ok_low = v >= lower if lower_inclusive else v > lower
            ok_up = v <= upper if upper_inclusive else v < upper
            return ok_low and ok_up

        return check

    @staticmethod
    def in_array(allowed: Sequence[Any]) -> Callable[[Any], bool]:
        allowed = list(allowed)
        return lambda v: v in allowed

    @staticmethod
    def not_null() -> Callable[[Any], bool]:
        return lambda v: v is not None

    @staticmethod
    def non_empty_array() -> Callable[[Any], bool]:
        return lambda v: v is not None and len(v) > 0

    @staticmethod
    def is_sub_set(allowed: Sequence[Any]) -> Callable[[Any], bool]:
        allowed_set = set(allowed)
        return lambda v: v is not None and set(v) <= allowed_set


class Param(Generic[T]):
    """Definition of a parameter. Ref Param.java."""

    def __init__(
        self,
        name: str,
        description: str,
        default_value: Optional[T] = None,
        validator: Callable[[Any], bool] = None,
    ):
        self.name = name
        self.description = description
        self.validator = validator or ParamValidators.always_true()
        if default_value is not None and not self.validator(default_value):
            raise ValueError(f"Invalid default value {default_value!r} for param {name}")
        self.default_value = default_value

    # JSON round-trip. Ref Param.jsonEncode/jsonDecode.
    def json_encode(self, value: T) -> Any:
        return _json_encode_value(value)

    def json_decode(self, payload: Any) -> T:
        return _json_decode_value(payload)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.name!r})"


class BoolParam(Param[bool]):
    pass


class IntParam(Param[int]):
    pass


class FloatParam(Param[float]):
    def json_decode(self, payload):
        return None if payload is None else float(payload)


class StringParam(Param[str]):
    pass


class ArrayParam(Param[list]):
    def json_decode(self, payload):
        return None if payload is None else list(payload)


class IntArrayParam(ArrayParam):
    pass


class FloatArrayParam(ArrayParam):
    def json_decode(self, payload):
        return None if payload is None else [float(v) for v in payload]


class StringArrayParam(ArrayParam):
    pass


class VectorParam(Param[Vector]):
    def json_decode(self, payload):
        # Accept the reference's jackson shapes too: a bare {"values": ...}
        # ({"size", "indices", "values"} for sparse) or a plain list — its
        # benchmark configs carry vector params that way.
        if isinstance(payload, dict) and "__type__" not in payload:
            if "indices" in payload:
                missing = {"size", "indices", "values"} - payload.keys()
                if missing:
                    raise ValueError(
                        f"sparse vector param {self.name!r} needs keys "
                        f"size/indices/values; missing {sorted(missing)}"
                    )
                return SparseVector(payload["size"], payload["indices"], payload["values"])
            if "values" in payload:
                return DenseVector(payload["values"])
        if isinstance(payload, (list, tuple)):
            return DenseVector(payload)
        return _json_decode_value(payload)


def _json_encode_value(value: Any) -> Any:
    if isinstance(value, DenseVector):
        return {"__type__": "DenseVector", "values": value.values.tolist()}
    if isinstance(value, SparseVector):
        return {
            "__type__": "SparseVector",
            "size": value.n,
            "indices": value.indices.tolist(),
            "values": value.values.tolist(),
        }
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    if isinstance(value, (list, tuple)):
        return [_json_encode_value(v) for v in value]
    if hasattr(value, "to_json_dict"):  # window descriptors etc.
        return value.to_json_dict()
    return value


def _json_decode_value(payload: Any) -> Any:
    if isinstance(payload, dict) and "__type__" in payload:
        t = payload["__type__"]
        if t == "DenseVector":
            return DenseVector(payload["values"])
        if t == "SparseVector":
            return SparseVector(payload["size"], payload["indices"], payload["values"])
        from flink_ml_tpu.ops.windows import Windows  # late import, avoids cycle

        decoded = Windows.from_json_dict(payload)
        if decoded is not None:
            return decoded
    if isinstance(payload, list):
        return [_json_decode_value(v) for v in payload]
    return payload


class WithParams:
    """Mixin giving a stage typed, validated, JSON-serializable params.

    Ref WithParams.java — the default get/set via getParamMap, plus the reflection-based
    param discovery from ParamUtils.java, realized here as an MRO walk over class
    attributes of type ``Param``.
    """

    def __init__(self, **kwargs):
        self._param_map: Dict[Param, Any] = {}
        for p in self._declared_params():
            self._param_map[p] = copy.deepcopy(p.default_value)
        for name, value in kwargs.items():
            self.set(self._param_by_name(name), value)

    @classmethod
    def _declared_params(cls) -> List[Param]:
        seen: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for attr in vars(klass).values():
                if isinstance(attr, Param):
                    seen[attr.name] = attr
        return list(seen.values())

    def _param_by_name(self, name: str) -> Param:
        for p in self._param_map:
            if p.name == name:
                return p
        raise KeyError(f"Stage {type(self).__name__} has no param named {name!r}")

    def get_param(self, name: str) -> Param:
        """Ref WithParams.getParam(String)."""
        return self._param_by_name(name)

    def get(self, param: Param) -> Any:
        if param not in self._param_map:
            raise KeyError(f"Param {param.name} is not defined on {type(self).__name__}")
        return self._param_map[param]

    def set(self, param: Param, value: Any) -> "WithParams":
        if param not in self._param_map:
            raise KeyError(f"Param {param.name} is not defined on {type(self).__name__}")
        if not param.validator(value):
            # Ref WithParams.java set(): the validator always runs, including on null.
            if value is None:
                raise ValueError(f"Parameter {param.name}'s value should not be null")
            raise ValueError(f"Parameter {param.name} is given an invalid value {value!r}")
        self._param_map[param] = value
        return self

    def get_param_map(self) -> Dict[Param, Any]:
        """Ref WithParams.getParamMap."""
        return self._param_map

    # --- persistence helpers --------------------------------------------------
    def param_map_to_json(self) -> Dict[str, Any]:
        return {p.name: p.json_encode(v) for p, v in self._param_map.items()}

    def load_param_map_from_json(self, payload: Dict[str, Any]) -> None:
        for name, encoded in payload.items():
            p = self._param_by_name(name)
            self._param_map[p] = p.json_decode(encoded)


def update_existing_params(target: WithParams, source: WithParams) -> None:
    """Copy every param value from ``source`` that ``target`` also declares.

    Ref ParamUtils.updateExistingParams — how an Estimator pushes its params onto the
    Model it produces (e.g. KMeans.fit → KMeansModel). Goes through ``target.set``
    so the target's validators run, and deep-copies so mutable values (arrays,
    vectors) are never aliased between source and target."""
    by_name = {p.name: v for p, v in source.get_param_map().items()}
    for p in list(target.get_param_map()):
        if p.name in by_name:
            target.set(p, copy.deepcopy(by_name[p.name]))
