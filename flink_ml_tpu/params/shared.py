"""Shared ``HasXxx`` param mixins.

Reference: flink-ml-servable-lib/src/main/java/org/apache/flink/ml/common/param/
(27 mixin interfaces: HasFeaturesCol, HasLabelCol, HasPredictionCol, ...). Each mixin
declares one Param as a class attribute plus typed accessors, and stages compose
capabilities by multiple inheritance — exactly the reference's interface-default-method
pattern.
"""
from __future__ import annotations

from flink_ml_tpu.params.param import (
    BoolParam,
    FloatParam,
    IntParam,
    Param,
    ParamValidators,
    StringArrayParam,
    StringParam,
    WithParams,
)

__all__ = [
    "HasFeaturesCol",
    "HasLabelCol",
    "HasWeightCol",
    "HasPredictionCol",
    "HasRawPredictionCol",
    "HasInputCol",
    "HasOutputCol",
    "HasInputCols",
    "HasOutputCols",
    "HasMaxIter",
    "HasTol",
    "HasLearningRate",
    "HasGlobalBatchSize",
    "HasReg",
    "HasElasticNet",
    "HasSeed",
    "HasDistanceMeasure",
    "HasK",
    "HasHandleInvalid",
    "HasBatchStrategy",
    "HasMultiClass",
    "HasCategoricalCols",
    "HasDecayFactor",
    "HasModelVersionCol",
    "HasMaxAllowedModelDelayMs",
    "HasWindows",
    "HasFlatten",
    "HasRelativeError",
    "HasNumFeatures",
]


class HasFeaturesCol(WithParams):
    FEATURES_COL = StringParam("featuresCol", "Features column name.", "features", ParamValidators.not_null())

    def get_features_col(self) -> str:
        return self.get(self.FEATURES_COL)

    def set_features_col(self, value: str):
        return self.set(self.FEATURES_COL, value)


class HasLabelCol(WithParams):
    LABEL_COL = StringParam("labelCol", "Label column name.", "label", ParamValidators.not_null())

    def get_label_col(self) -> str:
        return self.get(self.LABEL_COL)

    def set_label_col(self, value: str):
        return self.set(self.LABEL_COL, value)


class HasWeightCol(WithParams):
    WEIGHT_COL = StringParam("weightCol", "Weight column name.", None)

    def get_weight_col(self) -> str:
        return self.get(self.WEIGHT_COL)

    def set_weight_col(self, value: str):
        return self.set(self.WEIGHT_COL, value)


class HasPredictionCol(WithParams):
    PREDICTION_COL = StringParam("predictionCol", "Prediction column name.", "prediction", ParamValidators.not_null())

    def get_prediction_col(self) -> str:
        return self.get(self.PREDICTION_COL)

    def set_prediction_col(self, value: str):
        return self.set(self.PREDICTION_COL, value)


class HasRawPredictionCol(WithParams):
    RAW_PREDICTION_COL = StringParam("rawPredictionCol", "Raw prediction column name.", "rawPrediction")

    def get_raw_prediction_col(self) -> str:
        return self.get(self.RAW_PREDICTION_COL)

    def set_raw_prediction_col(self, value: str):
        return self.set(self.RAW_PREDICTION_COL, value)


class HasInputCol(WithParams):
    INPUT_COL = StringParam("inputCol", "Input column name.", "input", ParamValidators.not_null())

    def get_input_col(self) -> str:
        return self.get(self.INPUT_COL)

    def set_input_col(self, value: str):
        return self.set(self.INPUT_COL, value)


class HasOutputCol(WithParams):
    OUTPUT_COL = StringParam("outputCol", "Output column name.", "output", ParamValidators.not_null())

    def get_output_col(self) -> str:
        return self.get(self.OUTPUT_COL)

    def set_output_col(self, value: str):
        return self.set(self.OUTPUT_COL, value)


class HasInputCols(WithParams):
    INPUT_COLS = StringArrayParam("inputCols", "Input column names.", None, ParamValidators.non_empty_array())

    def get_input_cols(self):
        return self.get(self.INPUT_COLS)

    def set_input_cols(self, *value: str):
        return self.set(self.INPUT_COLS, list(value))


class HasOutputCols(WithParams):
    OUTPUT_COLS = StringArrayParam("outputCols", "Output column names.", None, ParamValidators.non_empty_array())

    def get_output_cols(self):
        return self.get(self.OUTPUT_COLS)

    def set_output_cols(self, *value: str):
        return self.set(self.OUTPUT_COLS, list(value))


class HasMaxIter(WithParams):
    MAX_ITER = IntParam("maxIter", "Maximum number of iterations.", 20, ParamValidators.gt(0))

    def get_max_iter(self) -> int:
        return self.get(self.MAX_ITER)

    def set_max_iter(self, value: int):
        return self.set(self.MAX_ITER, value)


class HasTol(WithParams):
    TOL = FloatParam("tol", "Convergence tolerance for iterative algorithms.", 1e-6, ParamValidators.gt_eq(0))

    def get_tol(self) -> float:
        return self.get(self.TOL)

    def set_tol(self, value: float):
        return self.set(self.TOL, value)


class HasLearningRate(WithParams):
    LEARNING_RATE = FloatParam("learningRate", "Learning rate of optimization method.", 0.1, ParamValidators.gt(0))

    def get_learning_rate(self) -> float:
        return self.get(self.LEARNING_RATE)

    def set_learning_rate(self, value: float):
        return self.set(self.LEARNING_RATE, value)


class HasGlobalBatchSize(WithParams):
    GLOBAL_BATCH_SIZE = IntParam("globalBatchSize", "Global batch size of training algorithms.", 32, ParamValidators.gt(0))

    def get_global_batch_size(self) -> int:
        return self.get(self.GLOBAL_BATCH_SIZE)

    def set_global_batch_size(self, value: int):
        return self.set(self.GLOBAL_BATCH_SIZE, value)


class HasReg(WithParams):
    REG = FloatParam("reg", "Regularization parameter.", 0.0, ParamValidators.gt_eq(0))

    def get_reg(self) -> float:
        return self.get(self.REG)

    def set_reg(self, value: float):
        return self.set(self.REG, value)


class HasElasticNet(WithParams):
    ELASTIC_NET = FloatParam(
        "elasticNet", "ElasticNet parameter (0 = L2, 1 = L1).", 0.0, ParamValidators.in_range(0.0, 1.0)
    )

    def get_elastic_net(self) -> float:
        return self.get(self.ELASTIC_NET)

    def set_elastic_net(self, value: float):
        return self.set(self.ELASTIC_NET, value)


class HasSeed(WithParams):
    SEED = IntParam("seed", "The random seed.", None)

    def get_seed(self) -> int:
        v = self.get(self.SEED)
        return 0 if v is None else v

    def set_seed(self, value: int):
        return self.set(self.SEED, value)


class HasDistanceMeasure(WithParams):
    DISTANCE_MEASURE = StringParam(
        "distanceMeasure",
        "Distance measure. Supported: euclidean, manhattan, cosine.",
        "euclidean",
        ParamValidators.in_array(["euclidean", "manhattan", "cosine"]),
    )

    def get_distance_measure(self) -> str:
        return self.get(self.DISTANCE_MEASURE)

    def set_distance_measure(self, value: str):
        return self.set(self.DISTANCE_MEASURE, value)


class HasK(WithParams):
    """Ref KMeansModelParams.K — number of clusters, default 2. Lives here (not
    clustering/kmeans.py) so the runtime-free KMeansModelServable can declare it
    without importing the training stack."""

    K = IntParam("k", "The max number of clusters to create.", 2, ParamValidators.gt(1))

    def get_k(self) -> int:
        return self.get(self.K)

    def set_k(self, value: int):
        return self.set(self.K, value)


class HasHandleInvalid(WithParams):
    ERROR_INVALID = "error"
    SKIP_INVALID = "skip"
    KEEP_INVALID = "keep"

    HANDLE_INVALID = StringParam(
        "handleInvalid",
        "Strategy to handle invalid entries.",
        "error",
        ParamValidators.in_array(["error", "skip", "keep"]),
    )

    def get_handle_invalid(self) -> str:
        return self.get(self.HANDLE_INVALID)

    def set_handle_invalid(self, value: str):
        return self.set(self.HANDLE_INVALID, value)


class HasBatchStrategy(WithParams):
    COUNT_STRATEGY = "count"

    BATCH_STRATEGY = StringParam(
        "batchStrategy", "Strategy to create mini batches from input data.", "count", ParamValidators.in_array(["count"])
    )

    def get_batch_strategy(self) -> str:
        return self.get(self.BATCH_STRATEGY)


class HasMultiClass(WithParams):
    MULTI_CLASS = StringParam(
        "multiClass",
        "Classification type.",
        "auto",
        ParamValidators.in_array(["auto", "binomial", "multinomial"]),
    )

    def get_multi_class(self) -> str:
        return self.get(self.MULTI_CLASS)

    def set_multi_class(self, value: str):
        return self.set(self.MULTI_CLASS, value)


class HasCategoricalCols(WithParams):
    CATEGORICAL_COLS = StringArrayParam("categoricalCols", "Categorical column names.", [])

    def get_categorical_cols(self):
        return self.get(self.CATEGORICAL_COLS)

    def set_categorical_cols(self, *value: str):
        return self.set(self.CATEGORICAL_COLS, list(value))


class HasDecayFactor(WithParams):
    DECAY_FACTOR = FloatParam(
        "decayFactor",
        "The forgetfulness of the previous centroids.",
        0.0,
        ParamValidators.in_range(0, 1),
    )

    def get_decay_factor(self) -> float:
        return self.get(self.DECAY_FACTOR)

    def set_decay_factor(self, value: float):
        return self.set(self.DECAY_FACTOR, value)


class HasModelVersionCol(WithParams):
    MODEL_VERSION_COL = StringParam("modelVersionCol", "Column which contains the version of the model data.", "version")

    def get_model_version_col(self) -> str:
        return self.get(self.MODEL_VERSION_COL)

    def set_model_version_col(self, value: str):
        return self.set(self.MODEL_VERSION_COL, value)


class HasMaxAllowedModelDelayMs(WithParams):
    MAX_ALLOWED_MODEL_DELAY_MS = IntParam(
        "maxAllowedModelDelayMs",
        "Max difference in ms between data timestamp and model timestamp at prediction.",
        0,
        ParamValidators.gt_eq(0),
    )

    def get_max_allowed_model_delay_ms(self) -> int:
        return self.get(self.MAX_ALLOWED_MODEL_DELAY_MS)

    def set_max_allowed_model_delay_ms(self, value: int):
        return self.set(self.MAX_ALLOWED_MODEL_DELAY_MS, value)


class HasWindows(WithParams):
    from flink_ml_tpu.ops.windows import GlobalWindows as _GW

    WINDOWS = Param("windows", "Windowing strategy that determines how to create mini-batches.", _GW())

    def get_windows(self):
        return self.get(self.WINDOWS)

    def set_windows(self, value):
        return self.set(self.WINDOWS, value)


class HasFlatten(WithParams):
    FLATTEN = BoolParam("flatten", "If false, output is a single row; if true, one row per element.", False)

    def get_flatten(self) -> bool:
        return self.get(self.FLATTEN)

    def set_flatten(self, value: bool):
        return self.set(self.FLATTEN, value)


class HasRelativeError(WithParams):
    RELATIVE_ERROR = FloatParam(
        "relativeError", "Relative target precision for approximate quantiles.", 0.001, ParamValidators.in_range(0.0, 1.0)
    )

    def get_relative_error(self) -> float:
        return self.get(self.RELATIVE_ERROR)

    def set_relative_error(self, value: float):
        return self.set(self.RELATIVE_ERROR, value)


class HasNumFeatures(WithParams):
    NUM_FEATURES = IntParam("numFeatures", "Number of features.", 262144, ParamValidators.gt(0))

    def get_num_features(self) -> int:
        return self.get(self.NUM_FEATURES)

    def set_num_features(self, value: int):
        return self.set(self.NUM_FEATURES, value)
