"""Typed parameter system with validation and JSON round-trip.

Reference: flink-ml-servable-core/src/main/java/org/apache/flink/ml/param/
(Param.java, WithParams.java, ParamValidators.java, 18 typed Param subclasses) and the
shared ``HasXxx`` mixin interfaces under flink-ml-servable-lib/.../common/param/.
"""

from flink_ml_tpu.params.param import (
    ArrayParam,
    BoolParam,
    FloatArrayParam,
    FloatParam,
    IntArrayParam,
    IntParam,
    Param,
    ParamValidators,
    StringArrayParam,
    StringParam,
    VectorParam,
    WithParams,
)
from flink_ml_tpu.params import shared

__all__ = [
    "ArrayParam",
    "BoolParam",
    "FloatArrayParam",
    "FloatParam",
    "IntArrayParam",
    "IntParam",
    "Param",
    "ParamValidators",
    "StringArrayParam",
    "StringParam",
    "VectorParam",
    "WithParams",
    "shared",
]
