"""Training-state checkpointing: snapshot/restore of iteration variables.

Reference (SURVEY.md §5.3/§5.4): Flink checkpoints every operator's training state —
SGD snapshots coefficient, feedback array and batch offset (SGD.java:308-363), the
iteration runtime snapshots in-flight feedback records (checkpoint/Checkpoints.java)
and aligns barriers between coordinator and feedback channel
(HeadOperatorCheckpointAligner.java:38-80). On restart the job resumes from the last
completed snapshot and converges to the same result
(BoundedAllRoundCheckpointITCase).

TPU-native collapse: the single controller means there are no in-flight records and
no barrier alignment — a checkpoint is exactly the iteration variables (device
arrays) plus the epoch counter, taken between epochs. ``CheckpointManager`` writes
them atomically (tmp dir + rename), keeps the newest ``max_to_keep``, and restores
the latest complete snapshot. The iteration drivers call ``save``/``restore_latest``
via ``IterationConfig.checkpoint_manager`` (iteration.py), giving every algorithm
built on ``iterate_*`` kill/resume for free — the fault-recovery contract the
reference gets from Flink restart strategies.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

__all__ = ["CheckpointManager"]

_STEP_PREFIX = "ckpt-"


class CheckpointManager:
    """Numbered atomic snapshots of a pytree of arrays under ``directory``.

    ``fingerprint`` is a run/config identity string (hash of hyperparameters +
    data shape, typically set by the algorithm via ``set_fingerprint``). It is
    recorded in each snapshot's META.json; ``restore_latest`` refuses a snapshot
    whose fingerprint differs — pointing a *different* job at an existing
    directory raises instead of silently resuming stale state.
    """

    def __init__(self, directory: str, max_to_keep: int = 2, fingerprint: Optional[str] = None):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.fingerprint = fingerprint
        self._user_pinned = fingerprint is not None
        os.makedirs(directory, exist_ok=True)

    def set_fingerprint(self, fingerprint: str) -> None:
        """Install the run identity computed by an algorithm.

        A fingerprint pinned explicitly at construction wins; an auto-installed
        one is *overwritten* on each call, so reusing one manager across
        differently-configured runs still trips the stale-resume guard.
        """
        if not self._user_pinned:
            self.fingerprint = fingerprint

    # --- write ---------------------------------------------------------------
    def save(self, step: int, state: Any) -> str:
        """Snapshot ``state`` (pytree of arrays/scalars) as checkpoint ``step``.

        Device arrays are fetched to host; the write is atomic (tmp + rename), so a
        kill mid-save can never leave a half checkpoint that ``restore_latest``
        would pick up — the moral of the reference's barrier-aligned snapshots.
        """
        leaves, treedef = jax.tree_util.tree_flatten(state)
        host_leaves = [np.asarray(jax.device_get(leaf)) for leaf in leaves]
        final_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step}")
        tmp_dir = final_dir + ".tmp"
        if os.path.exists(tmp_dir):
            shutil.rmtree(tmp_dir)
        os.makedirs(tmp_dir)
        np.savez(
            os.path.join(tmp_dir, "arrays.npz"),
            **{f"leaf_{i}": leaf for i, leaf in enumerate(host_leaves)},
        )
        with open(os.path.join(tmp_dir, "treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)
        with open(os.path.join(tmp_dir, "META.json"), "w") as f:
            json.dump(
                {
                    "step": step,
                    "num_leaves": len(host_leaves),
                    "fingerprint": self.fingerprint,
                },
                f,
            )
        if os.path.exists(final_dir):
            shutil.rmtree(final_dir)
        os.rename(tmp_dir, final_dir)
        self._prune()
        return final_dir

    # --- read ----------------------------------------------------------------
    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX) and not name.endswith(".tmp"):
                if os.path.exists(os.path.join(self.directory, name, "META.json")):
                    steps.append(int(name[len(_STEP_PREFIX) :]))
        return sorted(steps)

    def restore(self, step: int) -> Any:
        ckpt_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step}")
        with open(os.path.join(ckpt_dir, "treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        with np.load(os.path.join(ckpt_dir, "arrays.npz")) as z:
            leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def restore_latest(self) -> Optional[Tuple[int, Any]]:
        """(step, state) of the newest complete snapshot, or None.

        The signature the iteration drivers expect (iteration._maybe_restore).
        """
        steps = self.all_steps()
        if not steps:
            return None
        step = steps[-1]
        with open(os.path.join(self.directory, f"{_STEP_PREFIX}{step}", "META.json")) as f:
            meta = json.load(f)
        saved = meta.get("fingerprint")
        if saved is not None and self.fingerprint is not None and saved != self.fingerprint:
            raise ValueError(
                f"checkpoint directory {self.directory!r} holds snapshots of a different "
                f"run (fingerprint {saved!r} != {self.fingerprint!r}); point this job at "
                "a fresh directory or delete the stale checkpoints"
            )
        return step, self.restore(step)

    def _prune(self) -> None:
        steps = self.all_steps()
        for step in steps[: -self.max_to_keep] if self.max_to_keep else []:
            shutil.rmtree(os.path.join(self.directory, f"{_STEP_PREFIX}{step}"))
