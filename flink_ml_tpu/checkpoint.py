"""Training-state checkpointing: snapshot/restore of iteration variables.

Reference (SURVEY.md §5.3/§5.4): Flink checkpoints every operator's training state —
SGD snapshots coefficient, feedback array and batch offset (SGD.java:308-363), the
iteration runtime snapshots in-flight feedback records (checkpoint/Checkpoints.java)
and aligns barriers between coordinator and feedback channel
(HeadOperatorCheckpointAligner.java:38-80). On restart the job resumes from the last
completed snapshot and converges to the same result
(BoundedAllRoundCheckpointITCase).

TPU-native collapse: the single controller means there are no in-flight records and
no barrier alignment — a checkpoint is exactly the iteration variables (device
arrays) plus the epoch counter, taken between epochs. ``CheckpointManager`` writes
them atomically (tmp dir + fsync + rename), keeps the newest ``max_to_keep``, and
restores the latest *intact* snapshot.

Corruption tolerance (the supervised-execution contract, docs/fault_tolerance.md):
every leaf carries a CRC32 in META.json; ``restore_latest`` verifies, quarantines a
corrupt snapshot as ``ckpt-N.corrupt`` and falls back to the newest older intact one
instead of crashing — the failover the reference gets from replicated JobManager
checkpoint stores. A missing/truncated snapshot surfaces as the typed
``CheckpointCorruptError`` (step + path attached) so the supervisor's error
classifier can route it; a fingerprint mismatch is the typed — and fatal —
``FingerprintMismatchError``. The iteration drivers call ``save``/``restore_latest``
via ``IterationConfig.checkpoint_manager`` (iteration.py), giving every algorithm
built on ``iterate_*`` kill/resume for free.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import zlib
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from flink_ml_tpu.faults import faults
from flink_ml_tpu.metrics import MLMetrics, metrics

__all__ = [
    "CheckpointManager",
    "ShardedCheckpointManager",
    "CheckpointCorruptError",
    "FingerprintMismatchError",
    "MeshMismatchError",
    "scan_numbered_dirs",
]

_STEP_PREFIX = "ckpt-"
_CORRUPT_SUFFIX = ".corrupt"


def scan_numbered_dirs(directory: str, prefix: str = _STEP_PREFIX,
                       marker_file: str = "META.json") -> List[int]:
    """Numbers of the (apparently) complete ``<prefix><int>`` dirs, ascending.

    The hardened listing contract shared by checkpoint restore and the serving
    ``ModelVersionPoller``: anything whose name does not parse as
    ``<prefix><int>`` — quarantined ``.corrupt`` dirs, in-flight ``.tmp`` dirs,
    stray files — is skipped rather than crashing the listing, and a dir
    missing its ``marker_file`` (written last in every atomic-publish protocol
    here) is treated as incomplete.
    """
    numbers = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    for name in names:
        if not name.startswith(prefix):
            continue
        try:
            number = int(name[len(prefix):])
        except ValueError:
            continue
        if os.path.exists(os.path.join(directory, name, marker_file)):
            numbers.append(number)
    return sorted(numbers)


class CheckpointCorruptError(RuntimeError):
    """A snapshot is missing, truncated, or fails checksum verification.

    Carries ``step`` and ``path`` so the supervisor's error classifier
    (execution/classify.py) can route it and logs can point at the bad dir.
    """

    def __init__(self, step: int, path: str, reason: str):
        self.step = step
        self.path = path
        self.reason = reason
        super().__init__(f"checkpoint step {step} at {path!r} is corrupt: {reason}")


class FingerprintMismatchError(ValueError):
    """A directory holds snapshots of a *different* run/config.

    Subclasses ValueError for backward compatibility with callers matching the
    legacy message; classified FATAL by the supervisor — restarting cannot fix
    a job pointed at the wrong checkpoint directory.
    """


class MeshMismatchError(ValueError):
    """A snapshot with per-shard leaves was saved on a different mesh shape.

    Fatal like ``FingerprintMismatchError`` (and for the same reason): falling
    back to an older snapshot cannot fix a job resuming sharded training state
    onto an incompatible mesh — the operator must restart on the saved mesh
    shape or point the job at a fresh directory. Snapshots whose leaves are
    all replicated never raise this: they restore on any mesh.
    """


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _crc(arr) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


class CheckpointManager:
    """Numbered atomic snapshots of a pytree of arrays under ``directory``.

    ``fingerprint`` is a run/config identity string (hash of hyperparameters +
    data shape, typically set by the algorithm via ``set_fingerprint``). It is
    recorded in each snapshot's META.json; ``restore_latest`` refuses a snapshot
    whose fingerprint differs — pointing a *different* job at an existing
    directory raises instead of silently resuming stale state.
    """

    def __init__(self, directory: str, max_to_keep: int = 2, fingerprint: Optional[str] = None):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.fingerprint = fingerprint
        self._user_pinned = fingerprint is not None
        os.makedirs(directory, exist_ok=True)
        self._sweep_orphan_tmp()

    def _sweep_orphan_tmp(self) -> None:
        """Reclaim ``ckpt-N.tmp`` left by a kill mid-save.

        They are invisible to ``all_steps`` (never restored) but would
        otherwise accumulate forever; manager construction is the natural
        recovery point — any tmp dir found here is by definition from a dead
        incarnation, never from a concurrent save.
        """
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX) and name.endswith(".tmp"):
                path = os.path.join(self.directory, name)
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                else:
                    try:
                        os.remove(path)
                    except OSError:
                        continue
                metrics.counter(MLMetrics.CHECKPOINT_GROUP, MLMetrics.CHECKPOINT_TMP_SWEPT)

    def set_fingerprint(self, fingerprint: str) -> None:
        """Install the run identity computed by an algorithm.

        A fingerprint pinned explicitly at construction wins; an auto-installed
        one is *overwritten* on each call, so reusing one manager across
        differently-configured runs still trips the stale-resume guard.
        """
        if not self._user_pinned:
            self.fingerprint = fingerprint

    # --- write ---------------------------------------------------------------
    def save(self, step: int, state: Any) -> str:
        """Snapshot ``state`` (pytree of arrays/scalars) as checkpoint ``step``.

        Device arrays are fetched to host; the write is atomic and durable
        (tmp dir + per-file fsync + rename + dir fsync), so a kill — or power
        loss — mid-save can never leave a half checkpoint that
        ``restore_latest`` would pick up. Each leaf's CRC32 is recorded in
        META.json for read-time verification.
        """
        faults.trip("checkpoint.save", step=step)
        leaves, treedef = jax.tree_util.tree_flatten(state)
        host_leaves = [np.asarray(jax.device_get(leaf)) for leaf in leaves]
        meta = {
            "step": step,
            "num_leaves": len(host_leaves),
            "fingerprint": self.fingerprint,
            "crc32s": [_crc(leaf) for leaf in host_leaves],
        }
        return self._write_snapshot(
            step, {f"leaf_{i}": leaf for i, leaf in enumerate(host_leaves)},
            treedef, meta,
        )

    def _write_snapshot(self, step: int, entries: dict, treedef, meta: dict) -> str:
        """The atomic + durable write every snapshot layout shares (flat
        leaves here, per-shard pieces in ``ShardedCheckpointManager``):
        tmp dir + per-file fsync + rename + dir fsync, META.json last."""
        final_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step}")
        tmp_dir = final_dir + ".tmp"
        if os.path.exists(tmp_dir):
            shutil.rmtree(tmp_dir)
        os.makedirs(tmp_dir)
        np.savez(os.path.join(tmp_dir, "arrays.npz"), **entries)
        with open(os.path.join(tmp_dir, "treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)
            f.flush()
            os.fsync(f.fileno())
        with open(os.path.join(tmp_dir, "META.json"), "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_path(os.path.join(tmp_dir, "arrays.npz"))
        _fsync_path(tmp_dir)
        if os.path.exists(final_dir):
            shutil.rmtree(final_dir)
        os.rename(tmp_dir, final_dir)
        _fsync_path(self.directory)
        self._prune()
        return final_dir

    # --- read ----------------------------------------------------------------
    def all_steps(self) -> List[int]:
        """Steps of the (apparently) complete snapshots, ascending.

        Anything whose name does not parse as ``ckpt-<int>`` — quarantined
        ``ckpt-N.corrupt`` dirs, in-flight ``.tmp`` dirs, stray files — is
        skipped rather than crashing the listing (``scan_numbered_dirs``).
        """
        return scan_numbered_dirs(self.directory)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step}")

    def _read_meta(self, step: int) -> dict:
        ckpt_dir = self._step_dir(step)
        try:
            with open(os.path.join(ckpt_dir, "META.json")) as f:
                return json.load(f)
        except (OSError, ValueError) as e:  # missing, truncated, bad JSON
            raise CheckpointCorruptError(step, ckpt_dir, f"META.json unreadable: {e!r}")

    def restore(self, step: int) -> Any:
        """Load and verify snapshot ``step``.

        Any unreadable, truncated, or checksum-failing snapshot raises the
        typed ``CheckpointCorruptError`` (never a bare FileNotFoundError/
        KeyError/BadZipFile) so callers — and the supervisor's error
        classifier — have one failure type to route.
        """
        ckpt_dir = self._step_dir(step)
        meta = self._read_meta(step)
        try:
            with open(os.path.join(ckpt_dir, "treedef.pkl"), "rb") as f:
                treedef = pickle.load(f)
            with np.load(os.path.join(ckpt_dir, "arrays.npz")) as z:
                leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
        except CheckpointCorruptError:
            raise
        except Exception as e:  # OSError, KeyError, BadZipFile, UnpicklingError, ...
            raise CheckpointCorruptError(step, ckpt_dir, f"snapshot unreadable: {e!r}")
        expected = meta.get("num_leaves")
        if expected is not None and expected != len(leaves):
            raise CheckpointCorruptError(
                step, ckpt_dir, f"expected {expected} leaves, found {len(leaves)}"
            )
        crcs = meta.get("crc32s")
        if crcs is not None:  # pre-hardening snapshots lack checksums
            for i, (leaf, crc) in enumerate(zip(leaves, crcs)):
                actual = zlib.crc32(np.ascontiguousarray(leaf).tobytes()) & 0xFFFFFFFF
                if actual != crc:
                    raise CheckpointCorruptError(
                        step,
                        ckpt_dir,
                        f"leaf_{i} checksum mismatch (crc32 {actual:#x} != recorded {crc:#x})",
                    )
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _check_fingerprint(self, step: int, meta: dict) -> None:
        saved = meta.get("fingerprint")
        if saved is not None and self.fingerprint is not None and saved != self.fingerprint:
            raise FingerprintMismatchError(
                f"checkpoint directory {self.directory!r} holds snapshots of a different "
                f"run (fingerprint {saved!r} != {self.fingerprint!r}); point this job at "
                "a fresh directory or delete the stale checkpoints"
            )

    def _quarantine(self, step: int) -> None:
        """Move a corrupt snapshot aside as ``ckpt-N.corrupt`` (kept for
        forensics, invisible to ``all_steps``) instead of deleting evidence."""
        src = self._step_dir(step)
        dst = src + _CORRUPT_SUFFIX
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = f"{src}{_CORRUPT_SUFFIX}.{n}"
        try:
            os.rename(src, dst)
        except OSError:
            shutil.rmtree(src, ignore_errors=True)
        metrics.counter(MLMetrics.CHECKPOINT_GROUP, MLMetrics.CHECKPOINT_QUARANTINED)

    def restore_latest(self) -> Optional[Tuple[int, Any]]:
        """(step, state) of the newest *intact* snapshot, or None.

        The signature the iteration drivers expect (iteration._maybe_restore).
        A snapshot that fails verification is quarantined (``ckpt-N.corrupt``)
        and the next older one is tried — corruption degrades to a slightly
        older resume point, never a crash. A fingerprint mismatch still raises:
        falling back would resume some *other* job's state.
        """
        fell_back = False
        for step in reversed(self.all_steps()):
            try:
                meta = self._read_meta(step)
            except CheckpointCorruptError:
                self._quarantine(step)
                fell_back = True
                continue
            self._check_fingerprint(step, meta)
            try:
                state = self.restore(step)
            except CheckpointCorruptError:
                self._quarantine(step)
                fell_back = True
                continue
            if fell_back:
                metrics.counter(MLMetrics.CHECKPOINT_GROUP, MLMetrics.CHECKPOINT_FALLBACKS)
            return step, state
        return None

    def _prune(self) -> None:
        steps = self.all_steps()
        for step in steps[: -self.max_to_keep] if self.max_to_keep else []:
            shutil.rmtree(self._step_dir(step))


class ShardedCheckpointManager(CheckpointManager):
    """Per-shard snapshots of mesh-resident training state (train.mesh tier).

    Drop-in for ``CheckpointManager`` everywhere the iteration drivers accept
    one — same ``save``/``restore_latest`` contract, same atomicity, CRC32,
    quarantine and fallback discipline. The difference is the leaf layout: a
    device array whose sharding is NOT fully replicated is written as one
    ``leaf_{i}_piece_{j}`` entry per distinct shard index (shard-local D2H —
    the host never materializes the gathered global array at save time), and
    META.json records the mesh shape plus each piece's global placement.
    ``restore`` reassembles global host arrays (placement back onto the mesh
    is the resuming fit's job) and raises the typed — and, like a fingerprint
    mismatch, fatal — ``MeshMismatchError`` when per-shard pieces meet a
    manager configured for a different mesh shape. Snapshots holding only
    replicated/host leaves restore on ANY mesh (width-portable: e.g. KMeans
    centroids killed at mesh=2 resume at mesh=4).

    ``sharding``: a ``TrainSharding``/``MeshContext``-shaped object (duck
    typed ``n_data``/``n_model`` — this module stays importable below the
    parallel tier) or an ``(n_data, n_model)`` tuple; None skips the mesh
    compatibility check.
    """

    _FORMAT = "sharded-v1"

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 2,
        fingerprint: Optional[str] = None,
        sharding=None,
    ):
        super().__init__(directory, max_to_keep=max_to_keep, fingerprint=fingerprint)
        if sharding is None:
            self.mesh_shape: Optional[Tuple[int, int]] = None
        elif isinstance(sharding, tuple):
            self.mesh_shape = (int(sharding[0]), int(sharding[1]))
        else:
            self.mesh_shape = (int(sharding.n_data), int(sharding.n_model))

    @staticmethod
    def _leaf_pieces(leaf):
        """None for host/replicated leaves; else the deduped per-shard pieces
        ``[(bounds, host_piece), ...]`` sorted by position, where ``bounds``
        is ``((start, stop), ...)`` per dim. Replica copies (e.g. the model
        axis of a data-sharded leaf) are skipped — one piece per distinct
        index, so the snapshot stores each element exactly once."""
        if not isinstance(leaf, jax.Array):
            return None
        try:
            if leaf.sharding.is_fully_replicated:
                return None
        except AttributeError:
            return None
        seen = {}
        for shard in leaf.addressable_shards:
            bounds = tuple(
                (
                    0 if s.start is None else int(s.start),
                    int(leaf.shape[d]) if s.stop is None else int(s.stop),
                )
                for d, s in enumerate(shard.index)
            )
            if bounds not in seen:
                seen[bounds] = np.asarray(jax.device_get(shard.data))
        return sorted(seen.items())

    def save(self, step: int, state: Any) -> str:
        faults.trip("checkpoint.save", step=step)
        leaves, treedef = jax.tree_util.tree_flatten(state)
        entries: dict = {}
        descs: List[Optional[dict]] = []
        crcs: dict = {}
        n_pieces = 0
        for i, leaf in enumerate(leaves):
            pieces = self._leaf_pieces(leaf)
            if pieces is None:
                host = np.asarray(jax.device_get(leaf))
                entries[f"leaf_{i}"] = host
                crcs[f"leaf_{i}"] = _crc(host)
                descs.append(None)
                continue
            descs.append(
                {
                    "shape": [int(x) for x in leaf.shape],
                    "dtype": np.dtype(leaf.dtype).name,
                    "pieces": [[list(b) for b in bounds] for bounds, _ in pieces],
                }
            )
            for j, (_bounds, piece) in enumerate(pieces):
                name = f"leaf_{i}_piece_{j}"
                entries[name] = piece
                crcs[name] = _crc(piece)
                n_pieces += 1
        if n_pieces:
            metrics.counter(
                MLMetrics.CHECKPOINT_GROUP,
                MLMetrics.CHECKPOINT_SHARD_PIECES,
                n_pieces,
            )
        meta = {
            "format": self._FORMAT,
            "step": step,
            "num_leaves": len(leaves),
            "fingerprint": self.fingerprint,
            "mesh": list(self.mesh_shape) if self.mesh_shape else None,
            "leaves": descs,
            "crc32s": crcs,
        }
        return self._write_snapshot(step, entries, treedef, meta)

    def restore(self, step: int) -> Any:
        ckpt_dir = self._step_dir(step)
        meta = self._read_meta(step)
        if meta.get("format") != self._FORMAT:
            # A plain snapshot in this directory (e.g. the run started on the
            # flat manager before the mesh tier was enabled): read it as-is.
            return super().restore(step)
        try:
            with open(os.path.join(ckpt_dir, "treedef.pkl"), "rb") as f:
                treedef = pickle.load(f)
            with np.load(os.path.join(ckpt_dir, "arrays.npz")) as z:
                data = {name: z[name] for name in z.files}
        except CheckpointCorruptError:
            raise
        except Exception as e:  # OSError, KeyError, BadZipFile, UnpicklingError, ...
            raise CheckpointCorruptError(step, ckpt_dir, f"snapshot unreadable: {e!r}")
        for name, crc in (meta.get("crc32s") or {}).items():
            if name not in data:
                raise CheckpointCorruptError(step, ckpt_dir, f"{name} missing")
            actual = _crc(data[name])
            if actual != crc:
                raise CheckpointCorruptError(
                    step,
                    ckpt_dir,
                    f"{name} checksum mismatch (crc32 {actual:#x} != recorded {crc:#x})",
                )
        descs = meta.get("leaves")
        if descs is None or len(descs) != meta.get("num_leaves"):
            raise CheckpointCorruptError(
                step, ckpt_dir, "leaf descriptor table missing or truncated"
            )
        saved_mesh = meta.get("mesh")
        if (
            any(d is not None for d in descs)
            and self.mesh_shape is not None
            and saved_mesh is not None
            and tuple(saved_mesh) != self.mesh_shape
        ):
            raise MeshMismatchError(
                f"checkpoint step {step} holds per-shard leaves saved on mesh "
                f"{tuple(saved_mesh)}, but this run's train mesh is "
                f"{self.mesh_shape}; resume on the saved mesh shape or start "
                "from a fresh directory"
            )
        leaves = []
        for i, desc in enumerate(descs):
            if desc is None:
                if f"leaf_{i}" not in data:
                    raise CheckpointCorruptError(step, ckpt_dir, f"leaf_{i} missing")
                leaves.append(data[f"leaf_{i}"])
                continue
            out = np.zeros(tuple(desc["shape"]), np.dtype(desc["dtype"]))
            for j, bounds in enumerate(desc["pieces"]):
                name = f"leaf_{i}_piece_{j}"
                if name not in data:
                    raise CheckpointCorruptError(step, ckpt_dir, f"{name} missing")
                out[tuple(slice(a, b) for a, b in bounds)] = data[name]
            leaves.append(out)
        return jax.tree_util.tree_unflatten(treedef, leaves)
