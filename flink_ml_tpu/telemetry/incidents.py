"""Incident bundles — a self-contained postmortem directory per episode.

When the runtime makes a *bad-day* decision — a drift rollback, a version
quarantine, a sustained shed episode, a supervisor restart, a swap rejected
by the poller, or a crash-resume detected at journal startup — the flight
recorder snapshots everything an operator needs into one
``incident-<seq>-<kind>/`` directory:

- ``incident.json`` — kind, trigger context, sequence/incarnation anchors,
  the resolved runtime config (``config.to_dict()``), and the **version
  lineage** reconstructed from the journal window (every publish / swap /
  rollback / quarantine decision, in sequence order);
- ``journal.jsonl`` — the last ``observability.incident.window.s`` seconds
  of the decision journal (plus the incident's own record);
- ``metrics.prom`` — the full metrics registry in Prometheus exposition;
- ``spans.json`` — the tracer ring as a Chrome trace, when tracing is on.

Bundles are written by the journal's writer thread (never a hot path),
rate-limited per kind (``observability.incident.min.interval.s``) and
retained bounded (``observability.incident.keep`` — oldest deleted).
``tools/traceview.py incident <bundle>`` renders the postmortem timeline.
"""
from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional

from flink_ml_tpu.config import config
from flink_ml_tpu.trace import tracer

__all__ = ["list_bundles", "load_bundle", "version_lineage", "write_bundle"]

_BUNDLE_RE = re.compile(r"^incident-(\d+)-(.+)$")

#: Journal record kinds that constitute the version lineage.
_LINEAGE_KINDS = (
    "loop.publish",
    "serving.swap",
    "serving.rollback",
    "serving.swap.failed",
    "loop.quarantine",
    "loop.rollback",
)


def list_bundles(directory: str) -> List[str]:
    """Bundle directories under ``directory``, oldest first (by seq)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    found = []
    for name in names:
        m = _BUNDLE_RE.match(name)
        if m and os.path.isdir(os.path.join(directory, name)):
            found.append((int(m.group(1)), os.path.join(directory, name)))
    return [path for _, path in sorted(found)]


def version_lineage(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The model-version decisions in a journal window, in sequence order —
    who published/flipped/reverted/quarantined what, the trail a postmortem
    walks first."""
    lineage = []
    for rec in records:
        if rec.get("kind") in _LINEAGE_KINDS:
            entry = {"seq": rec.get("seq"), "kind": rec.get("kind"), "t": rec.get("t")}
            data = rec.get("data") or {}
            if "version" in data:
                entry["version"] = data["version"]
            if "scope" in rec:
                entry["scope"] = rec["scope"]
            lineage.append(entry)
    return lineage


def write_bundle(
    directory: str,
    kind: str,
    *,
    seq: int,
    incarnation: int,
    context: Dict[str, Any],
    records: List[Dict[str, Any]],
    window_s: float,
    now: float,
    wall: float,
    keep: int = 8,
) -> str:
    """Write one bundle (journal writer thread only); returns its path.
    ``records`` is the recorder's tail ring — the window filter keeps the
    trailing ``window_s`` seconds of it. Prunes the oldest bundles past
    ``keep`` after writing."""
    os.makedirs(directory, exist_ok=True)
    bundle = os.path.join(directory, f"incident-{seq:06d}-{_safe(kind)}")
    tmp = bundle + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)

    # The time window applies to THIS incarnation's records; records seeded
    # from an earlier incarnation (the crash-resume postmortem tail) carry a
    # different process's monotonic timebase and are kept as-is.
    horizon = now - max(0.0, window_s)
    window = [
        r for r in records
        if r.get("inc", incarnation) != incarnation
        or float(r.get("t", now)) >= horizon
    ]

    with open(os.path.join(tmp, "journal.jsonl"), "w", encoding="utf-8") as f:
        for rec in window:
            f.write(json.dumps(rec, separators=(",", ":"), default=str) + "\n")

    from flink_ml_tpu.metrics import metrics

    with open(os.path.join(tmp, "metrics.prom"), "w", encoding="utf-8") as f:
        f.write(metrics.render_prometheus())

    spans = None
    if tracer.enabled:
        spans = "spans.json"
        tracer.recorder.export_chrome_trace(os.path.join(tmp, spans))

    manifest = {
        "kind": kind,
        "seq": seq,
        "incarnation": incarnation,
        "t": now,
        "wall": wall,
        "window_s": window_s,
        "context": context,
        "journal_records": len(window),
        "spans": spans,
        "lineage": version_lineage(window),
        "config": _jsonable(config.to_dict()),
    }
    with open(os.path.join(tmp, "incident.json"), "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, default=str)

    os.rename(tmp, bundle)  # the checkpoint tier's atomic-publish discipline
    for old in list_bundles(directory)[: -keep or None]:
        if old != bundle:
            shutil.rmtree(old, ignore_errors=True)
    return bundle


def load_bundle(bundle: str) -> Dict[str, Any]:
    """Parse one bundle for analysis (tools/traceview.py incident): the
    manifest plus its journal records (and span events when captured).
    Raises ``OSError``/``ValueError`` on a malformed bundle."""
    with open(os.path.join(bundle, "incident.json"), encoding="utf-8") as f:
        manifest = json.load(f)
    records: List[Dict[str, Any]] = []
    with open(os.path.join(bundle, "journal.jsonl"), encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    events: List[Dict[str, Any]] = []
    spans_name = manifest.get("spans")
    if spans_name:
        spans_path = os.path.join(bundle, spans_name)
        if os.path.exists(spans_path):
            with open(spans_path, encoding="utf-8") as f:
                events = json.load(f).get("traceEvents", [])
    return {"manifest": manifest, "records": records, "trace_events": events}


def _safe(kind: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", kind)


def _jsonable(d: Dict[str, Any]) -> Dict[str, Any]:
    return {k: (v if isinstance(v, (int, float, str, bool, type(None))) else str(v)) for k, v in d.items()}
