"""flink_ml_tpu.telemetry — always-on flight recorder, incidents, endpoint.

Three pieces (docs/observability.md):

- :mod:`~flink_ml_tpu.telemetry.journal` — the :class:`FlightRecorder`:
  an always-on, append-only, crash-safe JSONL journal of runtime decisions,
  one bounded-queue enqueue on the hot path, a dedicated writer thread;
- :mod:`~flink_ml_tpu.telemetry.incidents` — self-contained
  ``incident-<seq>-<kind>/`` postmortem bundles (journal window + metrics +
  spans + config + version lineage), rate-limited and bounded-retention;
- :mod:`~flink_ml_tpu.telemetry.http` — the live ``/metrics`` /
  ``/healthz`` / ``/events`` endpoint behind ``observability.http.port``.

Layering: L1 like ``trace`` — the package imports only L0 (config, faults,
metrics) and L1 (trace), so instrumenting the serving tier keeps the
runtime-free guarantee. The faults module (L0) reaches the journal through
its observer hook, never by importing upward.
"""
from flink_ml_tpu.telemetry.incidents import (
    list_bundles,
    load_bundle,
    version_lineage,
    write_bundle,
)
from flink_ml_tpu.telemetry.journal import (
    FlightRecorder,
    configure,
    emit,
    get_recorder,
    incident,
    journal_files,
    journal_tail,
    read_journal,
)
from flink_ml_tpu.telemetry.http import TelemetryServer

__all__ = [
    "FlightRecorder",
    "TelemetryServer",
    "configure",
    "emit",
    "get_recorder",
    "incident",
    "journal_files",
    "journal_tail",
    "list_bundles",
    "load_bundle",
    "read_journal",
    "version_lineage",
    "write_bundle",
]
