"""Live telemetry endpoint — /metrics, /healthz, /events on a stdlib thread.

The fleet tier (ROADMAP item 3) needs a scrapeable per-replica surface; this
module is it, with zero dependencies beyond ``http.server``:

- ``GET /metrics`` — the whole metrics registry in Prometheus text
  exposition (``metrics.render_prometheus``: ``# TYPE`` lines, ``_total``
  counter suffixes, histogram summaries);
- ``GET /healthz`` — a JSON liveness/readiness snapshot of the attached
  :class:`~flink_ml_tpu.serving.server.InferenceServer` (serving version,
  queue depth, goodput fraction, controller state) with **503** while the
  server is draining or closed — the load-balancer contract;
- ``GET /events?n=50`` — the newest n flight-recorder records (the
  journal's in-memory tail ring).

Off by default: an ``InferenceServer`` starts one only when
``observability.http.port`` (or ``ServingConfig(http_port=...)``) is set;
port 0 binds an ephemeral port (tests read ``TelemetryServer.port``). The
whole surface is a cold export path — request handling never touches a
serving lock beyond the metrics registry's own.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from flink_ml_tpu.metrics import MLMetrics, metrics

__all__ = ["TelemetryServer"]


class TelemetryServer:  # graftcheck: cold
    """One HTTP thread serving /metrics, /healthz and /events.

    ``health`` is a callable returning ``(ok, payload)`` — an
    ``InferenceServer`` passes its own ``health`` method; without one the
    endpoint reports a bare 200 (process up). ``recorder`` defaults to the
    process flight recorder.
    """

    def __init__(
        self,
        port: int = 0,
        *,
        health: Optional[Callable[[], Tuple[bool, Dict[str, Any]]]] = None,
        recorder=None,
        host: str = "127.0.0.1",
        scope: str = MLMetrics.TELEMETRY_GROUP,
    ):
        if recorder is None:
            from flink_ml_tpu.telemetry.journal import get_recorder

            recorder = get_recorder()
        self.recorder = recorder
        self.scope = scope
        self._health = health
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # no stderr chatter per scrape
                pass

            def do_GET(self):
                try:
                    outer._handle(self)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # scraper went away mid-response

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"telemetry-http[{self.port}]",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- request handling (http.server worker threads) -------------------------
    def _handle(self, request) -> None:
        parsed = urlparse(request.path)
        metrics.counter(self.scope, MLMetrics.TELEMETRY_HTTP_REQUESTS)
        if parsed.path == "/metrics":
            body = metrics.render_prometheus().encode("utf-8")
            self._respond(request, 200, body, "text/plain; version=0.0.4; charset=utf-8")
        elif parsed.path == "/healthz":
            ok, payload = self._health() if self._health is not None else (True, {"status": "up"})
            body = json.dumps(payload, indent=1, default=str).encode("utf-8")
            self._respond(request, 200 if ok else 503, body, "application/json")
        elif parsed.path == "/events":
            try:
                n = int(parse_qs(parsed.query).get("n", ["100"])[0])
            except (ValueError, IndexError):
                n = 100
            body = json.dumps(self.recorder.tail(n), default=str).encode("utf-8")
            self._respond(request, 200, body, "application/json")
        else:
            self._respond(request, 404, b"not found\n", "text/plain")

    @staticmethod
    def _respond(request, code: int, body: bytes, content_type: str) -> None:
        request.send_response(code)
        request.send_header("Content-Type", content_type)
        request.send_header("Content-Length", str(len(body)))
        request.end_headers()
        request.wfile.write(body)

    # -- lifecycle -------------------------------------------------------------
    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "TelemetryServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
