"""FlightRecorder — the always-on, crash-safe decision journal.

graftscope (flink_ml_tpu/trace.py) attributes traced milliseconds, but it is
opt-in, in-memory, and dies with the process — exactly when a postmortem
needs it most. The flight recorder is the other half of the observability
story: an **always-on** (``observability.journal``, default on), append-only,
on-disk journal of the runtime's *decisions* — hot swaps, rollbacks,
quarantines, priority sheds, bucket downshifts, depth steps, fusion plan
choices, fault trips, supervisor restarts — one compact JSONL record per
decision, surviving any crash up to the last flushed line.

Design (docs/observability.md "Flight recorder"):

- **One enqueue on the hot path.** ``emit()`` builds a small dict and appends
  it to a bounded queue under a short lock — no I/O, no serialization, no
  clock beyond two reads. A dedicated writer thread (``flight-recorder`` in
  the graftcheck thread topology) serializes, assigns sequence numbers, and
  appends to disk. On queue overflow new events are **dropped and counted**
  (``dropped`` / ``ml.telemetry.journal.dropped``) — telemetry never applies
  backpressure to serving.
- **Crash-safe, torn-tail-tolerant.** Records are newline-delimited JSON,
  flushed per writer batch. A hard kill mid-write leaves at most one torn
  tail line; :func:`read_journal` skips unparsable lines, and a new
  incarnation resumes the **sequence numbers without reuse** (scanning the
  existing files for the maximum valid ``seq``), bumps the incarnation
  counter, journals a ``recorder.resume`` record, and — when the previous
  incarnation did not write its clean ``recorder.stop`` marker — emits a
  ``crash-resume`` incident bundle (telemetry/incidents.py).
- **Causally linked to graftscope.** Every record carries monotonic
  (``time.perf_counter`` — the tracer's timebase) and wall timestamps, the
  emitting thread's name, and — when tracing is on — the innermost open span
  id of the emitting thread, so ``tools/traceview.py incident`` can
  interleave decisions with span categories on one timeline.

The default journal directory is a fresh per-process directory under the
system temp dir (the journal is always on, but an unconfigured process never
scribbles into a repo or resumes someone else's sequence). Deployments set
``observability.journal.dir`` to a stable path to get cross-incarnation
resume and crash-resume incident bundles.
"""
from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_ml_tpu.config import Options, config
from flink_ml_tpu.faults import InjectedFault, faults
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.trace import tracer

__all__ = [
    "FlightRecorder",
    "configure",
    "emit",
    "get_recorder",
    "incident",
    "journal_files",
    "journal_tail",
    "read_journal",
]

#: journal-<incarnation>-<part>.jsonl
_FILE_RE = re.compile(r"^journal-(\d+)-(\d+)\.jsonl$")

#: Clean-shutdown marker record kind (see FlightRecorder.close).
_STOP_KIND = "recorder.stop"

#: In-memory tail ring the incident bundler and /events endpoint read.
_TAIL_CAPACITY = 2048


def journal_files(directory: str) -> List[Tuple[int, int, str]]:
    """Sorted ``(incarnation, part, path)`` of the journal files under
    ``directory`` (empty when the directory does not exist)."""
    out: List[Tuple[int, int, str]] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = _FILE_RE.match(name)
        if m:
            out.append((int(m.group(1)), int(m.group(2)), os.path.join(directory, name)))
    out.sort()
    return out


def _read_file(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """(valid records, torn/invalid line count) of one journal file. A torn
    tail — a kill mid-write — is at most one unparsable trailing line; any
    unparsable line anywhere is skipped and counted, never fatal."""
    records: List[Dict[str, Any]] = []
    torn = 0
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            payload = f.read()
    except OSError:
        return records, torn
    for line in payload.split("\n"):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            torn += 1
            continue
        if isinstance(rec, dict) and "seq" in rec:
            records.append(rec)
        else:
            torn += 1
    return records, torn


def read_journal(directory: str) -> List[Dict[str, Any]]:
    """Every valid record in the journal under ``directory``, in file order
    (incarnation, part, position). Torn/invalid lines are silently skipped —
    the torn-tail tolerance contract."""
    records: List[Dict[str, Any]] = []
    for _, _, path in journal_files(directory):
        recs, _ = _read_file(path)
        records.extend(recs)
    return records


def journal_tail(directory: str, n: int = 100) -> List[Dict[str, Any]]:
    """The newest ``n`` valid records of the on-disk journal."""
    return read_journal(directory)[-max(0, int(n)):]


class FlightRecorder:
    """The journal's writer half: a bounded queue fed by ``emit`` /
    ``incident`` on any thread, drained by one dedicated writer thread that
    owns the sequence counter, the open file, the in-memory tail ring, and
    the incident bundler. See the module docstring for the contract."""

    #: Injectable clocks (monotonic shares the tracer's timebase so journal
    #: records interleave exactly with span intervals).
    clock: Callable[[], float] = staticmethod(time.perf_counter)
    wall_clock: Callable[[], float] = staticmethod(time.time)

    def __init__(
        self,
        directory: Optional[str] = None,
        *,
        enabled: Optional[bool] = None,
        queue_capacity: Optional[int] = None,
        max_bytes: Optional[int] = None,
        keep_files: Optional[int] = None,
        incident_dir: Optional[str] = None,
        incident_window_s: Optional[float] = None,
        incident_keep: Optional[int] = None,
        incident_min_interval_s: Optional[float] = None,
        scope: str = MLMetrics.TELEMETRY_GROUP,
    ):
        self.enabled = (  # graftcheck: owned-by=main
            bool(enabled) if enabled is not None
            else bool(config.get(Options.OBSERVABILITY_JOURNAL))
        )
        if directory is None:
            directory = config.get(Options.OBSERVABILITY_JOURNAL_DIR)
        if directory is None and self.enabled:
            # Unconfigured default: a fresh per-process dir — always-on
            # recording without cross-process sequence collisions.
            directory = tempfile.mkdtemp(prefix="flink-ml-tpu-flight-")
        self.directory = directory
        self.scope = scope
        self.queue_capacity = int(
            queue_capacity if queue_capacity is not None
            else config.get(Options.OBSERVABILITY_JOURNAL_QUEUE)
        )
        self.max_bytes = int(
            max_bytes if max_bytes is not None
            else config.get(Options.OBSERVABILITY_JOURNAL_MAX_BYTES)
        )
        self.keep_files = max(1, int(
            keep_files if keep_files is not None
            else config.get(Options.OBSERVABILITY_JOURNAL_KEEP_FILES)
        ))
        self.incident_dir = incident_dir or (
            os.path.join(directory, "incidents") if directory else None
        )
        self.incident_window_s = float(
            incident_window_s if incident_window_s is not None
            else config.get(Options.OBSERVABILITY_INCIDENT_WINDOW_S)
        )
        self.incident_keep = max(1, int(
            incident_keep if incident_keep is not None
            else config.get(Options.OBSERVABILITY_INCIDENT_KEEP)
        ))
        self.incident_min_interval_s = float(
            incident_min_interval_s if incident_min_interval_s is not None
            else config.get(Options.OBSERVABILITY_INCIDENT_MIN_INTERVAL_S)
        )

        # Queue state — every access under _lock/_cond (shared-state-guard's
        # consistent-lockset contract across emitter threads and the writer).
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._closed = False
        self._dropped = 0
        self._enqueued = 0
        self._flushed_through = 0
        self._last_incident: Dict[str, float] = {}
        self._incidents_suppressed = 0

        # Writer-thread state: the startup scan (incarnation/sequence resume,
        # file open) and every mutation below happen ONLY on the writer
        # thread, so the hot path never does file I/O — emit() is one
        # bounded-queue append. Reads elsewhere (properties, tests) accept
        # benign staleness.
        self._seq = 0  # graftcheck: owned-by=flight-recorder
        self._incarnation = 0  # graftcheck: owned-by=flight-recorder
        self._part = 0  # graftcheck: owned-by=flight-recorder
        self._file = None  # graftcheck: owned-by=flight-recorder
        self._bytes = 0  # graftcheck: owned-by=flight-recorder
        self._write_errors = 0  # graftcheck: owned-by=flight-recorder
        self._events_written = 0  # graftcheck: owned-by=flight-recorder
        self._dropped_published = 0  # graftcheck: owned-by=flight-recorder
        self._incidents_written = 0  # graftcheck: owned-by=flight-recorder
        self._resumed_from = None  # graftcheck: owned-by=flight-recorder
        self._crash_resume = False  # graftcheck: owned-by=flight-recorder

        # Tail ring: appended by the writer, snapshotted by /events and the
        # incident bundler — its own short lock, never held during I/O.
        self._tail_lock = threading.Lock()
        self._tail: deque = deque(maxlen=_TAIL_CAPACITY)

        #: Set once the writer finished its startup scan (sequence resumed,
        #: file open, resume/incident records written) — flush() waits on it
        #: so "flush then read the journal" is race-free in tests.
        self._started = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.enabled:
            self._thread = threading.Thread(
                target=self._loop,
                name=f"flight-recorder[{self.directory}]",
                daemon=True,
            )
            self._thread.start()

    # -- the hot-path half -----------------------------------------------------
    def emit(self, kind: str, scope: Optional[str] = None, data: Optional[Dict[str, Any]] = None) -> bool:
        """Enqueue one decision record. Returns False when disabled, closed,
        or dropped on overflow — callers never care, but tests do. ONE
        bounded-queue append: no I/O, no serialization on this thread."""
        if not self.enabled:
            return False
        span = tracer.current() if tracer.enabled else None
        rec: Dict[str, Any] = {
            "kind": kind,
            "t": self.clock(),
            "wall": self.wall_clock(),
            "thread": threading.current_thread().name,
        }
        if scope is not None:
            rec["scope"] = scope
        if span is not None:
            rec["span"] = span.span_id
        if data:
            rec["data"] = data
        with self._cond:
            if self._closed:
                return False
            if len(self._queue) >= self.queue_capacity:
                self._dropped += 1
                return False
            self._queue.append(rec)
            self._enqueued += 1
            self._cond.notify()
        return True

    def incident(self, kind: str, scope: Optional[str] = None, context: Optional[Dict[str, Any]] = None) -> bool:
        """Request an incident bundle (written by the writer thread, off
        every hot path): the last ``incident_window_s`` of the journal, the
        full metrics registry, recent spans (if tracing is on), the resolved
        config, and the version lineage, into a self-contained
        ``incident-<seq>-<kind>/`` directory. Rate-limited per kind and
        bounded-retention (docs/observability.md "Incident bundles")."""
        if not self.enabled:
            return False
        now = self.clock()
        entry: Dict[str, Any] = {
            "kind": "incident",
            "_incident": kind,
            "t": now,
            "wall": self.wall_clock(),
            "thread": threading.current_thread().name,
        }
        if scope is not None:
            entry["scope"] = scope
        if context:
            entry["data"] = dict(context)
        with self._cond:
            if self._closed:
                return False
            last = self._last_incident.get(kind)
            if last is not None and now - last < self.incident_min_interval_s:
                self._incidents_suppressed += 1
                metrics.counter(self.scope, MLMetrics.TELEMETRY_INCIDENTS_SUPPRESSED)
                return False
            self._last_incident[kind] = now
            # Incidents are rare and precious: they enqueue even past the
            # event-drop watermark (the queue bound still exists — a closed
            # recorder or a dead writer simply never drains them).
            self._queue.append(entry)
            self._enqueued += 1
            self._cond.notify()
        return True

    # -- introspection ---------------------------------------------------------
    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    @property
    def incidents_suppressed(self) -> int:
        with self._lock:
            return self._incidents_suppressed

    @property
    def seq(self) -> int:
        """Last written sequence number (writer-owned; benign-stale read)."""
        return self._seq

    @property
    def incarnation(self) -> int:
        return self._incarnation

    @property
    def write_errors(self) -> int:
        return self._write_errors

    @property
    def crash_resumed(self) -> bool:
        """Whether startup found a previous incarnation without its clean
        stop marker (and therefore journaled a resume + incident)."""
        return self._crash_resume

    def tail(self, n: int = 100) -> List[Dict[str, Any]]:
        """The newest ``n`` records already written (the in-memory ring —
        what /events and incident bundles read)."""
        with self._tail_lock:
            records = list(self._tail)
        return records[-max(0, int(n)):]

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until everything enqueued so far is written and flushed (or
        the timeout passes — e.g. the writer died on an injected fault).
        Test/shutdown surface, never called from a hot path."""
        deadline = time.monotonic() + timeout_s
        if self.enabled and not self._started.wait(timeout_s):
            return False
        with self._cond:
            target = self._enqueued
            while self._flushed_through < target:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._alive():
                    return self._flushed_through >= target
                self._cond.wait(min(remaining, 0.1))
        return True

    def _alive(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    # -- lifecycle -------------------------------------------------------------
    def close(self, timeout_s: float = 10.0) -> None:
        """Journal the clean-shutdown marker, drain the queue, close the
        file. A recorder that is killed instead (no close) is exactly what
        the crash-resume path detects next incarnation."""
        self.emit(_STOP_KIND)
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    # -- the writer thread -----------------------------------------------------
    def _loop(self) -> None:
        try:
            self._startup()
            self._safe_flush()  # start/resume records visible before any batch
        except Exception:
            self._write_errors += 1
            return
        finally:
            self._started.set()
        crashed = False
        while not crashed:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.2)
                batch = list(self._queue)
                self._queue.clear()
                drained_to = self._enqueued
                closing = self._closed
            for entry in batch:
                try:
                    if "_incident" in entry:
                        self._handle_incident(entry)
                    else:
                        self._write_record(entry)
                except BaseException as e:  # noqa: BLE001 — per-record containment
                    self._write_errors += 1
                    if isinstance(e, InjectedFault):
                        # The telemetry.journal seam: a mid-write kill. Leave
                        # the torn tail exactly as a hard kill would and die —
                        # the crash-recovery tests resume a new incarnation
                        # over it.
                        crashed = True
                        break
                    try:  # seal the torn line so later records stay parsable
                        if self._file is not None:
                            self._file.write("\n")
                    except OSError:
                        pass
            self._safe_flush()
            self._publish_metrics()
            with self._cond:
                self._flushed_through = max(self._flushed_through, drained_to)
                self._cond.notify_all()
                if crashed or (closing and not self._queue):
                    break
        if not crashed and self._file is not None:
            try:
                self._file.flush()
                os.fsync(self._file.fileno())
                self._file.close()
            except OSError:
                self._write_errors += 1
            self._file = None

    def _startup(self) -> None:
        """Writer-thread first act: scan the directory, resume the sequence
        and incarnation counters past everything already on disk, open the
        new incarnation's file, and journal the start/resume record (plus
        the crash-resume incident when the last incarnation died unclean)."""
        os.makedirs(self.directory, exist_ok=True)
        last_seq = 0
        last_inc = 0
        last_kind: Optional[str] = None
        torn_tail = False
        prior: List[Dict[str, Any]] = []
        for inc, _, path in journal_files(self.directory):
            records, torn = _read_file(path)
            last_inc = max(last_inc, inc)
            prior.extend(records)
            if records:
                tail = records[-1]
                if tail["seq"] >= last_seq:
                    last_seq = tail["seq"]
                    last_kind = tail.get("kind")
                    torn_tail = torn > 0
            elif torn:
                torn_tail = True
        # Seed the tail ring with the previous life's newest records so a
        # crash-resume incident bundle is a postmortem of the PRIOR
        # incarnation, not an empty window. (Their monotonic `t` values are
        # from another process and incomparable — the bundler's window
        # filter exempts records of earlier incarnations.)
        if prior:
            with self._tail_lock:
                self._tail.extend(prior[-256:])
        self._seq = last_seq
        self._incarnation = last_inc + 1
        self._part = 0
        self._open_part()
        if last_inc == 0:
            self._write_record(self._system_record("recorder.start"))
            return
        # A previous incarnation exists: resume the sequence (no reuse) and
        # decide whether it shut down cleanly.
        self._resumed_from = last_inc
        clean = last_kind == _STOP_KIND
        self._crash_resume = not clean
        self._write_record(
            self._system_record(
                "recorder.resume",
                {
                    "prior_incarnation": last_inc,
                    "prior_seq": last_seq,
                    "clean_shutdown": clean,
                    "torn_tail": torn_tail,
                },
            )
        )
        if not clean:
            self._handle_incident(
                self._system_record(
                    "incident",
                    {
                        "prior_incarnation": last_inc,
                        "prior_seq": last_seq,
                        "torn_tail": torn_tail,
                    },
                    _incident="crash-resume",
                )
            )

    def _system_record(self, kind: str, data: Optional[Dict[str, Any]] = None, _incident: Optional[str] = None) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "kind": kind,
            "t": self.clock(),
            "wall": self.wall_clock(),
            "thread": threading.current_thread().name,
            "scope": self.scope,
        }
        if data:
            rec["data"] = data
        if _incident is not None:
            rec["_incident"] = _incident
        return rec

    def _open_part(self) -> None:
        path = os.path.join(
            self.directory, f"journal-{self._incarnation:06d}-{self._part:04d}.jsonl"
        )
        self._file = open(path, "a", encoding="utf-8")
        self._bytes = 0

    def _rotate_if_needed(self) -> None:
        if self._bytes < self.max_bytes:
            return
        try:
            self._file.flush()
            self._file.close()
        except OSError:
            self._write_errors += 1
        self._part += 1
        self._open_part()
        files = journal_files(self.directory)
        for _, _, path in files[: max(0, len(files) - self.keep_files)]:
            try:
                os.remove(path)
            except OSError:
                pass

    def _write_record(self, rec: Dict[str, Any]) -> None:
        """Serialize + append one record (writer thread only). The
        ``telemetry.journal`` fault point sits mid-write: an armed kill
        leaves a torn tail line, exactly like a power cut."""
        self._seq += 1
        rec = dict(rec)
        rec.pop("_incident", None)
        rec["seq"] = self._seq
        rec["inc"] = self._incarnation
        line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
        mid = max(1, len(line) // 2)
        self._file.write(line[:mid])
        try:
            faults.trip("telemetry.journal", seq=self._seq)
        except BaseException:
            self._file.flush()  # the torn half-line reaches disk, as a kill would
            raise
        self._file.write(line[mid:])
        self._bytes += len(line)
        self._events_written += 1
        with self._tail_lock:
            self._tail.append(rec)
        self._rotate_if_needed()

    def _handle_incident(self, entry: Dict[str, Any]) -> None:
        """Journal the incident record, then write the bundle (both on the
        writer thread — the journal's own record of the incident is part of
        the bundle's tail window)."""
        kind = entry["_incident"]
        record = dict(entry)
        record["kind"] = "incident"
        data = dict(record.get("data") or {})
        data["incident"] = kind
        record["data"] = data
        self._write_record(record)
        self._safe_flush()
        from flink_ml_tpu.telemetry.incidents import write_bundle

        try:
            path = write_bundle(
                self.incident_dir,
                kind,
                seq=self._seq,
                incarnation=self._incarnation,
                context=dict(entry.get("data") or {}),
                records=self.tail(_TAIL_CAPACITY),
                window_s=self.incident_window_s,
                now=self.clock(),
                wall=entry.get("wall", self.wall_clock()),
                keep=self.incident_keep,
            )
        except Exception:
            self._write_errors += 1
            return
        self._incidents_written += 1
        metrics.counter(self.scope, MLMetrics.TELEMETRY_INCIDENTS)
        self.emit("incident.written", self.scope, {"incident": kind, "path": path})

    def _safe_flush(self) -> None:
        if self._file is None:
            return
        try:
            self._file.flush()
        except OSError:
            self._write_errors += 1

    def _publish_metrics(self) -> None:
        metrics.gauge(self.scope, MLMetrics.TELEMETRY_SEQ, self._seq)
        written = self._events_written
        if written:
            self._events_written = 0
            metrics.counter(self.scope, MLMetrics.TELEMETRY_EVENTS, written)
        with self._lock:
            dropped = self._dropped
        delta = dropped - self._dropped_published
        if delta > 0:
            self._dropped_published = dropped
            metrics.counter(self.scope, MLMetrics.TELEMETRY_DROPPED, delta)
        if self._write_errors:
            metrics.gauge(self.scope, MLMetrics.TELEMETRY_WRITE_ERRORS, self._write_errors)


# -- the process recorder ------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    """The process flight recorder, created lazily on the first decision
    event (so importing the package never touches the filesystem — the
    writer thread's startup scan does, off every caller path)."""
    global _recorder
    rec = _recorder
    if rec is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
            rec = _recorder
    return rec


def configure(directory: Optional[str] = None, **kwargs) -> FlightRecorder:
    """Install a fresh process recorder (closing the previous one) — the
    deployment/test entry point for pointing the journal at a stable
    directory. Accepts every :class:`FlightRecorder` keyword."""
    global _recorder
    with _recorder_lock:
        previous = _recorder
        _recorder = FlightRecorder(directory, **kwargs)
    if previous is not None:
        previous.close()
    return _recorder


def emit(kind: str, scope: Optional[str] = None, data: Optional[Dict[str, Any]] = None) -> bool:
    """Journal one decision record through the process recorder."""
    return get_recorder().emit(kind, scope, data)


def incident(kind: str, scope: Optional[str] = None, context: Optional[Dict[str, Any]] = None) -> bool:
    """Request an incident bundle through the process recorder."""
    return get_recorder().incident(kind, scope, context)


def _on_fault_fired(point: str, hit: int, context: Dict[str, Any]) -> None:
    """The faults-module observer: every fired fault point lands in the
    journal (telemetry's own seam excluded — the writer must not journal
    its own injected death recursively)."""
    if point.startswith("telemetry."):
        return
    emit("fault.trip", None, {"point": point, "hit": hit, "context": dict(context)})


faults.add_observer(_on_fault_fired)
