"""Ring attention — sequence-parallel attention for long contexts.

No analogue exists in the reference (its long-data story is windows and
streaming, `ops/windows.py`); this is the TPU-native primitive for the
sequence lengths a single chip cannot hold. The design is the standard ring
schedule (blockwise attention with a streaming softmax, KV blocks rotating
around the device ring via ``ppermute`` so compute overlaps the ICI
transfer):

- the sequence axis is sharded over the mesh; each shard holds its Q block
  permanently and starts with its own KV block;
- at every one of ``n_shards`` steps, each shard attends its Q against the
  currently resident KV block, folding the scores into a running
  (max, normalizer, weighted-value) accumulator — the numerically stable
  streaming softmax, so no [T, T] score matrix ever exists;
- the KV block then moves to the next shard on the ring (one ``ppermute``
  per step — neighbor traffic that rides ICI, never all-to-all).

Peak memory per shard is O(T_local · d) instead of O(T²); attention FLOPs
stay on the MXU as [T_local, d] x [d, T_local] matmuls.

``ring_attention`` is the collective-style function used *inside* a
``shard_map`` (axis name = the sequence axis); ``ring_attention_sharded``
is the convenience wrapper that shards [B, T, H, D] inputs over the mesh's
data axis and jits the whole thing.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.parallel.mesh import DATA_AXIS, MeshContext, get_mesh_context

__all__ = ["ring_attention", "ring_attention_sharded"]


def ring_attention(
    q, k, v, axis_name: str, causal: bool = False, n_valid: int = None,
    flash: bool = False,
):
    """Attention for sequence-sharded q/k/v, inside a ``shard_map``.

    ``q, k, v``: [B, T_local, H, D] — this shard's slice of the sequence.
    Returns [B, T_local, H, D]. With ``causal``, positions attend only to
    global positions <= their own (global position = shard index · T_local +
    local offset; shards are assumed to hold contiguous sequence slices in
    axis order, which is how ``NamedSharding`` lays them out). ``n_valid``
    masks out key positions >= it — REQUIRED when the sequence was padded
    and ``causal`` is off, or padded keys would receive softmax weight in
    every real row.

    With ``flash`` the per-step fold runs as the fused Pallas kernels
    (``parallel/flash.py``): scores never touch HBM on the forward OR the
    backward (the fold's VJP is fused too, pinned AD-exact). Callers should
    gate it with ``flash_available`` (tiling + VMEM + TPU backend).
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = 1.0 / np.sqrt(D)

    from flink_ml_tpu.parallel.flash import fused_fold, reference_fold

    # Tensors ride the ring in [B, H, T, D] layout (one transpose in, one
    # out); both folds share reference_fold's contract — the jnp numerics
    # are the single source of truth the fused kernels (forward and
    # backward) are pinned against in tests.
    q_t = jnp.transpose(q, (0, 2, 1, 3))
    k_c = jnp.transpose(k, (0, 2, 1, 3))
    v_c = jnp.transpose(v, (0, 2, 1, 3))
    has_nv = n_valid is not None
    nv = jnp.asarray(0 if n_valid is None else n_valid, jnp.int32)

    if flash:
        def fold(m, l, acc, kb, vb, step_idx):
            src = (my_idx - step_idx) % n
            return fused_fold(
                q_t, kb, vb, m, l, acc, my_idx * T, src * T, causal, has_nv,
                nv, scale,
            )

    else:
        def fold(m, l, acc, kb, vb, step_idx):
            src = (my_idx - step_idx) % n
            return reference_fold(
                q_t, kb, vb, m, l, acc, my_idx * T, src * T, causal,
                nv if has_nv else None, scale,
            )

    def step(carry, step_idx):
        kb, vb, m, l, acc = carry
        m, l, acc = fold(m, l, acc, kb, vb, step_idx)
        # rotate KV to the next shard on the ring
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (kb, vb, m, l, acc), None

    # pcast-to-varying: the accumulators are per-shard state (varying over the
    # sequence axis) — shard_map's scan requires the carry variance to match.
    m0 = jax.lax.pcast(jnp.full((B, H, T), -jnp.inf, q.dtype), axis_name, to="varying")
    l0 = jax.lax.pcast(jnp.zeros((B, H, T), q.dtype), axis_name, to="varying")
    acc0 = jax.lax.pcast(jnp.zeros((B, H, T, D), q.dtype), axis_name, to="varying")
    # n-1 rotations suffice: the last resident block folds without being
    # rotated back to its origin (that final exchange would be dead traffic).
    (kb, vb, m, l, acc), _ = jax.lax.scan(
        step, (k_c, v_c, m0, l0, acc0), jnp.arange(n - 1)
    )
    m, l, acc = fold(m, l, acc, kb, vb, n - 1)
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, H, Tq, D]
    return jnp.transpose(out, (0, 2, 1, 3))  # [B, Tq, H, D]


@functools.cache
def _sharded_program(mesh, causal: bool, masked: bool, flash: bool):
    spec = P(None, DATA_AXIS)  # [B, T, H, D] sharded over the sequence dim
    if masked:
        # n_valid arrives as a traced replicated scalar, so ONE compiled
        # program serves every real length of a padded-sequence workload.
        def per_shard(q, k, v, n_valid):
            return ring_attention(
                q, k, v, DATA_AXIS, causal=causal, n_valid=n_valid, flash=flash
            )

        return jax.jit(
            jax.shard_map(
                per_shard, mesh=mesh, in_specs=(spec, spec, spec, P()), out_specs=spec
            )
        )

    def per_shard(q, k, v):
        return ring_attention(q, k, v, DATA_AXIS, causal=causal, flash=flash)

    return jax.jit(
        jax.shard_map(
            per_shard, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
        )
    )


def ring_attention_sharded(
    q, k, v, causal: bool = False, ctx: MeshContext = None, n_valid: int = None
):
    """Full-sequence attention with [B, T, H, D] inputs sharded over the
    mesh's data axis as the sequence axis. T must divide evenly by the axis
    size; for an uneven sequence, pad q/k/v at the tail and pass the real
    length as ``n_valid`` — padded keys are then masked out of every row
    (without it, tail padding is only safe under ``causal``, where real
    rows never attend forward into it)."""
    ctx = ctx or get_mesh_context()
    T = np.shape(q)[1]
    if T % ctx.n_data:
        raise ValueError(
            f"sequence length {T} not divisible by mesh axis {ctx.n_data}; "
            "pad the sequence and pass n_valid"
        )
    from flink_ml_tpu.parallel.flash import flash_available

    # f32 only: the fused fold's accumulators are f32 (the jnp path keeps
    # the input dtype), so other dtypes stay on the jnp fold.
    flash = flash_available(
        T // ctx.n_data, int(np.shape(q)[3]), list(ctx.mesh.devices.flat)
    ) and np.dtype(getattr(q, "dtype", np.float32)) == np.dtype(np.float32)
    if n_valid is None:
        return _sharded_program(ctx.mesh, causal, False, flash)(q, k, v)
    return _sharded_program(ctx.mesh, causal, True, flash)(
        q, k, v, jnp.asarray(n_valid, jnp.int32)
    )
