"""Training-side sharding: model state and mini-batches on the mesh.

The companion of ``servable/sharding.py`` for the OTHER half of the loop
(ROADMAP item 4): where ``PlanSharding`` places served batches and weights,
``TrainSharding`` places the iteration drivers' epoch state — SGD
coefficients, KMeans centroids, MLP layers — as ``NamedSharding``-resident
device arrays on a ``parallel/mesh.py`` MeshContext, and deals the training
rows so the deterministic mapreduce tier (``parallel/collectives.py``) can
reduce them with a width-invariant association.

Bit-stability construction (docs/distributed_training.md):

1. **Block-cyclic deal.** Rows are zero-padded to the batch quantum and their
   8-row blocks dealt round-robin to the data shards (shard k gets global
   blocks k, k+N, k+2N, …) — realized host-side as one permutation before a
   standard contiguous ``device_put``. A global minibatch window [s, s+B)
   with s and B multiples of 8·N is then a *contiguous local* window
   [s/N, s/N + B/N) on every shard, so the trainers' cheap ``dynamic_slice``
   minibatching survives unchanged — and the set of global rows each epoch
   consumes is the same at every mesh width.
2. **Deterministic reduce.** Per-8-row-block partials, an all_gather that
   restores global block order, and a balanced pairwise tree fold replicated
   on every device (``collectives.mapreduce_sum``). Same blocks, same tree,
   at every width — epochs are bit-identical to mesh=1 by construction.

Multi-host (``train.mesh.hosts``): ``ensure_distributed`` guards the one
``jax.distributed.initialize`` call a pod-scale run needs; single-host runs
never touch it. Resolution (``resolve_train_sharding``) differs deliberately
from the serving tier's: ``train.mesh=1`` is NOT a no-op — it returns a
width-1 TrainSharding so mesh=1 runs the *same deterministic program* the
wider meshes run, which is what makes the bit-stability contract testable.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import jax
import numpy as np

from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.parallel.collectives import BLOCK_ROWS
from flink_ml_tpu.parallel.mesh import DATA_AXIS, MeshContext

__all__ = [
    "TrainSharding",
    "ShardedTrainCache",
    "resolve_train_sharding",
    "ensure_distributed",
]

_distributed_lock = threading.Lock()
_distributed_initialized = False


def ensure_distributed(n_hosts: Optional[int] = None) -> bool:
    """Initialize ``jax.distributed`` once, iff a multi-host mesh is asked for.

    Reads ``train.mesh.hosts`` when ``n_hosts`` is None. Hosts <= 1 — the
    entire single-host world, including every test and smoke in this repo —
    returns False without importing or touching the distributed runtime, so
    single-host behavior is exactly unchanged. Hosts > 1 calls
    ``jax.distributed.initialize()`` (coordinator address, process id and
    count come from the standard JAX_* / cloud TPU environment, the same
    contract ``jax.distributed`` documents); a second call is a no-op.
    """
    global _distributed_initialized
    if n_hosts is None:
        from flink_ml_tpu.config import Options, config

        n_hosts = config.get(Options.TRAIN_MESH_HOSTS)
    if not n_hosts or int(n_hosts) <= 1:
        return False
    with _distributed_lock:
        if not _distributed_initialized:
            jax.distributed.initialize()
            _distributed_initialized = True
    return True


class TrainSharding:
    """Placement + deal discipline for one sharded training run.

    ``n_data`` × ``n_model`` devices (the train mesh is always single-slice;
    multi-slice training goes through the mesh context's hierarchical psums,
    not the deterministic tier). Immutable; ``key`` joins run fingerprints and
    program-cache keys.
    """

    def __init__(
        self,
        n_data: int = 1,
        n_model: int = 1,
        devices=None,
    ):
        if n_data < 1 or n_model < 1:
            raise ValueError(f"train mesh axes must be >= 1, got {n_data}x{n_model}")
        devices = list(devices) if devices is not None else jax.devices()
        need = n_data * n_model
        if need > len(devices):
            raise ValueError(
                f"train.mesh {n_data}x{n_model} needs {need} devices, "
                f"only {len(devices)} visible"
            )
        self.ctx = MeshContext(devices=devices[:need], n_data=n_data, n_model=n_model)
        self.n_data = n_data
        self.n_model = n_model

    @property
    def key(self):
        return (self.n_data, self.n_model)

    @property
    def mesh(self):
        return self.ctx.mesh

    @property
    def data_axes(self):
        return self.ctx.data_axes

    # --- quanta --------------------------------------------------------------
    @property
    def row_quantum(self) -> int:
        """Rows per indivisible unit: one 8-row block per data shard."""
        return BLOCK_ROWS * self.n_data

    def round_batch(self, global_batch: int) -> int:
        """Smallest quantum multiple >= ``global_batch`` (the 8·N remainder
        discipline: every shard's local minibatch is whole 8-row blocks)."""
        q = self.row_quantum
        return max(q, ((int(global_batch) + q - 1) // q) * q)

    def padded_rows(self, n: int, global_batch: int) -> int:
        """Rows after padding: the smallest multiple of ``global_batch`` >= n.

        A function of (n, B) only — never of the mesh width — so the padded
        row count, and with it every epoch's consumed global window, is
        width-invariant. Multiples of B keep the offset-cycling schedule
        clamp-free: each epoch's window [e·B mod n', +B) is quantum-aligned.
        """
        b = int(global_batch)
        if b % self.row_quantum:
            raise ValueError(
                f"global batch {b} is not a multiple of the row quantum "
                f"{self.row_quantum} (use round_batch)"
            )
        return max(b, ((int(n) + b - 1) // b) * b)

    def deal_permutation(self, n_padded: int) -> np.ndarray:
        """Row permutation realizing the block-cyclic deal as contiguous shards.

        Global block g lands on shard g mod N at local position g // N; the
        permuted array's contiguous shard k therefore holds blocks
        k, k+N, k+2N, … — what ``mapreduce_sum``'s gather-unpermute inverts.
        """
        if n_padded % self.row_quantum:
            raise ValueError(
                f"{n_padded} rows not a multiple of the quantum {self.row_quantum}"
            )
        n_blocks = n_padded // BLOCK_ROWS
        order = np.arange(n_blocks).reshape(-1, self.n_data).T.reshape(-1)
        return (order[:, None] * BLOCK_ROWS + np.arange(BLOCK_ROWS)).reshape(-1)

    # --- placement -----------------------------------------------------------
    def place_state(self, tree):
        """Model state (coefficients / centroids / MLP layers) as replicated
        NamedSharding-resident device arrays — the broadcast-variable layout
        every epoch program reads without a host round trip."""
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, self.ctx.replicated), tree
        )

    def replicate(self, array) -> jax.Array:  # graftcheck: ingest
        """The blessed device_put boundary for replicated train state."""
        return jax.device_put(array, self.ctx.replicated)

    def deal_cache(
        self,
        columns: Dict[str, np.ndarray],
        global_batch: Optional[int] = None,
        dtype=np.float32,
    ) -> "ShardedTrainCache":
        """Ingest host columns under the deal discipline (one permutation +
        one device_put per column). ``global_batch`` defaults to one quantum;
        callers round it first (``round_batch``)."""
        b = self.round_batch(global_batch if global_batch else self.row_quantum)
        return ShardedTrainCache(columns, self, b, dtype=dtype)


class ShardedTrainCache:
    """Columnar training set resident in HBM under the block-cyclic deal.

    The TrainSharding analogue of ``iteration.DeviceDataCache``: same surface
    (``cache[name]``, ``mask``, ``local_rows``, ``n_valid``) so the trainers'
    epoch programs are layout-agnostic — only the ingest (here) and the
    reduce (``collectives.mapreduce_sum``) know about the deal. Padding rows
    carry zero data and a zero mask, so they are additively inert in every
    deterministic fold.
    """

    def __init__(  # graftcheck: ingest
        self,
        columns: Dict[str, np.ndarray],
        sharding: TrainSharding,
        global_batch: int,
        dtype=np.float32,
    ):
        self.sharding = sharding
        self.global_batch = int(global_batch)
        lengths = {np.asarray(c).shape[0] for c in columns.values()}
        if len(lengths) != 1:
            raise ValueError(f"inconsistent column lengths {lengths}")
        (n,) = lengths
        self.n_valid = n
        self.n_padded = sharding.padded_rows(n, self.global_batch)
        perm = sharding.deal_permutation(self.n_padded)
        pad = self.n_padded - n
        batch_sharding = sharding.ctx.batch
        self.arrays: Dict[str, jax.Array] = {}
        for name, col in columns.items():
            col = np.asarray(col)
            if col.dtype.kind == "f":
                col = col.astype(dtype)
            if pad:
                col = np.concatenate(
                    [col, np.zeros((pad,) + col.shape[1:], col.dtype)]
                )
            # the blessed device_put boundary (8·N row-remainder discipline;
            # one H2D per column per fit)
            self.arrays[name] = jax.device_put(col[perm], batch_sharding)
        mask = np.zeros(self.n_padded, np.float32)
        mask[:n] = 1.0
        self.arrays["__mask__"] = jax.device_put(mask[perm], batch_sharding)
        metrics.counter(
            MLMetrics.TRAIN_GROUP, MLMetrics.TRAIN_SHARD_INGEST_ROWS, n
        )
        metrics.counter(MLMetrics.TRAIN_GROUP, MLMetrics.TRAIN_SHARD_PAD_ROWS, pad)

    @property
    def local_rows(self) -> int:
        """Rows per data shard (padded; a multiple of the local batch)."""
        return self.n_padded // self.sharding.n_data

    @property
    def local_batch(self) -> int:
        return self.global_batch // self.sharding.n_data

    def __getitem__(self, name: str) -> jax.Array:
        return self.arrays[name]

    @property
    def mask(self) -> jax.Array:
        return self.arrays["__mask__"]


def resolve_train_sharding(devices=None) -> Optional[TrainSharding]:
    """The config-driven entry: a TrainSharding iff ``train.mesh`` is set.

    Unlike ``resolve_plan_sharding``, an EXPLICIT ``train.mesh=1`` resolves
    (width-1 deterministic program — the bit-stability reference point);
    unset/0 returns None and the legacy single-device paths run unchanged.
    Raises when the requested grid exceeds the visible devices — silently
    training narrower than asked for would invalidate every checkpoint and
    throughput assumption downstream.
    """
    from flink_ml_tpu.config import Options, config

    n_data = config.get(Options.TRAIN_MESH)
    if not n_data or int(n_data) < 1:
        return None
    n_model = config.get(Options.TRAIN_MESH_MODEL) or 1
    ensure_distributed()
    return TrainSharding(int(n_data), int(n_model), devices=devices)
