"""Expert-parallel mixture-of-experts dispatch — the ``ep`` axis primitive.

No analogue exists in the reference (its models are single coefficient
vectors); this completes the framework's parallelism vocabulary alongside
data (dp), model/tensor (tp), and sequence (sp, ``parallel/ring.py``)
sharding. The design is the standard switch-routing schedule:

- experts shard over the mesh axis (each shard owns ``E / n_shards``
  expert FFNs), tokens shard over the same axis;
- each shard routes its tokens top-1 (router logits → expert, gate prob),
  packs them into fixed-capacity per-expert slots (static shapes — tokens
  past an expert's capacity are dropped, the Switch-Transformer overflow
  rule, and their output contribution is zero);
- ONE ``all_to_all`` carries every slot to the shard owning its expert,
  the owner runs its experts' FFNs as one batched matmul pair, and the
  reverse ``all_to_all`` returns outputs to the token's home shard, where
  they combine scaled by the gate probability.

Per-step traffic is two all-to-alls of the capacity buffers — the exact
collective the task's "all-to-all" parallelism calls for — and every shape
is static, so the whole thing jits into one SPMD program.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.parallel.mesh import DATA_AXIS, MeshContext, get_mesh_context

__all__ = ["moe_ffn", "moe_ffn_sharded"]


def moe_ffn(x, router, w1, w2, axis_name: str, capacity: int):
    """Top-1 expert-parallel FFN inside a ``shard_map``.

    ``x [t, d]`` — this shard's tokens; ``router [d, E]`` replicated;
    ``w1 [e_local, d, h]`` / ``w2 [e_local, h, d]`` — this shard's experts
    (``E = e_local · n_shards``; expert ``e`` lives on shard ``e // e_local``).
    ``capacity`` — max tokens any (shard → expert) pair may send per step.
    Returns ``[t, d]`` with dropped-overflow tokens contributing zero.
    """
    n = jax.lax.psum(1, axis_name)
    t, d = x.shape
    e_local = w1.shape[0]
    E = e_local * n

    logits = x @ router  # [t, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [t] top-1
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]  # [t]

    # Position of each token within its expert's send queue (stable order);
    # tokens at position >= capacity overflow and are dropped.
    one_hot = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # [t, E]
    pos = jnp.cumsum(one_hot, axis=0) - 1  # position among same-expert tokens
    slot = jnp.sum(pos * one_hot, axis=1)  # [t]
    keep = slot < capacity

    # Pack: buffers [E, capacity, d] (+ a validity mask), then reshape the
    # leading axis to [n, e_local·capacity] rows for the all_to_all.
    # Overflowing tokens write to the out-of-range slot ``capacity`` so
    # mode="drop" discards them — routing them to slot 0 would race with the
    # legitimate occupant of slot 0.
    safe_slot = jnp.where(keep, slot, capacity)
    buf = jnp.zeros((E, capacity, d), x.dtype)
    buf = buf.at[expert, safe_slot].set(x, mode="drop")

    # all_to_all: split the expert axis across shards; shard s receives, from
    # every peer, the slots destined for ITS experts.
    recv = jax.lax.all_to_all(
        buf.reshape(n, e_local, capacity, d), axis_name, split_axis=0, concat_axis=0
    )  # [n (source shard), e_local, capacity, d]
    recv_tokens = recv.transpose(1, 0, 2, 3).reshape(e_local, n * capacity, d)

    # Each local expert processes all its received slots as one matmul pair.
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", recv_tokens, w1))
    out_tokens = jnp.einsum("ech,ehd->ecd", h, w2)  # [e_local, n·capacity, d]

    # Reverse all_to_all: route outputs back to each token's home shard.
    back = out_tokens.reshape(e_local, n, capacity, d).transpose(1, 0, 2, 3)
    returned = jax.lax.all_to_all(back, axis_name, split_axis=0, concat_axis=0)
    returned = returned.reshape(E, capacity, d)  # [E, capacity, d], home slots

    # Unpack: each kept token reads its slot and scales by its gate; slot
    # occupancy is shard-local, so ``keep`` alone decides who was served.
    gathered = returned[expert, jnp.where(keep, slot, 0)]  # [t, d]
    return jnp.where(keep[:, None], gathered * gate[:, None], 0.0)


@functools.cache
def _sharded_program(mesh, capacity: int):
    def per_shard(x, router, w1, w2):
        return moe_ffn(x, router, w1, w2, DATA_AXIS, capacity)

    tok = P(DATA_AXIS)
    exp = P(DATA_AXIS)
    return jax.jit(
        jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(tok, P(), exp, exp),
            out_specs=tok,
        )
    )


def moe_ffn_sharded(x, router, w1, w2, capacity: int, ctx: MeshContext = None):
    """Expert-parallel FFN over the mesh: ``x [T, d]`` sharded over tokens,
    ``w1 [E, d, h]`` / ``w2 [E, h, d]`` sharded over experts (both on the data
    axis; ``T`` and ``E`` must divide by its size), ``router [d, E]``
    replicated. ``capacity`` bounds tokens per (shard, expert) pair per step.
    """
    ctx = ctx or get_mesh_context()
    T, E = np.shape(x)[0], np.shape(w1)[0]
    if T % ctx.n_data or E % ctx.n_data:
        raise ValueError(
            f"tokens ({T}) and experts ({E}) must divide by the mesh axis "
            f"({ctx.n_data})"
        )
    return _sharded_program(ctx.mesh, capacity)(x, router, w1, w2)
