"""Device-mesh management: the framework's "cluster".

Reference mapping (SURVEY.md §2.9): a Flink cluster is JobManager + TaskManager slots and
the parallelism of a job is its slot count; here the "cluster" is a
``jax.sharding.Mesh`` over TPU chips and the parallelism is the mesh's ``data`` axis
size. The single-controller Python process plays the JobManager role (globally aligned
by construction — the whole SharedProgressAligner/epoch-watermark machinery of
``flink-ml-iteration`` collapses, see SURVEY.md §7.3); SPMD programs under ``jit`` play
the TaskManager role.

Axes:
  - ``data``  — batch (data-parallel) axis; every algorithm shards its input batch here.
    The analogue of ``rebalance()`` partitioning in the reference (SGD.java:90).
  - ``model`` — optional second axis for sharding very wide coefficient vectors /
    expert dims (tensor parallelism). Size 1 by default.

The mesh is process-global state (like the reference's StreamExecutionEnvironment),
managed via ``set_mesh_context``/``get_mesh_context`` or the ``mesh_context`` context
manager. Multi-host: construct with ``jax.devices()`` spanning hosts and identical code
runs SPMD over ICI/DCN — collectives are inserted by XLA from the sharding annotations.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "MeshContext",
    "get_mesh_context",
    "set_mesh_context",
    "mesh_context",
]

DATA_AXIS = "data"
MODEL_AXIS = "model"

_lock = threading.Lock()
_current: Optional["MeshContext"] = None


class MeshContext:
    """A device mesh plus the sharding vocabulary every algorithm uses.

    ``n_data`` × ``n_model`` device grid. All helpers return ``NamedSharding``s bound to
    this mesh, so jit'd programs get their collectives from XLA's SPMD partitioner.
    """

    def __init__(
        self,
        devices: Optional[Sequence[Any]] = None,
        n_data: Optional[int] = None,
        n_model: Optional[int] = None,
    ):
        # Unspecified axis sizes come from the runtime config tier (the
        # job-parallelism role of the reference's cluster config).
        from flink_ml_tpu.config import Options, config

        if n_model is None:
            n_model = config.get(Options.MESH_MODEL_AXIS_SIZE)
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if n_data is None:
            n_data = config.get(Options.MESH_DATA_AXIS_SIZE)
        if n_data is None:
            n_data = len(devices) // n_model
        if n_data * n_model > len(devices):
            raise ValueError(
                f"mesh {n_data}x{n_model} needs {n_data * n_model} devices, "
                f"got {len(devices)}"
            )
        grid = np.asarray(devices[: n_data * n_model]).reshape(n_data, n_model)
        self.mesh = Mesh(grid, (DATA_AXIS, MODEL_AXIS))
        self.n_data = n_data
        self.n_model = n_model

    # --- sharding vocabulary -------------------------------------------------
    @property
    def replicated(self) -> NamedSharding:
        """Model/broadcast sharding — every device holds a full copy.

        The analogue of ``.broadcast()`` + BroadcastUtils variables (SGD.java:89,
        KMeans.java:154): instead of shipping the model over the network each epoch,
        it is laid out replicated and XLA keeps the copies coherent."""
        return NamedSharding(self.mesh, P())

    @property
    def batch(self) -> NamedSharding:
        """Leading-dim sharded over ``data`` — for [n, ...] batches."""
        return NamedSharding(self.mesh, P(DATA_AXIS))

    @property
    def model_dim(self) -> NamedSharding:
        """Leading-dim sharded over ``model`` — for very wide coefficients (TP)."""
        return NamedSharding(self.mesh, P(MODEL_AXIS))

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    # --- data placement ------------------------------------------------------
    def pad_batch(self, n: int) -> int:
        """Rows of padding needed to make ``n`` divisible by the data-axis size."""
        r = n % self.n_data
        return 0 if r == 0 else self.n_data - r

    def shard_batch(self, array, pad_value=0.0) -> Tuple[jax.Array, int]:
        """Place a host [n, ...] array onto the mesh sharded over ``data``.

        Pads the batch to a multiple of the data-axis size (XLA requires even
        shards); returns (device_array, n_valid). Callers carry ``n_valid`` (or a
        weight column zeroed on padding) so padded rows never affect results — the
        moral equivalent of the reference's per-partition record counts.
        """
        array = np.asarray(array)
        pad = self.pad_batch(array.shape[0])
        if pad:
            array = np.concatenate(
                [array, np.full((pad,) + array.shape[1:], pad_value, array.dtype)]
            )
        return jax.device_put(array, self.batch), array.shape[0] - pad

    def replicate(self, array) -> jax.Array:
        return jax.device_put(array, self.replicated)

    def __repr__(self) -> str:
        return f"MeshContext(data={self.n_data}, model={self.n_model})"


def is_tpu_backend(devices) -> bool:
    """Whether every device is a TPU (the Mosaic/Pallas compile target)."""
    devices = list(devices)
    return bool(devices) and all(
        "TPU" in getattr(d, "device_kind", "") for d in devices
    )


def vma_of(x):
    """Varying-mesh-axes of a traced value (shard_map tracks these; Pallas
    out_shapes must declare them explicitly), or None outside shard_map."""
    import jax

    try:
        return jax.typeof(x).vma or None
    except Exception:
        return None


def get_mesh_context() -> MeshContext:
    """The process-global mesh; lazily created over all visible devices."""
    global _current
    with _lock:
        if _current is None:
            _current = MeshContext()
        return _current


def set_mesh_context(ctx: Optional[MeshContext]) -> None:
    global _current
    with _lock:
        _current = ctx


@contextlib.contextmanager
def mesh_context(ctx: MeshContext):
    """Temporarily install ``ctx`` as the global mesh (tests, multi-mesh programs)."""
    global _current
    with _lock:
        prev, _current = _current, ctx
    try:
        yield ctx
    finally:
        with _lock:
            _current = prev
