"""Device-mesh management: the framework's "cluster".

Reference mapping (SURVEY.md §2.9): a Flink cluster is JobManager + TaskManager slots and
the parallelism of a job is its slot count; here the "cluster" is a
``jax.sharding.Mesh`` over TPU chips and the parallelism is the mesh's ``data`` axis
size. The single-controller Python process plays the JobManager role (globally aligned
by construction — the whole SharedProgressAligner/epoch-watermark machinery of
``flink-ml-iteration`` collapses, see SURVEY.md §7.3); SPMD programs under ``jit`` play
the TaskManager role.

Axes:
  - ``slice`` — optional outermost axis modelling multi-slice (DCN-connected)
    topologies: devices within a slice talk over ICI, across slices over DCN.
    Size 1 by default (single slice; the axis then never appears in specs).
  - ``data``  — batch (data-parallel) axis; every algorithm shards its input batch here.
    The analogue of ``rebalance()`` partitioning in the reference (SGD.java:90).
  - ``model`` — optional axis for sharding very wide coefficient vectors /
    expert dims (tensor parallelism). Size 1 by default.

Multi-slice placement rules (SURVEY §2.9 comm backend): the batch shards over
``(slice, data)`` jointly (``data_axes``), so the ONLY per-step collective
that crosses DCN is the gradient/stat psum's slice-level reduction stage —
XLA lowers ``psum(x, ("slice", "data"))`` hierarchically: reduce-scatter/
all-reduce over ICI within each slice, then the slice-count-sized exchange
over DCN, then broadcast back over ICI. Model-axis collectives (TP margins,
one-hot crossings) and minibatch compute never leave a slice — the model
axis is always innermost. Programs that ignore the slice axis (specs naming
only ``data``/``model``) still run correctly on a multi-slice mesh: shard_map
replicates their inputs across slices and every slice computes identically —
correct, just redundant; the flagship trainers (SGD, MLP) scale across it.

The mesh is process-global state (like the reference's StreamExecutionEnvironment),
managed via ``set_mesh_context``/``get_mesh_context`` or the ``mesh_context`` context
manager. Multi-host: construct with ``jax.devices()`` spanning hosts and identical code
runs SPMD over ICI/DCN — collectives are inserted by XLA from the sharding annotations.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "SLICE_AXIS",
    "MeshContext",
    "get_mesh_context",
    "set_mesh_context",
    "mesh_context",
]

DATA_AXIS = "data"
MODEL_AXIS = "model"
SLICE_AXIS = "slice"

_lock = threading.Lock()
_current: Optional["MeshContext"] = None


class MeshContext:
    """A device mesh plus the sharding vocabulary every algorithm uses.

    ``n_data`` × ``n_model`` device grid. All helpers return ``NamedSharding``s bound to
    this mesh, so jit'd programs get their collectives from XLA's SPMD partitioner.
    """

    def __init__(
        self,
        devices: Optional[Sequence[Any]] = None,
        n_data: Optional[int] = None,
        n_model: Optional[int] = None,
        n_slices: int = 1,
    ):
        # Unspecified axis sizes come from the runtime config tier (the
        # job-parallelism role of the reference's cluster config).
        from flink_ml_tpu.config import Options, config

        if n_model is None:
            n_model = config.get(Options.MESH_MODEL_AXIS_SIZE)
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if n_data is None:
            n_data = config.get(Options.MESH_DATA_AXIS_SIZE)
        if n_data is None:
            n_data = len(devices) // (n_model * n_slices)
        # ``n_data`` is the PER-SLICE data width; devices must arrive
        # slice-major (jax.devices() orders multi-slice topologies that way),
        # so contiguity along the trailing axes stays intra-slice ICI.
        need = n_slices * n_data * n_model
        if need > len(devices):
            raise ValueError(
                f"mesh {n_slices}x{n_data}x{n_model} needs {need} devices, "
                f"got {len(devices)}"
            )
        grid = np.asarray(devices[:need]).reshape(n_slices, n_data, n_model)
        self.mesh = Mesh(grid, (SLICE_AXIS, DATA_AXIS, MODEL_AXIS))
        self.n_slices = n_slices
        # Total data-parallel shard count: row partitioning, local batches and
        # cache layouts all see slices as extra data shards.
        self.n_data = n_slices * n_data
        self.n_model = n_model

    # --- sharding vocabulary -------------------------------------------------
    @property
    def replicated(self) -> NamedSharding:
        """Model/broadcast sharding — every device holds a full copy.

        The analogue of ``.broadcast()`` + BroadcastUtils variables (SGD.java:89,
        KMeans.java:154): instead of shipping the model over the network each epoch,
        it is laid out replicated and XLA keeps the copies coherent."""
        return NamedSharding(self.mesh, P())

    @property
    def data_axes(self):
        """The mesh axes a batch dim shards over — ``("slice", "data")`` on a
        multi-slice mesh, plain ``"data"`` otherwise. Programs that scale
        across slices use this in their specs and gradient psums; XLA then
        lowers the reduction hierarchically (ICI within a slice, DCN across)."""
        return (SLICE_AXIS, DATA_AXIS) if self.n_slices > 1 else DATA_AXIS

    @property
    def batch(self) -> NamedSharding:
        """Leading-dim sharded over the data axes — for [n, ...] batches."""
        return NamedSharding(self.mesh, P(self.data_axes))

    @property
    def model_dim(self) -> NamedSharding:
        """Leading-dim sharded over ``model`` — for very wide coefficients (TP)."""
        return NamedSharding(self.mesh, P(MODEL_AXIS))

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    # --- data placement ------------------------------------------------------
    def pad_batch(self, n: int) -> int:
        """Rows of padding needed to make ``n`` divisible by the data-axis size."""
        r = n % self.n_data
        return 0 if r == 0 else self.n_data - r

    def shard_batch(self, array, pad_value=0.0) -> Tuple[jax.Array, int]:
        """Place a host [n, ...] array onto the mesh sharded over ``data``.

        Pads the batch to a multiple of the data-axis size (XLA requires even
        shards); returns (device_array, n_valid). Callers carry ``n_valid`` (or a
        weight column zeroed on padding) so padded rows never affect results — the
        moral equivalent of the reference's per-partition record counts.
        """
        array = np.asarray(array)
        pad = self.pad_batch(array.shape[0])
        if pad:
            array = np.concatenate(
                [array, np.full((pad,) + array.shape[1:], pad_value, array.dtype)]
            )
        return jax.device_put(array, self.batch), array.shape[0] - pad

    def replicate(self, array) -> jax.Array:
        return jax.device_put(array, self.replicated)

    def __repr__(self) -> str:
        extra = f", slices={self.n_slices}" if self.n_slices > 1 else ""
        return f"MeshContext(data={self.n_data}, model={self.n_model}{extra})"


def is_tpu_backend(devices) -> bool:
    """Whether every device is a TPU (the Mosaic/Pallas compile target)."""
    devices = list(devices)
    return bool(devices) and all(
        "TPU" in getattr(d, "device_kind", "") for d in devices
    )


def vma_of(x):
    """Varying-mesh-axes of a traced value (shard_map tracks these; Pallas
    out_shapes must declare them explicitly), or None outside shard_map."""
    import jax

    try:
        return jax.typeof(x).vma or None
    except Exception:
        return None


def shape_dtype_struct(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` with the vma annotation when this jax supports
    it (>= 0.6); older jaxlibs have no varying-axes tracking to annotate."""
    import jax

    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def get_mesh_context() -> MeshContext:
    """The process-global mesh; lazily created over all visible devices."""
    global _current
    with _lock:
        if _current is None:
            _current = MeshContext()
        return _current


def set_mesh_context(ctx: Optional[MeshContext]) -> None:
    global _current
    with _lock:
        _current = ctx


@contextlib.contextmanager
def mesh_context(ctx: MeshContext):
    """Temporarily install ``ctx`` as the global mesh (tests, multi-mesh programs)."""
    global _current
    with _lock:
        prev, _current = _current, ctx
    try:
        yield ctx
    finally:
        with _lock:
            _current = prev
