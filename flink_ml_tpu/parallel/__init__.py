"""Distributed substrate: device mesh, shardings, collectives.

This package replaces the reference's entire communication stack (SURVEY.md §2.9/§5.8):
Flink's Netty network shuffles + ``AllReduceImpl``'s 3-stage chunked dataflow become XLA
collectives over the ICI mesh, and the broadcast-variable machinery becomes replicated
shardings. There is no hand-written transport: the XLA runtime is the native backend.
"""
from flink_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    MeshContext,
    get_mesh_context,
    set_mesh_context,
    mesh_context,
)
from flink_ml_tpu.parallel.collectives import (
    BLOCK_ROWS,
    all_reduce_sum,
    all_reduce_mean,
    block_partials,
    mapreduce_sum,
    psum_tree,
    shard_batch_spec,
    tree_fold_sum,
)
from flink_ml_tpu.parallel.train_sharding import (
    ShardedTrainCache,
    TrainSharding,
    ensure_distributed,
    resolve_train_sharding,
)
from flink_ml_tpu.parallel.quantile import QuantileSummary
from flink_ml_tpu.parallel.ring import ring_attention, ring_attention_sharded
from flink_ml_tpu.parallel.moe import moe_ffn, moe_ffn_sharded
from flink_ml_tpu.parallel.datastream_utils import (
    aggregate,
    co_group,
    co_group_cache,
    distributed_quantiles,
    distributed_sort,
    distributed_sort_cache,
    map_partition,
    reduce,
    sample,
    sample_cache,
)

__all__ = [
    "moe_ffn",
    "moe_ffn_sharded",
    "ring_attention",
    "ring_attention_sharded",
    "DATA_AXIS",
    "MODEL_AXIS",
    "MeshContext",
    "get_mesh_context",
    "set_mesh_context",
    "mesh_context",
    "all_reduce_sum",
    "all_reduce_mean",
    "psum_tree",
    "shard_batch_spec",
    "BLOCK_ROWS",
    "block_partials",
    "mapreduce_sum",
    "tree_fold_sum",
    "TrainSharding",
    "ShardedTrainCache",
    "resolve_train_sharding",
    "ensure_distributed",
    "QuantileSummary",
    "aggregate",
    "co_group",
    "co_group_cache",
    "distributed_quantiles",
    "distributed_sort",
    "distributed_sort_cache",
    "map_partition",
    "reduce",
    "sample",
    "sample_cache",
]
