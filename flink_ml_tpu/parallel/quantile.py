"""Greenwald-Khanna approximate quantile summary (mergeable sketch).

Reference: ``flink-ml-lib/.../common/util/QuantileSummary.java:42`` — the GK01
"Space-efficient Online Computation of Quantile Summaries" sketch used by
RobustScaler and KBinsDiscretizer. Each summary holds tuples (value, g, delta)
where g is the gap in min-rank to the previous tuple and delta the max-rank
slack; inserts buffer into a head buffer, compression merges adjacent tuples
while g_i + g_{i+1} + delta_{i+1} stays under 2·eps·count, and two summaries
merge by interleaving with delta inflation — making the sketch associative, the
property that lets every mesh shard sketch its rows independently and a single
host-side merge produce the global quantiles (the reference does the same per
Flink subtask and merges in a parallelism-1 operator).

TPU-build deviations (shape, not semantics):
  - storage is flat numpy arrays (values[], g[], delta[]) instead of per-tuple
    objects, and inserts are whole-chunk vectorized merges — one ``insert_all``
    of a million-row column costs two sorts, not a million list appends;
  - the structure is mutated in place (the reference returns fresh copies).
"""
from __future__ import annotations

import math
from typing import Iterable, Optional, Sequence, Union

import numpy as np

__all__ = ["QuantileSummary"]

_DEFAULT_HEAD_SIZE = 50_000
_DEFAULT_COMPRESS_THRESHOLD = 10_000


class QuantileSummary:
    """GK sketch over a stream of doubles; query error is ``relative_error`` ranks."""

    def __init__(
        self,
        relative_error: float = 0.001,
        compress_threshold: int = _DEFAULT_COMPRESS_THRESHOLD,
    ):
        if not 0 <= relative_error <= 1:
            raise ValueError("An appropriate relative error must be in the range [0, 1].")
        if compress_threshold <= 0:
            raise ValueError("A compress threshold must be greater than 0.")
        self.relative_error = relative_error
        self.compress_threshold = compress_threshold
        self.count = 0
        self.values = np.empty(0, np.float64)
        self.g = np.empty(0, np.int64)
        self.delta = np.empty(0, np.int64)
        self._head: list = []  # list of numpy chunks, concatenated at flush
        self._head_n = 0

    # --- write side ----------------------------------------------------------
    def insert(self, item: float) -> "QuantileSummary":
        """Ref QuantileSummary.insert — buffered single insert."""
        return self.insert_all(np.asarray([item], np.float64))

    def insert_all(self, items: Union[np.ndarray, Iterable[float]]) -> "QuantileSummary":
        """Vectorized chunk insert (the TPU-build batch path): chunks stay numpy
        arrays in the head buffer and concatenate once at flush — no per-item
        boxing."""
        arr = np.asarray(items if isinstance(items, np.ndarray) else list(items), np.float64).ravel()
        if arr.size == 0:
            return self
        self._head.append(arr)
        self._head_n += arr.size
        if self._head_n >= _DEFAULT_HEAD_SIZE:
            self._flush_head()
            if len(self.values) >= self.compress_threshold:
                self.compress()
        return self

    def _flush_head(self) -> None:
        """Ref insertHeadBuffer — merge the sorted head buffer into the sampled
        tuples. New items get delta = floor(2·eps·count_before_flush), except an
        item placed at the very front or the very back of the summary (delta 0).
        """
        if not self._head:
            return
        chunk = np.sort(np.concatenate(self._head))
        self._head = []
        self._head_n = 0
        old_n = len(self.values)
        m = chunk.size
        # Position of each new item among existing tuples: existing tuples with
        # value <= item precede it (ref: `sampled[cursor].value <= sorted[i]`).
        pos = np.searchsorted(self.values, chunk, side="right")

        delta_new = np.full(m, math.floor(2.0 * self.relative_error * self.count), np.int64)
        # First new item that lands before every existing tuple starts the summary.
        if m and (old_n == 0 or pos[0] == 0):
            delta_new[0] = 0
        # Last new item that lands after every existing tuple ends the summary.
        if m and pos[-1] == old_n:
            delta_new[-1] = 0

        # Interleave old tuples and the chunk by final position.
        total = old_n + m
        new_idx = pos + np.arange(m)  # final slots of the chunk items
        values = np.empty(total, np.float64)
        g = np.empty(total, np.int64)
        delta = np.empty(total, np.int64)
        old_mask = np.ones(total, bool)
        old_mask[new_idx] = False
        values[new_idx] = chunk
        g[new_idx] = 1
        delta[new_idx] = delta_new
        values[old_mask] = self.values
        g[old_mask] = self.g
        delta[old_mask] = self.delta

        self.values, self.g, self.delta = values, g, delta
        self.count += m

    # --- compression ---------------------------------------------------------
    def compress(self) -> "QuantileSummary":
        """Ref QuantileSummary.compress — flush then COMPRESS with threshold
        2·eps·count."""
        self._flush_head()
        self._compress_internal(2.0 * self.relative_error * self.count)
        return self

    def _compress_internal(self, merge_threshold: float) -> None:
        """Ref compressInternal — right-to-left greedy merge of adjacent tuples
        while g_i + g_head + delta_head < threshold.

        The scalar scan accumulates ``head_g = Σ g[i..head]``; with the suffix
        sums ``G[i] = Σ g[i:]`` the merge condition for tuple ``i`` under head
        ``h`` is ``G[i] < threshold - delta[h] + G[h+1]`` — and since ``G`` is
        non-increasing in ``i``, once it fails it stays failed, so each run's
        boundary is ONE searchsorted instead of a per-tuple Python step. The
        host loop runs over *kept* tuples (bounded ~1/(2·eps)), not all n —
        the difference between O(n) Python iterations per flush and O(k·log n)
        at 10M-row fit scale. Merge decisions are integer-exact and identical
        to the scalar scan's: the integer LHS ``G[i] + delta[h] - G[h+1]`` is
        compared against ``ceil(threshold)`` in int64 (for integer x and real
        t, ``x < t`` iff ``x < ceil(t)``), so suffix sums near 2^63 — far past
        float64's 2^53 integer range — cannot flip a decision."""
        n = len(self.values)
        if n == 0:
            return
        # G[i] = sum(g[i:]); G[n] = 0. Non-increasing in i (g >= 1).
        G = np.zeros(n + 1, np.int64)
        G[:n] = np.cumsum(self.g[::-1])[::-1]
        keep: list = []
        head = n - 1
        int_threshold = math.ceil(merge_threshold)
        while head >= 1:
            bound = int_threshold - int(self.delta[head]) + int(G[head + 1])
            # tuples i in [1, head-1] merge while G[i] < bound; G[1:head] is
            # non-increasing, so the run ends at the LAST i with G[i] >= bound
            seg = G[1:head]
            n_keepable = int(np.searchsorted(-seg, -bound, side="right"))
            if n_keepable == 0:  # everything down to 1 merges into this head
                keep.append((head, int(G[1] - G[head + 1])))
                head = 0
            else:
                new_head = n_keepable  # position in [1, head-1]
                keep.append((head, int(G[new_head + 1] - G[head + 1])))
                head = new_head
        if not keep:  # n == 1: the single tuple is kept as-is
            keep.append((0, int(self.g[0])))
        keep.reverse()
        idx = np.asarray([k[0] for k in keep], np.int64)
        gs = np.asarray([k[1] for k in keep], np.int64)
        if self.values[0] <= self.values[idx[0]] and n > 1:
            idx = np.concatenate([[0], idx])
            gs = np.concatenate([[self.g[0]], gs])
        self.values = self.values[idx]
        self.delta = self.delta[idx]
        self.g = gs

    # --- merge ---------------------------------------------------------------
    def merge(self, other: "QuantileSummary") -> "QuantileSummary":
        """Ref QuantileSummary.merge — interleave two compressed summaries,
        inflating deltas of interior tuples by the other side's error budget,
        then compress at the merged threshold. Returns self (mutated)."""
        if self._head or other._head:
            raise ValueError("Both summaries must be compressed before merge.")
        if other.count == 0:
            return self
        if self.count == 0:
            self.relative_error = other.relative_error
            self.count = other.count
            self.values = other.values.copy()
            self.g = other.g.copy()
            self.delta = other.delta.copy()
            return self

        add_self = math.floor(2.0 * other.relative_error * other.count)
        add_other = math.floor(2.0 * self.relative_error * self.count)
        # Merge order: on ties the other side's tuple goes first
        # (ref: `if (selfSample.value < otherSample.value)` else take other).
        # A self tuple is preceded by >=1 other tuple iff some other value <= it;
        # an other tuple is preceded by >=1 self tuple iff some self value < it.
        n_other_before_self = np.searchsorted(other.values, self.values, side="right")
        n_self_before_other = np.searchsorted(self.values, other.values, side="left")
        delta_self = self.delta + np.where(n_other_before_self > 0, add_self, 0)
        delta_other = other.delta + np.where(n_self_before_other > 0, add_other, 0)

        pos_self = n_other_before_self + np.arange(len(self.values))
        total = len(self.values) + len(other.values)
        values = np.empty(total, np.float64)
        g = np.empty(total, np.int64)
        delta = np.empty(total, np.int64)
        self_mask = np.zeros(total, bool)
        self_mask[pos_self] = True
        values[self_mask] = self.values
        g[self_mask] = self.g
        delta[self_mask] = delta_self
        values[~self_mask] = other.values
        g[~self_mask] = other.g
        delta[~self_mask] = delta_other

        self.relative_error = max(self.relative_error, other.relative_error)
        self.count += other.count
        self.values, self.g, self.delta = values, g, delta
        self._compress_internal(2.0 * self.relative_error * self.count)
        return self

    # --- query ---------------------------------------------------------------
    def query(self, percentiles: Union[float, Sequence[float]]) -> Union[float, np.ndarray]:
        """Ref QuantileSummary.query — approximate quantiles at the given
        percentiles (requires a compressed summary)."""
        scalar = np.isscalar(percentiles)
        ps = np.atleast_1d(np.asarray(percentiles, np.float64))
        if np.any((ps < 0) | (ps > 1)):
            raise ValueError("percentile should be in the range [0.0, 1.0].")
        if self._head:
            raise ValueError("Cannot operate on an uncompressed summary, call compress() first.")
        if len(self.values) == 0:
            raise ValueError("Cannot query percentiles without any records inserted.")

        min_rank = np.cumsum(self.g)
        max_rank = min_rank + self.delta
        target_error = float(np.max(self.delta + self.g)) / 2.0

        out = np.empty(len(ps), np.float64)
        for i, p in enumerate(ps):
            if p <= self.relative_error:
                out[i] = self.values[0]
            elif p >= 1.0 - self.relative_error:
                out[i] = self.values[-1]
            else:
                rank = math.ceil(p * self.count)
                ok = (max_rank - target_error < rank) & (rank <= min_rank + target_error)
                # Ref findApproximateQuantile: first satisfying tuple among all
                # but the last; default to the last value.
                ok = ok[:-1]
                out[i] = self.values[int(np.argmax(ok))] if ok.any() else self.values[-1]
        return float(out[0]) if scalar else out

    @property
    def is_empty(self) -> bool:
        return not self._head and len(self.values) == 0
