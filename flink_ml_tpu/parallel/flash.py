"""Fused (flash-style) local attention block for ring attention.

The ring schedule's hot op is the per-step fold: this shard's queries
against the currently resident KV block, folded into the streaming-softmax
accumulator (``parallel/ring.py``). The jnp form materializes the
``[B, H, Tq, Tk]`` score and probability tensors through HBM every step —
at long local sequence lengths that traffic, not the matmuls, bounds the
step.

This module fuses one fold into a Pallas kernel: per ``(batch·head,
Q-tile)`` grid cell, the scores for the whole resident KV block live only
in VMEM — matmul, mask, streaming-softmax rescale and the ``p @ v``
accumulation happen in one pass, and only the ``O(T·D)`` accumulator
state touches HBM. The numerics replicate the jnp fold exactly: running
max with ``-inf`` hygiene (rows with nothing attendable yet must not
produce NaNs), masked positions dropped before the exponential, and the
same correction factors.

Gradients: ``fused_fold`` carries a ``jax.custom_vjp`` whose backward
recomputes through the reference jnp fold, so ``jax.grad`` through ring
attention stays exact while the primal path takes the fused kernel. (The
backward therefore still materializes scores — a fused backward kernel is
a further optimization, not a correctness requirement.)

Availability: TPU compiled, or any backend under ``interpret=True``. The
caller (``ring.py``) falls back to the jnp fold when the local length does
not tile or the devices have no Mosaic backend.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_fold", "flash_available", "reference_fold", "TQ_TILE"]

TQ_TILE = 256  # Q rows per grid cell


_KV_VMEM_BUDGET = 1 << 20  # Tk*D f32 elements the kernel may stage per head
_TK_MAX = 16384  # score/probability buffers are [TQ_TILE, Tk] f32 in VMEM


def flash_available(T: int, D: int, devices=None) -> bool:
    """Whether the fused fold applies: Q tiles must divide the local length,
    one head's KV block AND the [TQ_TILE, Tk] score/probability buffers must
    fit the kernel's VMEM staging (the fold brings the whole resident block
    on-chip; past either budget the jnp fold's streamed HBM form is the
    right tool), and the devices must be TPUs (Mosaic target)."""
    from flink_ml_tpu.parallel.mesh import is_tpu_backend

    if T % TQ_TILE or T * D > _KV_VMEM_BUDGET or T > _TK_MAX:
        return False
    return is_tpu_backend(devices if devices is not None else jax.devices())


def reference_fold(q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, n_valid, scale):
    """The jnp fold in [B, H, ...] layout (ring.py numerics) — the source of
    truth the kernel is tested against and the backward recomputes through.

    ``q`` [B, H, Tq, D]; ``kb``/``vb`` [B, H, Tk, D]; ``m``/``l`` [B, H, Tq];
    ``acc`` [B, H, Tq, D]. ``q_pos0``/``k_pos0`` are the global positions of
    query/key 0 (traced scalars); ``n_valid`` masks keys at global positions
    >= it (None = unmasked).
    """
    Tq, Tk = q.shape[2], kb.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kb) * scale
    if causal or n_valid is not None:
        q_pos = q_pos0 + jnp.arange(Tq)
        k_pos = k_pos0 + jnp.arange(Tk)
        mask = jnp.ones((Tq, Tk), bool)
        if causal:
            mask &= q_pos[:, None] >= k_pos[None, :]
        if n_valid is not None:
            mask &= (k_pos < jnp.asarray(n_valid))[None, :]
        s = jnp.where(mask[None, None, :, :], s, -jnp.inf)
    block_max = jnp.max(s, axis=-1)
    new_m = jnp.maximum(m, block_max)
    safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    correction = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    new_l = l * correction + jnp.sum(p, axis=-1)
    new_acc = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
    return new_m, new_l, new_acc


def _fold_pallas(q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, n_valid, scale,
                 interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Tk = kb.shape[2]
    BH = B * H
    masked = n_valid is not None

    def kernel(scalars_ref, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
               mo_ref, lo_ref, ao_ref):
        j = pl.program_id(1)
        qt = q_ref[0]  # [TQ, D]
        s = jax.lax.dot_general(
            qt, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [TQ, Tk]
        if causal or masked:
            q_pos = (
                scalars_ref[0] + j * TQ_TILE
                + jax.lax.broadcasted_iota(jnp.int32, (TQ_TILE, Tk), 0)
            )
            k_pos = scalars_ref[1] + jax.lax.broadcasted_iota(
                jnp.int32, (TQ_TILE, Tk), 1
            )
            keep = jnp.ones((TQ_TILE, Tk), bool)
            if causal:
                keep &= q_pos >= k_pos
            if masked:
                keep &= k_pos < scalars_ref[2]
            s = jnp.where(keep, s, -jnp.inf)
        # m/l ride as [TQ, 1] columns (Mosaic wants >= 2-D tiles with an
        # aligned or full trailing dim); all the math stays 2-D.
        mcol = m_ref[0]  # [TQ, 1]
        new_m = jnp.maximum(mcol, jnp.max(s, axis=1, keepdims=True))
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        p = jnp.exp(s - safe_m)
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        correction = jnp.where(jnp.isneginf(mcol), 0.0, jnp.exp(mcol - safe_m))
        mo_ref[0] = new_m
        lo_ref[0] = l_ref[0] * correction + jnp.sum(p, axis=1, keepdims=True)
        ao_ref[0] = acc_ref[0] * correction + jnp.dot(
            p, v_ref[0], preferred_element_type=jnp.float32
        )

    scalars = jnp.stack(
        [
            jnp.asarray(q_pos0, jnp.int32),
            jnp.asarray(k_pos0, jnp.int32),
            jnp.asarray(0 if n_valid is None else n_valid, jnp.int32),
        ]
    )
    tile2 = pl.BlockSpec(
        (1, TQ_TILE, 1), lambda i, j, *_: (i, j, 0), memory_space=pltpu.VMEM
    )
    tile3 = pl.BlockSpec(
        (1, TQ_TILE, D), lambda i, j, *_: (i, j, 0), memory_space=pltpu.VMEM
    )
    full3 = pl.BlockSpec((1, Tk, D), lambda i, j, *_: (i, 0, 0), memory_space=pltpu.VMEM)
    from flink_ml_tpu.parallel.mesh import vma_of

    vma = vma_of(q)
    mo, lo, ao = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, Tq // TQ_TILE),
            in_specs=[tile3, full3, full3, tile2, tile2, tile3],
            out_specs=[tile2, tile2, tile3],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, 1), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((BH, Tq, 1), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((BH, Tq, D), jnp.float32, vma=vma),
        ],
        interpret=interpret,
    )(
        scalars,
        q.reshape(BH, Tq, D),
        kb.reshape(BH, Tk, D),
        vb.reshape(BH, Tk, D),
        m.reshape(BH, Tq, 1),
        l.reshape(BH, Tq, 1),
        acc.reshape(BH, Tq, D),
    )
    return mo.reshape(B, H, Tq), lo.reshape(B, H, Tq), ao.reshape(B, H, Tq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 11, 12))
def fused_fold(q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, has_n_valid,
               n_valid, scale, interpret=False):
    """One ring-attention fold, fused. Same contract as ``reference_fold``
    (``n_valid`` is a traced scalar consumed only when ``has_n_valid``);
    the primal runs the Pallas kernel, gradients recompute through the jnp
    fold. ``causal``/``has_n_valid``/``scale``/``interpret`` are static.
    """
    return _fold_pallas(
        q, kb, vb, m, l, acc, q_pos0, k_pos0, causal,
        n_valid if has_n_valid else None, scale, interpret=interpret,
    )


def _fused_fold_fwd(q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, has_n_valid,
                    n_valid, scale, interpret=False):
    out = fused_fold(
        q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, has_n_valid, n_valid,
        scale, interpret,
    )
    return out, (q, kb, vb, m, l, acc, q_pos0, k_pos0, n_valid)


def _fused_fold_bwd(causal, has_n_valid, scale, interpret, res, g):
    q, kb, vb, m, l, acc, q_pos0, k_pos0, n_valid = res
    _, vjp = jax.vjp(
        lambda q_, kb_, vb_, m_, l_, acc_: reference_fold(
            q_, kb_, vb_, m_, l_, acc_, q_pos0, k_pos0, causal,
            n_valid if has_n_valid else None, scale,
        ),
        q, kb, vb, m, l, acc,
    )
    dq, dkb, dvb, dm, dl, dacc = vjp(g)
    # integer position/count args carry no cotangent
    return dq, dkb, dvb, dm, dl, dacc, None, None, None


fused_fold.defvjp(_fused_fold_fwd, _fused_fold_bwd)
