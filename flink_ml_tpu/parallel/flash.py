"""Fused (flash-style) local attention block for ring attention.

The ring schedule's hot op is the per-step fold: this shard's queries
against the currently resident KV block, folded into the streaming-softmax
accumulator (``parallel/ring.py``). The jnp form materializes the
``[B, H, Tq, Tk]`` score and probability tensors through HBM every step —
at long local sequence lengths that traffic, not the matmuls, bounds the
step.

This module fuses one fold into a Pallas kernel: per ``(batch·head,
Q-tile)`` grid cell, the scores for the whole resident KV block live only
in VMEM — matmul, mask, streaming-softmax rescale and the ``p @ v``
accumulation happen in one pass, and only the ``O(T·D)`` accumulator
state touches HBM. The numerics replicate the jnp fold exactly: running
max with ``-inf`` hygiene (rows with nothing attendable yet must not
produce NaNs), masked positions dropped before the exponential, and the
same correction factors.

Gradients: ``fused_fold`` carries a ``jax.custom_vjp`` whose backward is
fused too — a hand-derived fold VJP (``reference_fold_bwd``, pinned
against jax AD including the ``-inf`` first-fold, masked-row and max-tie
edges) run as two Pallas kernels: a dq-kernel owning full score rows
(which also emits the row-level max/tie quantities) and a dkv-kernel
owning score columns with Q-axis grid accumulation. ``jax.grad`` through
ring attention is therefore exact and never materializes scores in HBM.

Availability: TPU compiled, or any backend under ``interpret=True``. The
caller (``ring.py``) falls back to the jnp fold when the local length does
not tile or the devices have no Mosaic backend.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = [
    "fused_fold",
    "flash_available",
    "flash_train_available",
    "reference_fold",
    "TQ_TILE",
]

TQ_TILE = 256  # Q rows per grid cell


_KV_VMEM_BUDGET = 1 << 20  # Tk*D f32 elements the kernel may stage per head
# T=8192 (with D=128, so T*D == _KV_VMEM_BUDGET) is the largest shape whose
# Mosaic compilation is verified on hardware; every admitted (T, D) then has
# score-buffer and KV footprints <= that shape's in all three kernels. 16384
# admitted shapes (e.g. T=16384, D=64) stage [TQ_TILE, 16384] f32 scores plus
# full KV — past the scoped-VMEM limit on paper and never compile-checked on
# chip, so they are rejected until verified.
_TK_MAX = 8192


def flash_available(T: int, D: int, devices=None) -> bool:
    """Whether the fused fold applies: Q tiles must divide the local length,
    one head's KV block AND the [TQ_TILE, Tk] score/probability buffers must
    fit the kernel's VMEM staging (the fold brings the whole resident block
    on-chip; past either budget the jnp fold's streamed HBM form is the
    right tool), and the devices must be TPUs (Mosaic target)."""
    from flink_ml_tpu.parallel.mesh import is_tpu_backend

    if T % TQ_TILE or T * D > _KV_VMEM_BUDGET or T > _TK_MAX:
        return False
    return is_tpu_backend(devices if devices is not None else jax.devices())


# Per-kernel-output VMEM envelope for the TRAINING (fwd+bwd) graph. Measured
# on a v5e chip: when the backward pallas_call's [B*H, T, D]-shaped outputs
# total near the 16 MB scoped-VMEM limit, XLA's latency optimizer places
# them in VMEM (S(1)) and the compile fails with a scoped-vmem OOM —
# observed failing at B*H*T*(D+2)*4 = 16.8-17.2 MB (B=1, T=8192, H=4,
# D=128) and succeeding at 8.4 MB (B=1, T=4096); forward-only graphs place
# the same outputs in HBM and compile fine up to flash_available's bounds.
# 9 MB admits every shape verified good and rejects the untested band up to
# the observed failures.
_TRAIN_OUT_VMEM_BUDGET = 9 << 20


def flash_train_available(T: int, D: int, batch: int, n_heads: int, devices=None) -> bool:
    """Whether the fused fold may serve a TRAINING step (fwd + the fused
    backward). Stricter than ``flash_available``: the backward graph's
    [batch*heads, T, D] pallas outputs must stay under
    ``_TRAIN_OUT_VMEM_BUDGET`` or XLA's VMEM output placement blows the
    scoped limit (see note above). Past the budget the jnp fold trains the
    same numbers through HBM — slower, never a compile failure."""
    if not flash_available(T, D, devices):
        return False
    return batch * n_heads * T * (D + 2) * 4 <= _TRAIN_OUT_VMEM_BUDGET


def reference_fold(q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, n_valid, scale):
    """The jnp fold in [B, H, ...] layout (ring.py numerics) — the source of
    truth the kernel is tested against and the backward recomputes through.

    ``q`` [B, H, Tq, D]; ``kb``/``vb`` [B, H, Tk, D]; ``m``/``l`` [B, H, Tq];
    ``acc`` [B, H, Tq, D]. ``q_pos0``/``k_pos0`` are the global positions of
    query/key 0 (traced scalars); ``n_valid`` masks keys at global positions
    >= it (None = unmasked).
    """
    Tq, Tk = q.shape[2], kb.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kb) * scale
    if causal or n_valid is not None:
        q_pos = q_pos0 + jnp.arange(Tq)
        k_pos = k_pos0 + jnp.arange(Tk)
        mask = jnp.ones((Tq, Tk), bool)
        if causal:
            mask &= q_pos[:, None] >= k_pos[None, :]
        if n_valid is not None:
            mask &= (k_pos < jnp.asarray(n_valid))[None, :]
        s = jnp.where(mask[None, None, :, :], s, -jnp.inf)
    block_max = jnp.max(s, axis=-1)
    new_m = jnp.maximum(m, block_max)
    safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    correction = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    new_l = l * correction + jnp.sum(p, axis=-1)
    new_acc = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
    return new_m, new_l, new_acc


def _fold_pallas(q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, n_valid, scale,
                 interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, D = q.shape
    Tk = kb.shape[2]
    BH = B * H
    masked = n_valid is not None

    def kernel(scalars_ref, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
               mo_ref, lo_ref, ao_ref):
        j = pl.program_id(1)
        qt = q_ref[0]  # [TQ, D]
        s = jax.lax.dot_general(
            qt, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [TQ, Tk]
        if causal or masked:
            q_pos = (
                scalars_ref[0] + j * TQ_TILE
                + jax.lax.broadcasted_iota(jnp.int32, (TQ_TILE, Tk), 0)
            )
            k_pos = scalars_ref[1] + jax.lax.broadcasted_iota(
                jnp.int32, (TQ_TILE, Tk), 1
            )
            keep = jnp.ones((TQ_TILE, Tk), bool)
            if causal:
                keep &= q_pos >= k_pos
            if masked:
                keep &= k_pos < scalars_ref[2]
            s = jnp.where(keep, s, -jnp.inf)
        # m/l ride as [TQ, 1] columns (Mosaic wants >= 2-D tiles with an
        # aligned or full trailing dim); all the math stays 2-D.
        mcol = m_ref[0]  # [TQ, 1]
        new_m = jnp.maximum(mcol, jnp.max(s, axis=1, keepdims=True))
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        p = jnp.exp(s - safe_m)
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        correction = jnp.where(jnp.isneginf(mcol), 0.0, jnp.exp(mcol - safe_m))
        mo_ref[0] = new_m
        lo_ref[0] = l_ref[0] * correction + jnp.sum(p, axis=1, keepdims=True)
        ao_ref[0] = acc_ref[0] * correction + jnp.dot(
            p, v_ref[0], preferred_element_type=jnp.float32
        )

    scalars = jnp.stack(
        [
            jnp.asarray(q_pos0, jnp.int32),
            jnp.asarray(k_pos0, jnp.int32),
            jnp.asarray(0 if n_valid is None else n_valid, jnp.int32),
        ]
    )
    tile2 = pl.BlockSpec(
        (1, TQ_TILE, 1), lambda i, j, *_: (i, j, 0), memory_space=pltpu.VMEM
    )
    tile3 = pl.BlockSpec(
        (1, TQ_TILE, D), lambda i, j, *_: (i, j, 0), memory_space=pltpu.VMEM
    )
    full3 = pl.BlockSpec((1, Tk, D), lambda i, j, *_: (i, 0, 0), memory_space=pltpu.VMEM)
    from flink_ml_tpu.parallel.mesh import shape_dtype_struct, vma_of

    vma = vma_of(q)
    mo, lo, ao = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, Tq // TQ_TILE),
            in_specs=[tile3, full3, full3, tile2, tile2, tile3],
            out_specs=[tile2, tile2, tile3],
        ),
        out_shape=[
            shape_dtype_struct((BH, Tq, 1), jnp.float32, vma=vma),
            shape_dtype_struct((BH, Tq, 1), jnp.float32, vma=vma),
            shape_dtype_struct((BH, Tq, D), jnp.float32, vma=vma),
        ],
        interpret=interpret,
    )(
        scalars,
        q.reshape(BH, Tq, D),
        kb.reshape(BH, Tk, D),
        vb.reshape(BH, Tk, D),
        m.reshape(BH, Tq, 1),
        l.reshape(BH, Tq, 1),
        acc.reshape(BH, Tq, D),
    )
    return mo.reshape(B, H, Tq), lo.reshape(B, H, Tq), ao.reshape(B, H, Tq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 11, 12))
def fused_fold(q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, has_n_valid,
               n_valid, scale, interpret=False):
    """One ring-attention fold, fused. Same contract as ``reference_fold``
    (``n_valid`` is a traced scalar consumed only when ``has_n_valid``);
    the primal runs the Pallas forward kernel and gradients run the fused
    backward kernels (``_fold_bwd_pallas``, AD-exact).
    ``causal``/``has_n_valid``/``scale``/``interpret`` are static.
    """
    return _fold_pallas(
        q, kb, vb, m, l, acc, q_pos0, k_pos0, causal,
        n_valid if has_n_valid else None, scale, interpret=interpret,
    )


def _fused_fold_fwd(q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, has_n_valid,
                    n_valid, scale, interpret=False):
    out = fused_fold(
        q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, has_n_valid, n_valid,
        scale, interpret,
    )
    return out, (q, kb, vb, m, l, acc, q_pos0, k_pos0, n_valid)


def _fused_fold_bwd(causal, has_n_valid, scale, interpret, res, g):
    q, kb, vb, m, l, acc, q_pos0, k_pos0, n_valid = res
    dm, dl, dacc = g
    dq, dkb, dvb, dm_in, dl_in, dacc_in = _fold_bwd_pallas(
        q, kb, vb, m, l, acc, q_pos0, k_pos0, causal,
        n_valid if has_n_valid else None, scale, dm, dl, dacc,
        interpret=interpret,
    )
    # integer position/count args carry no cotangent
    return dq, dkb, dvb, dm_in, dl_in, dacc_in, None, None, None


fused_fold.defvjp(_fused_fold_fwd, _fused_fold_bwd)


# ---------------------------------------------------------------------------
# Fused backward: the fold's hand-derived VJP (pinned against jax.vjp of
# reference_fold, including the -inf first-fold and masked-row edges) run as
# two Pallas kernels. The dq-kernel owns full score rows, so it computes the
# row-level quantities (safe max, block max, tie coefficient) once and hands
# them to the dkv-kernel, whose cells own score columns.
# ---------------------------------------------------------------------------

_TQ_BWD = 64  # Q rows per dq-kernel cell (3 [TQ, Tk] f32 buffers live at once)
_TK_BWD = 256  # K rows per dkv-kernel cell
_TQ_DKV = 2048  # Q rows per dkv accumulation step (third grid dim)


def reference_fold_bwd(q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, n_valid,
                       scale, dm, dl, dacc):
    """Hand-derived VJP of ``reference_fold`` — AD-equivalent (max ties split
    0.5/0.5 like ``jnp.maximum``; reduce-max ties spread evenly). The jnp
    source of truth the Pallas backward kernels are tested against."""
    Tq, Tk = q.shape[2], kb.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kb) * scale
    if causal or n_valid is not None:
        q_pos = q_pos0 + jnp.arange(Tq)
        k_pos = k_pos0 + jnp.arange(Tk)
        keep = jnp.ones((Tq, Tk), bool)
        if causal:
            keep &= q_pos[:, None] >= k_pos[None, :]
        if n_valid is not None:
            keep &= (k_pos < jnp.asarray(n_valid))[None, :]
        s = jnp.where(keep[None, None], s, -jnp.inf)
    B = jnp.max(s, axis=-1)
    new_m = jnp.maximum(m, B)
    safe = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
    P = jnp.exp(s - safe[..., None])
    P = jnp.where(jnp.isneginf(s), 0.0, P)
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe))

    dP = dl[..., None] + jnp.einsum("bhqd,bhkd->bhqk", dacc, vb)
    dv = jnp.einsum("bhqk,bhqd->bhkd", P, dacc)
    dcorr = dl * l + jnp.sum(dacc * acc, axis=-1)
    dl_in = dl * corr
    dacc_in = dacc * corr[..., None]
    ds = dP * P
    dsafe = -jnp.sum(dP * P, axis=-1) - dcorr * corr
    dm_in = jnp.where(jnp.isneginf(m), 0.0, dcorr * corr)
    dnew_m = dm + jnp.where(jnp.isneginf(new_m), 0.0, dsafe)
    take_m = jnp.where(m > B, 1.0, jnp.where(m == B, 0.5, 0.0))
    dm_in = dm_in + dnew_m * take_m
    dB = dnew_m * (1.0 - take_m)
    is_max = (s == B[..., None]) & ~jnp.isneginf(s)
    cnt = jnp.maximum(jnp.sum(is_max, axis=-1), 1)
    ds = ds + is_max * (dB / cnt)[..., None]
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kb) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q) * scale
    return dq, dk, dv, dm_in, dl_in, dacc_in


def _fold_bwd_pallas(q, kb, vb, m, l, acc, q_pos0, k_pos0, causal, n_valid,
                     scale, dm, dl, dacc, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from flink_ml_tpu.parallel.mesh import shape_dtype_struct, vma_of

    B_, H, Tq, D = q.shape
    Tk = kb.shape[2]
    BH = B_ * H
    masked = n_valid is not None
    # tiles clamp to the largest 256-aligned divisor of the actual dims
    # (flash_available guarantees T % 256 == 0, so these always divide)
    tq_bwd = min(_TQ_BWD, Tq)
    tk_bwd = min(_TK_BWD, Tk)
    tq_dkv = next(c for c in (_TQ_DKV, 1024, 512, 256, Tq) if Tq % c == 0)

    def mask_of(q_pos, k_pos):
        keep = jnp.ones(q_pos.shape, bool)
        if causal:
            keep &= q_pos >= k_pos
        return keep

    def dq_kernel(scalars_ref, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
                  dm_ref, dl_ref, dacc_ref,
                  dqo_ref, dmo_ref, dlo_ref, dao_ref, safe_ref, b_ref, dbc_ref):
        j = pl.program_id(1)
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [TQ, Tk]
        if causal or masked:
            q_pos = (
                scalars_ref[0] + j * tq_bwd
                + jax.lax.broadcasted_iota(jnp.int32, (tq_bwd, Tk), 0)
            )
            k_pos = scalars_ref[1] + jax.lax.broadcasted_iota(
                jnp.int32, (tq_bwd, Tk), 1
            )
            keep = mask_of(q_pos, k_pos)
            if masked:
                keep &= k_pos < scalars_ref[2]
            s = jnp.where(keep, s, -jnp.inf)
        mcol = m_ref[0]  # [TQ, 1]
        Bcol = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(mcol, Bcol)
        safe = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        P = jnp.exp(s - safe)
        P = jnp.where(jnp.isneginf(s), 0.0, P)
        corr = jnp.where(jnp.isneginf(mcol), 0.0, jnp.exp(mcol - safe))

        dlc = dl_ref[0]  # [TQ, 1]
        dP = dlc + jax.lax.dot_general(
            dacc_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [TQ, Tk]
        dPP = dP * P
        dcorr = dlc * l_ref[0] + jnp.sum(
            dacc_ref[0] * acc_ref[0], axis=1, keepdims=True
        )
        dsafe = -jnp.sum(dPP, axis=1, keepdims=True) - dcorr * corr
        dnew_m = dm_ref[0] + jnp.where(jnp.isneginf(new_m), 0.0, dsafe)
        take_m = jnp.where(mcol > Bcol, 1.0, jnp.where(mcol == Bcol, 0.5, 0.0))
        dB = dnew_m * (1.0 - take_m)
        is_max = (s == Bcol) & ~jnp.isneginf(s)
        cnt = jnp.maximum(jnp.sum(is_max.astype(jnp.float32), axis=1, keepdims=True), 1.0)
        dbc = dB / cnt
        ds = dPP + is_max.astype(jnp.float32) * dbc
        dqo_ref[0] = jax.lax.dot_general(
            ds, k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        dmo_ref[0] = jnp.where(jnp.isneginf(mcol), 0.0, dcorr * corr) + dnew_m * take_m
        dlo_ref[0] = dlc * corr
        dao_ref[0] = dacc_ref[0] * corr
        safe_ref[0] = safe
        b_ref[0] = Bcol
        dbc_ref[0] = dbc

    def dkv_kernel(scalars_ref, k_ref, v_ref, q_ref, dacc_ref, dl_ref,
                   safe_ref, b_ref, dbc_ref, dko_ref, dvo_ref):
        # grid (BH, ktiles, qtiles): the q axis is the innermost accumulation
        # dim — dk/dv blocks are revisited across it and accumulated in VMEM.
        jk = pl.program_id(1)
        jq = pl.program_id(2)
        s_col = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [TQ_DKV, TK]
        if causal or masked:
            q_pos = (
                scalars_ref[0] + jq * tq_dkv
                + jax.lax.broadcasted_iota(jnp.int32, (tq_dkv, tk_bwd), 0)
            )
            k_pos = (
                scalars_ref[1] + jk * tk_bwd
                + jax.lax.broadcasted_iota(jnp.int32, (tq_dkv, tk_bwd), 1)
            )
            keep = mask_of(q_pos, k_pos)
            if masked:
                keep &= k_pos < scalars_ref[2]
            s_col = jnp.where(keep, s_col, -jnp.inf)
        P_col = jnp.exp(s_col - safe_ref[0])
        P_col = jnp.where(jnp.isneginf(s_col), 0.0, P_col)
        dP_col = dl_ref[0] + jax.lax.dot_general(
            dacc_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        is_max = (s_col == b_ref[0]) & ~jnp.isneginf(s_col)
        ds_col = dP_col * P_col + is_max.astype(jnp.float32) * dbc_ref[0]
        dk_part = jax.lax.dot_general(
            ds_col, q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        dv_part = jax.lax.dot_general(
            P_col, dacc_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        @pl.when(jq == 0)
        def _():
            dko_ref[0] = jnp.zeros_like(dko_ref[0])
            dvo_ref[0] = jnp.zeros_like(dvo_ref[0])

        dko_ref[0] += dk_part
        dvo_ref[0] += dv_part

    scalars = jnp.stack(
        [
            jnp.asarray(q_pos0, jnp.int32),
            jnp.asarray(k_pos0, jnp.int32),
            jnp.asarray(0 if n_valid is None else n_valid, jnp.int32),
        ]
    )
    vma = vma_of(q)

    def col(tile):
        return pl.BlockSpec((1, tile, 1), lambda i, j, *_: (i, j, 0), memory_space=pltpu.VMEM)

    def mat(tile):
        return pl.BlockSpec((1, tile, D), lambda i, j, *_: (i, j, 0), memory_space=pltpu.VMEM)

    fullk_mat = pl.BlockSpec((1, Tk, D), lambda i, j, *_: (i, 0, 0), memory_space=pltpu.VMEM)

    def sds(shape):
        return shape_dtype_struct(shape, jnp.float32, vma=vma)

    q4 = q.reshape(BH, Tq, D)
    k4 = kb.reshape(BH, Tk, D)
    v4 = vb.reshape(BH, Tk, D)
    dacc4 = dacc.reshape(BH, Tq, D)
    dl4 = dl.reshape(BH, Tq, 1)
    dq_o, dm_o, dl_o, dacc_o, safe_r, b_r, dbc_r = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, Tq // tq_bwd),
            in_specs=[
                mat(tq_bwd), fullk_mat, fullk_mat,
                col(tq_bwd), col(tq_bwd), mat(tq_bwd),
                col(tq_bwd), col(tq_bwd), mat(tq_bwd),
            ],
            out_specs=[
                mat(tq_bwd), col(tq_bwd), col(tq_bwd), mat(tq_bwd),
                col(tq_bwd), col(tq_bwd), col(tq_bwd),
            ],
        ),
        out_shape=[
            sds((BH, Tq, D)), sds((BH, Tq, 1)), sds((BH, Tq, 1)),
            sds((BH, Tq, D)), sds((BH, Tq, 1)), sds((BH, Tq, 1)),
            sds((BH, Tq, 1)),
        ],
        interpret=interpret,
    )(
        scalars, q4, k4, v4,
        m.reshape(BH, Tq, 1), l.reshape(BH, Tq, 1), acc.reshape(BH, Tq, D),
        dm.reshape(BH, Tq, 1), dl4, dacc4,
    )

    kmat = pl.BlockSpec(
        (1, tk_bwd, D), lambda i, jk, jq, *_: (i, jk, 0), memory_space=pltpu.VMEM
    )
    qmat = pl.BlockSpec(
        (1, tq_dkv, D), lambda i, jk, jq, *_: (i, jq, 0), memory_space=pltpu.VMEM
    )
    qcol = pl.BlockSpec(
        (1, tq_dkv, 1), lambda i, jk, jq, *_: (i, jq, 0), memory_space=pltpu.VMEM
    )
    dk_o, dv_o = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, Tk // tk_bwd, Tq // tq_dkv),
            in_specs=[kmat, kmat, qmat, qmat, qcol, qcol, qcol, qcol],
            out_specs=[kmat, kmat],
        ),
        out_shape=[sds((BH, Tk, D)), sds((BH, Tk, D))],
        interpret=interpret,
    )(scalars, k4, v4, q4, dacc4, dl4, safe_r, b_r, dbc_r)

    return (
        dq_o.reshape(B_, H, Tq, D),
        dk_o.reshape(B_, H, Tk, D),
        dv_o.reshape(B_, H, Tk, D),
        dm_o.reshape(B_, H, Tq),
        dl_o.reshape(B_, H, Tq),
        dacc_o.reshape(B_, H, Tq, D),
    )
