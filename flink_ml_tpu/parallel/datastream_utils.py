"""The distributed-compute utility belt.

Reference: ``flink-ml-core/.../common/datastream/DataStreamUtils.java`` —
``sample:298`` (distributed reservoir), ``mapPartition:118``, ``reduce:153``
(two-stage partial → final), ``aggregate:236`` (createAccumulator/add/merge/
getResult), ``coGroup:409`` (sort-merge join with managed memory), plus the
global sort the evaluator builds on (BinaryClassificationEvaluator.java:178).

TPU-build shape: a "partition" is a contiguous row range of a columnar batch —
the slice a mesh shard owns (MeshContext splits batches the same way). Heavy
per-element work runs vectorized; the big sort runs on the device
(``jnp.sort`` over the [P, m] shard matrix — every shard sorted in one SPMD
program); the between-stage glue (splitters, bucket exchange, prefix merges)
is single-controller host code, the analogue of the reference's
parallelism-1 merge operators.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.parallel.mesh import MeshContext, get_mesh_context
from flink_ml_tpu.parallel.quantile import QuantileSummary

__all__ = [
    "map_partition",
    "aggregate",
    "reduce",
    "sample",
    "co_group",
    "distributed_sort",
    "distributed_sort_cache",
    "distributed_quantiles",
]

Columns = Dict[str, np.ndarray]


def _num_rows(columns: Columns) -> int:
    return int(next(iter(columns.values())).shape[0])


def _partition_slices(n: int, p: int) -> List[slice]:
    """Contiguous row ranges, one per "subtask" — the reference's rebalance()."""
    bounds = np.linspace(0, n, p + 1).astype(int)
    return [slice(int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])]


def map_partition(
    columns: Columns,
    fn: Callable[[Columns], object],
    ctx: Optional[MeshContext] = None,
) -> List[object]:
    """Apply ``fn`` once per partition (ref DataStreamUtils.mapPartition:118).

    ``fn`` receives a dict of row-range views; returns the list of per-partition
    results in partition order.
    """
    ctx = ctx or get_mesh_context()
    n = _num_rows(columns)
    return [
        fn({k: v[sl] for k, v in columns.items()})
        for sl in _partition_slices(n, ctx.n_data)
    ]


def aggregate(
    columns: Columns,
    create_accumulator: Callable[[], object],
    add: Callable[[object, Columns], object],
    merge: Callable[[object, object], object],
    get_result: Callable[[object], object] = lambda acc: acc,
    ctx: Optional[MeshContext] = None,
):
    """Two-stage aggregation (ref DataStreamUtils.aggregate:236): every
    partition folds its rows into an accumulator, a final single-controller
    stage merges the partials."""
    partials = map_partition(
        columns, lambda part: add(create_accumulator(), part), ctx=ctx
    )
    acc = partials[0]
    for other in partials[1:]:
        acc = merge(acc, other)
    return get_result(acc)


def reduce(
    columns: Columns,
    fn: Callable[[Columns, Columns], Columns],
    ctx: Optional[MeshContext] = None,
) -> Columns:
    """Two-stage reduce (ref DataStreamUtils.reduce:153): partial reduce per
    partition (here: the partition slice itself), then a parallelism-1 final
    reduce over the partials."""
    parts = map_partition(columns, lambda part: part, ctx=ctx)
    acc = parts[0]
    for other in parts[1:]:
        acc = fn(acc, other)
    return acc


def sample(
    columns: Columns,
    num_samples: int,
    seed: int = 0,
    chunk_rows: int = 1 << 16,
) -> Columns:
    """Uniform reservoir sample of ``num_samples`` rows (ref
    DataStreamUtils.sample:298, Algorithm R over the stream).

    Chunk-vectorized: per chunk, row i (globally) survives with probability
    num_samples/(i+1) into a uniformly random slot; numpy assignment applies
    duplicate slot writes in order, which reproduces the sequential algorithm.
    """
    n = _num_rows(columns)
    if num_samples >= n:
        return {k: v.copy() for k, v in columns.items()}
    rng = np.random.default_rng(seed)
    reservoir_idx = np.arange(num_samples)
    for lo in range(num_samples, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        gidx = np.arange(lo, hi)
        accept = rng.random(hi - lo) < num_samples / (gidx + 1.0)
        taken = gidx[accept]
        slots = rng.integers(0, num_samples, size=taken.size)
        reservoir_idx[slots] = taken  # later writes win, like sequential R
    return {k: v[reservoir_idx] for k, v in columns.items()}


def co_group(
    left_keys: np.ndarray,
    right_keys: np.ndarray,
) -> Iterator[Tuple[object, np.ndarray, np.ndarray]]:
    """Sort-merge co-group (ref DataStreamUtils.coGroup:409): yields
    ``(key, left_row_indices, right_row_indices)`` for every key present on
    either side, in key order. The reference sorts both inputs with managed
    memory and walks them together; here both sides argsort once and the walk
    is a vectorized boundary computation."""
    left_keys = np.asarray(left_keys)
    right_keys = np.asarray(right_keys)
    lo = np.argsort(left_keys, kind="stable")
    ro = np.argsort(right_keys, kind="stable")
    ls, rs = left_keys[lo], right_keys[ro]
    keys = np.union1d(ls, rs)
    l_start = np.searchsorted(ls, keys, side="left")
    l_end = np.searchsorted(ls, keys, side="right")
    r_start = np.searchsorted(rs, keys, side="left")
    r_end = np.searchsorted(rs, keys, side="right")
    for i, key in enumerate(keys):
        yield key, lo[l_start[i] : l_end[i]], ro[r_start[i] : r_end[i]]


def distributed_sort(
    keys: np.ndarray,
    values: Optional[Columns] = None,
    descending: bool = False,
    ctx: Optional[MeshContext] = None,
) -> List[Columns]:
    """Global sort by ``keys``, returned as ordered per-shard buckets.

    The reference's evaluator sorts globally by score via range partitioning
    (BinaryClassificationEvaluator.java:178). Stages here:

    1. splitter selection: p-1 quantiles of a strided key sample (host; the
       splitters only affect bucket *balance*, never correctness);
    2. bucket exchange: vectorized ``searchsorted`` routes each row to the
       bucket owning its key range — ``side='right'`` keeps all ties of a
       splitter value in one bucket, which is what lets callers group tied
       keys without cross-bucket fixups;
    3. one device program sorts every bucket in parallel: buckets pad to a
       common width with +inf and ``jnp.argsort`` runs row-wise over the
       [P, m] matrix (the sort is stable, so pad entries trail real entries).

    Returns ``n_data`` dicts, each with key ``"__key__"`` plus the value
    columns, globally ordered: every key in bucket b <= every key in b+1
    (reversed when descending). NaN keys are not supported.
    """
    ctx = ctx or get_mesh_context()
    keys = np.asarray(keys)
    values = values or {}
    n = keys.shape[0]
    p = ctx.n_data
    if n == 0:
        return [{"__key__": keys[:0], **{k: v[:0] for k, v in values.items()}}]

    # 1. splitters from a strided sample.
    if p > 1:
        stride = max(1, n // (p * 64))
        splitters = np.quantile(keys[::stride], np.linspace(0, 1, p + 1)[1:-1])
    else:
        splitters = np.empty(0, np.float64)

    # 2. bucket routing.
    bucket = np.searchsorted(splitters, keys, side="right")
    order = np.argsort(bucket, kind="stable")
    bounds = np.searchsorted(bucket[order], np.arange(p + 1))
    sizes = np.diff(bounds)

    # 3. all buckets sorted in ONE device program.
    width = int(sizes.max())
    mat = np.full((p, max(width, 1)), np.inf, np.float64)
    for b in range(p):
        mat[b, : sizes[b]] = keys[order[bounds[b] : bounds[b + 1]]]
    perm = np.asarray(jnp.argsort(jnp.asarray(mat), axis=1))

    out: List[Columns] = []
    for b in range(p):
        rows = order[bounds[b] : bounds[b + 1]][perm[b, : sizes[b]]]
        if descending:
            rows = rows[::-1]
        out.append({"__key__": keys[rows], **{k: v[rows] for k, v in values.items()}})
    return out[::-1] if descending else out


def distributed_sort_cache(
    cache,
    key_col: str,
    value_cols: Sequence[str] = (),
    descending: bool = False,
    bucket_rows: int = 1 << 20,
    spill_dir: Optional[str] = None,
    key_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
) -> Iterator[Columns]:
    """Out-of-core global sort over a host-tier cache — the external analogue
    of ``distributed_sort`` for datasets larger than host RAM.

    The reference sorts via managed memory with disk spill
    (``DataStreamUtils.java:409`` + the ``sort/`` package); here the same job
    is three streaming passes over a ``HostDataCache``:

    1. a mergeable GK sketch of the keys picks ``ceil(n / bucket_rows) - 1``
       range splitters (rank error only moves bucket *boundaries*, never
       ordering — same contract as the in-RAM splitter sample);
    2. every chunk routes its rows by ``searchsorted(side='right')`` into
       per-bucket spill caches (``memory_budget_bytes=0`` — the capacity tier
       holds them on disk; ties of one key always share a bucket);
    3. buckets load one at a time (the only thing ever resident is one
       ``bucket_rows``-sized bucket), sort on device, and yield in global
       order.

    Yields ``Columns`` dicts with ``"__key__"`` plus ``value_cols``, ordered
    like ``distributed_sort``'s bucket list. ``key_fn`` optionally derives
    the scalar sort key from the raw key column (e.g. the last column of a
    [n, c] rawPrediction). A heavily tied key can oversize its bucket (ties
    are indivisible under range partitioning — reference behavior too).
    NaN keys are not supported.
    """
    import shutil
    import tempfile

    from flink_ml_tpu.config import resolve_cache_config
    from flink_ml_tpu.iteration.datacache import HostDataCache

    n = int(cache.num_rows)
    if n == 0:
        return
    extract = key_fn or (lambda a: a)

    def chunk_keys(chunk: Columns) -> np.ndarray:
        return np.asarray(extract(np.asarray(chunk[key_col])), np.float64).ravel()

    n_buckets = max(1, -(-n // bucket_rows))
    if n_buckets > 1:
        sketch = QuantileSummary(0.001)
        for chunk in cache.iter_rows():
            sketch.insert_all(chunk_keys(chunk))
            sketch.compress()
        probs = np.linspace(0.0, 1.0, n_buckets + 1)[1:-1]
        splitters = np.unique(np.atleast_1d(sketch.query(probs)))
    else:
        splitters = np.empty(0, np.float64)
    n_buckets = len(splitters) + 1  # duplicate splitters merge buckets

    _, base_spill = resolve_cache_config(None, spill_dir)
    if base_spill is not None:
        os.makedirs(base_spill, exist_ok=True)
    own_dir = tempfile.mkdtemp(prefix="flinkml_sort_", dir=base_spill)
    try:
        buckets = [
            HostDataCache(memory_budget_bytes=0, spill_dir=f"{own_dir}/b{b}")
            for b in range(n_buckets)
        ]
        for chunk in cache.iter_rows():
            keys = chunk_keys(chunk)
            route = np.searchsorted(splitters, keys, side="right")
            order = np.argsort(route, kind="stable")
            bounds = np.searchsorted(route[order], np.arange(n_buckets + 1))
            for b in range(n_buckets):
                rows = order[bounds[b] : bounds[b + 1]]
                if rows.size:
                    buckets[b].append(
                        {
                            "__key__": keys[rows],
                            **{k: np.asarray(chunk[k])[rows] for k in value_cols},
                        }
                    )

        for b in reversed(range(n_buckets)) if descending else range(n_buckets):
            nb = int(buckets[b].num_rows)
            if nb == 0:
                continue
            cols = buckets[b].rows(0, nb)
            keys = np.asarray(cols["__key__"], np.float64)
            perm = np.asarray(jnp.argsort(jnp.asarray(keys)))
            if descending:
                perm = perm[::-1]
            yield {
                "__key__": keys[perm],
                **{k: np.asarray(cols[k])[perm] for k in value_cols},
            }
    finally:
        shutil.rmtree(own_dir, ignore_errors=True)


def distributed_quantiles(
    X: np.ndarray,
    probs: Sequence[float],
    relative_error: float = 0.001,
    ctx: Optional[MeshContext] = None,
) -> np.ndarray:
    """Per-column quantiles of ``X [n, d]`` via mergeable GK sketches.

    Every partition sketches its rows independently (``QuantileSummary`` per
    column), the host merges the sketches — the exact layout of the reference's
    RobustScaler/KBinsDiscretizer fit (per-subtask QuantileSummary + the
    parallelism-1 merge). Error is ``relative_error`` in *rank*, so results on
    small inputs (sketch below its compress threshold) are exact.
    """
    X = np.asarray(X, np.float64)
    if X.ndim == 1:
        X = X[:, None]
    d = X.shape[1]

    def sketch_partition(part: Columns) -> List[QuantileSummary]:
        block = part["x"]
        sketches = []
        for j in range(d):
            s = QuantileSummary(relative_error)
            s.insert_all(block[:, j])
            s.compress()
            sketches.append(s)
        return sketches

    partials = map_partition({"x": X}, sketch_partition, ctx=ctx)
    merged = partials[0]
    for other in partials[1:]:
        merged = [a.merge(b) for a, b in zip(merged, other)]
    probs = np.asarray(probs, np.float64)
    return np.stack([np.atleast_1d(s.query(probs)) for s in merged], axis=1)
