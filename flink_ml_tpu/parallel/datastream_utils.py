"""The distributed-compute utility belt.

Reference: ``flink-ml-core/.../common/datastream/DataStreamUtils.java`` —
``sample:298`` (distributed reservoir), ``mapPartition:118``, ``reduce:153``
(two-stage partial → final), ``aggregate:236`` (createAccumulator/add/merge/
getResult), ``coGroup:409`` (sort-merge join with managed memory), plus the
global sort the evaluator builds on (BinaryClassificationEvaluator.java:178).

TPU-build shape: a "partition" is a contiguous row range of a columnar batch —
the slice a mesh shard owns (MeshContext splits batches the same way). Heavy
per-element work runs vectorized; the big sort runs on the device
(``jnp.sort`` over the [P, m] shard matrix — every shard sorted in one SPMD
program); the between-stage glue (splitters, bucket exchange, prefix merges)
is single-controller host code, the analogue of the reference's
parallelism-1 merge operators.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.parallel.mesh import MeshContext, get_mesh_context
from flink_ml_tpu.parallel.quantile import QuantileSummary

__all__ = [
    "map_partition",
    "aggregate",
    "reduce",
    "sample",
    "sample_cache",
    "co_group",
    "co_group_cache",
    "distributed_sort",
    "distributed_sort_cache",
    "distributed_quantiles",
]

Columns = Dict[str, np.ndarray]


def _num_rows(columns: Columns) -> int:
    return int(next(iter(columns.values())).shape[0])


def _partition_slices(n: int, p: int) -> List[slice]:
    """Contiguous row ranges, one per "subtask" — the reference's rebalance()."""
    bounds = np.linspace(0, n, p + 1).astype(int)
    return [slice(int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])]


def map_partition(
    columns: Columns,
    fn: Callable[[Columns], object],
    ctx: Optional[MeshContext] = None,
    parallel: Optional[bool] = None,
) -> List[object]:
    """Apply ``fn`` once per partition (ref DataStreamUtils.mapPartition:118).

    ``fn`` receives a dict of row-range views; returns the list of per-partition
    results in partition order. ``parallel`` runs partitions on a thread pool
    — the analogue of the reference's per-subtask parallelism
    (DataStreamUtils.java:236): numpy-heavy ``fn``s (sketching, sorting,
    bincounts) release the GIL and scale with host cores. Default (None):
    threads when the host has more than one core and there is more than one
    partition; a single-core host or single partition stays in-line (a pool
    would only add overhead).

    Thread-safety contract: under the threaded default ``fn`` may run
    concurrently from multiple threads — exactly like a reference
    ``mapPartition`` UDF runs on concurrent subtasks — so an ``fn`` that
    mutates shared state must either synchronize it or be called with
    ``parallel=False`` to pin the sequential order."""
    ctx = ctx or get_mesh_context()
    n = _num_rows(columns)
    slices = _partition_slices(n, ctx.n_data)
    if parallel is None:
        parallel = len(slices) > 1 and (os.cpu_count() or 1) > 1
    if not parallel:
        return [fn({k: v[sl] for k, v in columns.items()}) for sl in slices]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(
        max_workers=min(len(slices), os.cpu_count() or 1)
    ) as pool:
        futures = [
            pool.submit(fn, {k: v[sl] for k, v in columns.items()})
            for sl in slices
        ]
        return [f.result() for f in futures]  # partition order preserved


def aggregate(
    columns: Columns,
    create_accumulator: Callable[[], object],
    add: Callable[[object, Columns], object],
    merge: Callable[[object, object], object],
    get_result: Callable[[object], object] = lambda acc: acc,
    ctx: Optional[MeshContext] = None,
):
    """Two-stage aggregation (ref DataStreamUtils.aggregate:236): every
    partition folds its rows into an accumulator, a final single-controller
    stage merges the partials."""
    partials = map_partition(
        columns, lambda part: add(create_accumulator(), part), ctx=ctx
    )
    acc = partials[0]
    for other in partials[1:]:
        acc = merge(acc, other)
    return get_result(acc)


def reduce(
    columns: Columns,
    fn: Callable[[Columns, Columns], Columns],
    ctx: Optional[MeshContext] = None,
    parallel: Optional[bool] = None,
    identity: Optional[Columns] = None,
) -> Columns:
    """Two-stage reduce (ref DataStreamUtils.reduce:153).

    ``fn`` is a record-level reducer: it receives two one-row column dicts and
    returns one (the reference's ``ReduceFunction`` over records). Stage 1
    folds every partition's OWN rows into a single-row partial — running on
    the ``map_partition`` thread belt, so partials compute concurrently like
    the reference's per-subtask partial-reduce operators; stage 2 is the
    parallelism-1 final fold over the per-partition partials. ``fn`` must be
    associative (any reduce's contract): the row-visit order within a
    partition is positional, but the partition boundaries move with the mesh's
    data-axis size.

    ``identity`` is the reducer's one-row neutral element (e.g. zeros for a
    sum). With it, an empty partition folds to ``identity`` instead of
    contributing nothing, and an all-empty input returns ``identity`` — the
    SAME zero-element semantics the device collective gives a masked-out
    shard (``collectives.mapreduce_sum`` over all-zero blocks), so a
    host-belt fold and a mesh-backed fold of the same data agree even when a
    shard owns no rows. Without it (the legacy default), empty partitions
    contribute no partial — like an empty subtask in the reference — and
    all-empty input returns the empty columns unchanged.
    """

    def partial(part: Columns) -> Optional[Columns]:
        n = _num_rows(part)
        if n == 0:
            return None if identity is None else dict(identity)
        acc = {k: v[0:1] for k, v in part.items()}
        for i in range(1, n):
            acc = fn(acc, {k: v[i : i + 1] for k, v in part.items()})
        return acc

    partials = [
        p
        for p in map_partition(columns, partial, ctx=ctx, parallel=parallel)
        if p is not None
    ]
    if not partials:
        if identity is not None:
            return dict(identity)
        return {k: v[0:0] for k, v in columns.items()}
    acc = partials[0]
    for other in partials[1:]:
        acc = fn(acc, other)
    return acc


def sample(
    columns: Columns,
    num_samples: int,
    seed: int = 0,
    chunk_rows: int = 1 << 16,
) -> Columns:
    """Uniform reservoir sample of ``num_samples`` rows (ref
    DataStreamUtils.sample:298, Algorithm R over the stream).

    Chunk-vectorized: per chunk, row i (globally) survives with probability
    num_samples/(i+1) into a uniformly random slot; numpy assignment applies
    duplicate slot writes in order, which reproduces the sequential algorithm.
    """
    n = _num_rows(columns)
    if num_samples >= n:
        return {k: v.copy() for k, v in columns.items()}
    rng = np.random.default_rng(seed)
    reservoir_idx = np.arange(num_samples)
    for lo in range(num_samples, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        gidx = np.arange(lo, hi)
        accept = rng.random(hi - lo) < num_samples / (gidx + 1.0)
        taken = gidx[accept]
        slots = rng.integers(0, num_samples, size=taken.size)
        reservoir_idx[slots] = taken  # later writes win, like sequential R
    return {k: v[reservoir_idx] for k, v in columns.items()}


def sample_cache(
    cache,
    num_samples: int,
    seed: int = 0,
) -> Columns:
    """``sample`` over a host-tier cache: one streaming pass of Algorithm R.

    The reservoir (``num_samples`` rows) is the only thing resident — the
    dataset streams chunk-by-chunk out of the capacity tier, so sampling a
    dataset far beyond host RAM costs one pass of disk reads. Same
    chunk-vectorized survival/slot trick as the in-RAM ``sample`` (ref
    DataStreamUtils.sample:298); results are a uniform ``num_samples``-subset
    regardless of how the cache happens to be chunked.
    """
    rng = np.random.default_rng(seed)
    reservoir: Columns = {}
    filled = 0  # rows 0..filled-1 of the reservoir are real
    seen = 0  # rows consumed from the stream so far

    for chunk in cache.iter_rows():
        chunk = {k: np.asarray(v) for k, v in chunk.items()}
        m = _num_rows(chunk)
        if not reservoir:
            reservoir = {
                k: np.empty((num_samples,) + v.shape[1:], v.dtype)
                for k, v in chunk.items()
            }
        lo = 0
        if filled < num_samples:  # fill phase
            take = min(num_samples - filled, m)
            for k, v in chunk.items():
                reservoir[k][filled : filled + take] = v[:take]
            filled += take
            seen += take
            lo = take
        if lo < m:  # replacement phase, chunk-vectorized
            gidx = np.arange(seen, seen + (m - lo))
            accept = rng.random(m - lo) < num_samples / (gidx + 1.0)
            taken = np.flatnonzero(accept) + lo
            slots = rng.integers(0, num_samples, size=taken.size)
            for k, v in chunk.items():
                reservoir[k][slots] = v[taken]  # later writes win, like sequential R
            seen += m - lo
    if filled < num_samples:
        return {k: v[:filled] for k, v in reservoir.items()}
    return reservoir


def co_group(
    left_keys: np.ndarray,
    right_keys: np.ndarray,
) -> Iterator[Tuple[object, np.ndarray, np.ndarray]]:
    """Sort-merge co-group (ref DataStreamUtils.coGroup:409): yields
    ``(key, left_row_indices, right_row_indices)`` for every key present on
    either side, in key order. The reference sorts both inputs with managed
    memory and walks them together; here both sides argsort once and the walk
    is a vectorized boundary computation."""
    left_keys = np.asarray(left_keys)
    right_keys = np.asarray(right_keys)
    lo = np.argsort(left_keys, kind="stable")
    ro = np.argsort(right_keys, kind="stable")
    ls, rs = left_keys[lo], right_keys[ro]
    keys = np.union1d(ls, rs)
    l_start = np.searchsorted(ls, keys, side="left")
    l_end = np.searchsorted(ls, keys, side="right")
    r_start = np.searchsorted(rs, keys, side="left")
    r_end = np.searchsorted(rs, keys, side="right")
    for i, key in enumerate(keys):
        yield key, lo[l_start[i] : l_end[i]], ro[r_start[i] : r_end[i]]


def _sketch_splitters(caches, key_of_chunk, n_buckets: int) -> np.ndarray:
    """Range splitters for ``n_buckets`` buckets: one GK sketch streamed over
    every cache's chunks (rank error only moves bucket *boundaries*, never
    ordering). Duplicate splitters collapse, merging their buckets."""
    if n_buckets <= 1:
        return np.empty(0, np.float64)
    sketch = QuantileSummary(0.001)
    for cache in caches:
        for chunk in cache.iter_rows():
            sketch.insert_all(key_of_chunk(chunk))
            sketch.compress()
    probs = np.linspace(0.0, 1.0, n_buckets + 1)[1:-1]
    return np.unique(np.atleast_1d(sketch.query(probs)))


def _spill_by_range(cache, key_of_chunk, value_cols, splitters, spill_prefix):
    """Route a cache's chunks into per-bucket spill caches by key range.

    ``side='right'`` keeps all ties of a splitter value in one bucket — the
    invariant both the external sort and the co-group lean on. Returns the
    bucket list plus the observed (dtype, trailing-shape) of each value
    column, so callers can manufacture dtype-consistent empties.
    """
    from flink_ml_tpu.iteration.datacache import HostDataCache

    n_buckets = len(splitters) + 1
    buckets = [
        HostDataCache(memory_budget_bytes=0, spill_dir=f"{spill_prefix}{b}")
        for b in range(n_buckets)
    ]
    col_specs: Dict[str, Tuple] = {}
    for chunk in cache.iter_rows():
        keys = key_of_chunk(chunk)
        route = np.searchsorted(splitters, keys, side="right")
        order = np.argsort(route, kind="stable")
        bounds = np.searchsorted(route[order], np.arange(n_buckets + 1))
        for k in value_cols:
            v = np.asarray(chunk[k])
            col_specs.setdefault(k, (v.dtype, v.shape[1:]))
        for b in range(n_buckets):
            rows = order[bounds[b] : bounds[b + 1]]
            if rows.size:
                buckets[b].append(
                    {
                        "__key__": keys[rows],
                        **{k: np.asarray(chunk[k])[rows] for k in value_cols},
                    }
                )
    return buckets, col_specs


def co_group_cache(
    left_cache,
    right_cache,
    key_col: str,
    left_value_cols: Sequence[str] = (),
    right_value_cols: Sequence[str] = (),
    bucket_rows: int = 1 << 20,
    spill_dir: Optional[str] = None,
) -> Iterator[Tuple[object, Columns, Columns]]:
    """Out-of-core sort-merge co-group over two host-tier caches.

    The reference's ``coGroup`` (DataStreamUtils.java:409) sorts both inputs
    through managed memory and walks them together; here both sides range-
    partition by *shared* splitters (a GK sketch over the union of keys), each
    bucket pair loads one at a time, and the in-RAM ``co_group`` walks the
    pair. Ties of one key always share a bucket (``side='right'`` routing), so
    no key group ever straddles buckets; the only resident state is one bucket
    from each side.

    Yields ``(key, left_rows, right_rows)`` in global key order, where the
    row dicts carry the requested value columns (empty-length arrays when a
    key is absent from one side).

    Keys share ``distributed_sort_cache``'s contract: treated as float64
    range-partition keys (NaN unsupported; integer keys above 2^53 can
    collide under the cast — unlike the in-RAM ``co_group``, which compares
    keys in their own dtype). A side whose cache holds zero rows has no
    observable column dtypes, so its value columns degrade to 1-D float64
    empties.
    """
    import shutil
    import tempfile

    from flink_ml_tpu.config import resolve_cache_config

    n_total = int(left_cache.num_rows) + int(right_cache.num_rows)
    if n_total == 0:
        return

    def key_of(chunk: Columns) -> np.ndarray:
        return np.asarray(chunk[key_col], np.float64).ravel()

    splitters = _sketch_splitters(
        (left_cache, right_cache), key_of, max(1, -(-n_total // bucket_rows))
    )
    n_buckets = len(splitters) + 1

    _, base_spill = resolve_cache_config(None, spill_dir)
    if base_spill is not None:
        os.makedirs(base_spill, exist_ok=True)
    own_dir = tempfile.mkdtemp(prefix="flinkml_cogroup_", dir=base_spill)
    try:
        sides = [
            _spill_by_range(cache, key_of, cols, splitters, f"{own_dir}/{tag}")
            for tag, cache, cols in (
                ("l", left_cache, left_value_cols),
                ("r", right_cache, right_value_cols),
            )
        ]

        def _load(buckets, specs, cols, b):
            nb = int(buckets[b].num_rows)
            if nb:
                return buckets[b].rows(0, nb)
            return {
                "__key__": np.empty(0, np.float64),
                **{
                    k: np.empty((0,) + specs[k][1], specs[k][0]) if k in specs else np.empty(0)
                    for k in cols
                },
            }

        for b in range(n_buckets):
            lcols = _load(sides[0][0], sides[0][1], left_value_cols, b)
            rcols = _load(sides[1][0], sides[1][1], right_value_cols, b)
            for key, lidx, ridx in co_group(lcols["__key__"], rcols["__key__"]):
                yield (
                    key,
                    {k: np.asarray(lcols[k])[lidx] for k in left_value_cols},
                    {k: np.asarray(rcols[k])[ridx] for k in right_value_cols},
                )
    finally:
        shutil.rmtree(own_dir, ignore_errors=True)


def distributed_sort(
    keys: np.ndarray,
    values: Optional[Columns] = None,
    descending: bool = False,
    ctx: Optional[MeshContext] = None,
) -> List[Columns]:
    """Global sort by ``keys``, returned as ordered per-shard buckets.

    The reference's evaluator sorts globally by score via range partitioning
    (BinaryClassificationEvaluator.java:178). Stages here:

    1. splitter selection: p-1 quantiles of a strided key sample (host; the
       splitters only affect bucket *balance*, never correctness);
    2. bucket exchange: vectorized ``searchsorted`` routes each row to the
       bucket owning its key range — ``side='right'`` keeps all ties of a
       splitter value in one bucket, which is what lets callers group tied
       keys without cross-bucket fixups;
    3. one device program sorts every bucket in parallel: buckets pad to a
       common width with +inf and ``jnp.argsort`` runs row-wise over the
       [P, m] matrix (the sort is stable, so pad entries trail real entries).

    Returns ``n_data`` dicts, each with key ``"__key__"`` plus the value
    columns, globally ordered: every key in bucket b <= every key in b+1
    (reversed when descending). NaN keys are not supported.
    """
    ctx = ctx or get_mesh_context()
    keys = np.asarray(keys)
    values = values or {}
    n = keys.shape[0]
    p = ctx.n_data
    if n == 0:
        return [{"__key__": keys[:0], **{k: v[:0] for k, v in values.items()}}]

    # 1. splitters from a strided sample.
    if p > 1:
        stride = max(1, n // (p * 64))
        splitters = np.quantile(keys[::stride], np.linspace(0, 1, p + 1)[1:-1])
    else:
        splitters = np.empty(0, np.float64)

    # 2. bucket routing.
    bucket = np.searchsorted(splitters, keys, side="right")
    order = np.argsort(bucket, kind="stable")
    bounds = np.searchsorted(bucket[order], np.arange(p + 1))
    sizes = np.diff(bounds)

    # 3. all buckets sorted in ONE device program.
    width = int(sizes.max())
    mat = np.full((p, max(width, 1)), np.inf, np.float64)
    for b in range(p):
        mat[b, : sizes[b]] = keys[order[bounds[b] : bounds[b + 1]]]
    perm = np.asarray(jnp.argsort(jnp.asarray(mat), axis=1))

    out: List[Columns] = []
    for b in range(p):
        rows = order[bounds[b] : bounds[b + 1]][perm[b, : sizes[b]]]
        if descending:
            rows = rows[::-1]
        out.append({"__key__": keys[rows], **{k: v[rows] for k, v in values.items()}})
    return out[::-1] if descending else out


def distributed_sort_cache(
    cache,
    key_col: str,
    value_cols: Sequence[str] = (),
    descending: bool = False,
    bucket_rows: int = 1 << 20,
    spill_dir: Optional[str] = None,
    key_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
) -> Iterator[Columns]:
    """Out-of-core global sort over a host-tier cache — the external analogue
    of ``distributed_sort`` for datasets larger than host RAM.

    The reference sorts via managed memory with disk spill
    (``DataStreamUtils.java:409`` + the ``sort/`` package); here the same job
    is three streaming passes over a ``HostDataCache``:

    1. a mergeable GK sketch of the keys picks ``ceil(n / bucket_rows) - 1``
       range splitters (rank error only moves bucket *boundaries*, never
       ordering — same contract as the in-RAM splitter sample);
    2. every chunk routes its rows by ``searchsorted(side='right')`` into
       per-bucket spill caches (``memory_budget_bytes=0`` — the capacity tier
       holds them on disk; ties of one key always share a bucket);
    3. buckets load one at a time (the only thing ever resident is one
       ``bucket_rows``-sized bucket), sort on device, and yield in global
       order.

    Yields ``Columns`` dicts with ``"__key__"`` plus ``value_cols``, ordered
    like ``distributed_sort``'s bucket list. ``key_fn`` optionally derives
    the scalar sort key from the raw key column (e.g. the last column of a
    [n, c] rawPrediction). A heavily tied key can oversize its bucket (ties
    are indivisible under range partitioning — reference behavior too).
    NaN keys are not supported.
    """
    import shutil
    import tempfile

    from flink_ml_tpu.config import resolve_cache_config

    n = int(cache.num_rows)
    if n == 0:
        return
    extract = key_fn or (lambda a: a)

    def chunk_keys(chunk: Columns) -> np.ndarray:
        return np.asarray(extract(np.asarray(chunk[key_col])), np.float64).ravel()

    splitters = _sketch_splitters((cache,), chunk_keys, max(1, -(-n // bucket_rows)))
    n_buckets = len(splitters) + 1  # duplicate splitters merge buckets

    _, base_spill = resolve_cache_config(None, spill_dir)
    if base_spill is not None:
        os.makedirs(base_spill, exist_ok=True)
    own_dir = tempfile.mkdtemp(prefix="flinkml_sort_", dir=base_spill)
    try:
        buckets, _ = _spill_by_range(cache, chunk_keys, value_cols, splitters, f"{own_dir}/b")

        for b in reversed(range(n_buckets)) if descending else range(n_buckets):
            nb = int(buckets[b].num_rows)
            if nb == 0:
                continue
            cols = buckets[b].rows(0, nb)
            keys = np.asarray(cols["__key__"], np.float64)
            perm = np.asarray(jnp.argsort(jnp.asarray(keys)))
            if descending:
                perm = perm[::-1]
            yield {
                "__key__": keys[perm],
                **{k: np.asarray(cols[k])[perm] for k in value_cols},
            }
    finally:
        shutil.rmtree(own_dir, ignore_errors=True)


def distributed_quantiles(
    X: np.ndarray,
    probs: Sequence[float],
    relative_error: float = 0.001,
    ctx: Optional[MeshContext] = None,
) -> np.ndarray:
    """Per-column quantiles of ``X [n, d]`` via mergeable GK sketches.

    Every partition sketches its rows independently (``QuantileSummary`` per
    column), the host merges the sketches — the exact layout of the reference's
    RobustScaler/KBinsDiscretizer fit (per-subtask QuantileSummary + the
    parallelism-1 merge). Error is ``relative_error`` in *rank*, so results on
    small inputs (sketch below its compress threshold) are exact.
    """
    X = np.asarray(X, np.float64)
    if X.ndim == 1:
        X = X[:, None]
    d = X.shape[1]

    def sketch_partition(part: Columns) -> List[QuantileSummary]:
        block = part["x"]
        sketches = []
        for j in range(d):
            s = QuantileSummary(relative_error)
            s.insert_all(block[:, j])
            s.compress()
            sketches.append(s)
        return sketches

    partials = map_partition({"x": X}, sketch_partition, ctx=ctx)
    merged = partials[0]
    for other in partials[1:]:
        merged = [a.merge(b) for a, b in zip(merged, other)]
    probs = np.asarray(probs, np.float64)
    return np.stack([np.atleast_1d(s.query(probs)) for s in merged], axis=1)
