"""Collectives: the AllReduce that replaces a 3-stage Flink dataflow.

Reference: ``flink-ml-core/.../common/datastream/AllReduceImpl.java:54-102`` implements
all-reduce as chunked reduce-scatter + all-gather over Flink network shuffles
(AllReduceSend:108 / AllReduceSum:146 / AllReduceRecv:202, 4KB-double chunks), and
``DataStreamUtils.allReduceSum:105`` is its public face used by SGD (SGD.java:130).

TPU-native: one ``jax.lax.psum`` over the ICI mesh — the chunking, routing and
reassembly are XLA's problem. Two usage styles:

1. **Implicit (preferred)**: write the global computation (e.g. a gradient mean over the
   full logical batch) under ``jit`` with the batch sharded over ``data``; XLA's SPMD
   partitioner inserts the psum. Most algorithms use this style.
2. **Explicit**: ``shard_map`` a per-shard function and call ``psum_tree`` inside —
   needed when per-device code is genuinely different (e.g. Pallas kernels) or when the
   reduction shape must be controlled by hand.

``all_reduce_sum``/``all_reduce_mean`` here are the explicit style packaged to match
``DataStreamUtils.allReduceSum`` semantics for host-resident arrays.
"""
from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.parallel.mesh import DATA_AXIS, MeshContext, get_mesh_context

__all__ = ["psum_tree", "all_reduce_sum", "all_reduce_mean", "shard_batch_spec"]


def psum_tree(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    """``lax.psum`` over every leaf of a pytree (inside shard_map/jit-SPMD only)."""
    return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, axis_name), tree)


def shard_batch_spec() -> P:
    """PartitionSpec for a leading-dim batch shard."""
    return P(DATA_AXIS)


@functools.lru_cache(maxsize=32)
def _shard_mapped_sum(mesh):
    def per_shard(x):
        return jax.lax.psum(jnp.sum(x, axis=0), DATA_AXIS)

    return jax.jit(
        jax.shard_map(per_shard, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P())
    )


def all_reduce_sum(array, ctx: MeshContext = None):
    """Sum [p, ...] partitions (or an [n, ...] batch) across the mesh → replicated result.

    Parity with ``DataStreamUtils.allReduceSum:105``: every "subtask" (device shard)
    contributes its partial, every device ends with the identical total.
    """
    ctx = ctx or get_mesh_context()
    x, _ = ctx.shard_batch(array)
    return _shard_mapped_sum(ctx.mesh)(x)


def all_reduce_mean(array, ctx: MeshContext = None):
    ctx = ctx or get_mesh_context()
    arr = jnp.asarray(array)
    n = arr.shape[0]
    x, _ = ctx.shard_batch(arr)
    return _shard_mapped_sum(ctx.mesh)(x) / n
