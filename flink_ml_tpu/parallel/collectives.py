"""Collectives: the AllReduce that replaces a 3-stage Flink dataflow.

Reference: ``flink-ml-core/.../common/datastream/AllReduceImpl.java:54-102`` implements
all-reduce as chunked reduce-scatter + all-gather over Flink network shuffles
(AllReduceSend:108 / AllReduceSum:146 / AllReduceRecv:202, 4KB-double chunks), and
``DataStreamUtils.allReduceSum:105`` is its public face used by SGD (SGD.java:130).

TPU-native: one ``jax.lax.psum`` over the ICI mesh — the chunking, routing and
reassembly are XLA's problem. Two usage styles:

1. **Implicit (preferred)**: write the global computation (e.g. a gradient mean over the
   full logical batch) under ``jit`` with the batch sharded over ``data``; XLA's SPMD
   partitioner inserts the psum. Most algorithms use this style.
2. **Explicit**: ``shard_map`` a per-shard function and call ``psum_tree`` inside —
   needed when per-device code is genuinely different (e.g. Pallas kernels) or when the
   reduction shape must be controlled by hand.

``all_reduce_sum``/``all_reduce_mean`` here are the explicit style packaged to match
``DataStreamUtils.allReduceSum`` semantics for host-resident arrays.

Deterministic mapreduce tier (PR 20, DrJAX-style — PAPERS.md): ``psum`` leaves
the reduction order to XLA, so the same global batch summed at mesh widths 1
and N can differ in the last ulp. The training tier's bit-stability contract
(docs/distributed_training.md) instead fixes the reduction *structure* in the
program itself: per-8-row-block partials folded in row order
(``block_partials``), an ``all_gather`` that reassembles the partials in
GLOBAL block order under the block-cyclic data deal
(``parallel/train_sharding.py``), and a balanced pairwise tree fold whose
shape depends only on the global block count (``tree_fold_sum``). Every add
is elementwise with a width-invariant association, so mesh widths 1/2/4/8
produce bit-identical epoch results by construction. ``mapreduce_sum`` is the
packaged primitive the sharded trainers call inside ``shard_map``.
"""
from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.parallel.mesh import DATA_AXIS, MeshContext, get_mesh_context

__all__ = [
    "psum_tree",
    "all_reduce_sum",
    "all_reduce_mean",
    "shard_batch_spec",
    "BLOCK_ROWS",
    "block_partials",
    "tree_fold_sum",
    "mapreduce_sum",
]

#: Row-block quantum of the deterministic mapreduce tier. Matches
#: ``servable.sharding.MIN_SHARD_ROWS`` — XLA's CPU gemv row-blocking works in
#: units of 8, so rows inside complete 8-row blocks are bit-invariant across
#: batch shapes (the PR 9 measurement the serving tier's remainder discipline
#: rests on); the training tier reduces in the same units so the per-row math
#: feeding the fold is itself width-stable.
BLOCK_ROWS = 8


def psum_tree(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    """``lax.psum`` over every leaf of a pytree (inside shard_map/jit-SPMD only)."""
    return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, axis_name), tree)


def shard_batch_spec() -> P:
    """PartitionSpec for a leading-dim batch shard."""
    return P(DATA_AXIS)


@functools.lru_cache(maxsize=32)
def _shard_mapped_sum(mesh):
    def per_shard(x):
        return jax.lax.psum(jnp.sum(x, axis=0), DATA_AXIS)

    return jax.jit(
        jax.shard_map(per_shard, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P())
    )


def all_reduce_sum(array, ctx: MeshContext = None):
    """Sum [p, ...] partitions (or an [n, ...] batch) across the mesh → replicated result.

    Parity with ``DataStreamUtils.allReduceSum:105``: every "subtask" (device shard)
    contributes its partial, every device ends with the identical total.
    """
    ctx = ctx or get_mesh_context()
    x, _ = ctx.shard_batch(array)
    return _shard_mapped_sum(ctx.mesh)(x)


def all_reduce_mean(array, ctx: MeshContext = None):
    ctx = ctx or get_mesh_context()
    arr = jnp.asarray(array)
    n = arr.shape[0]
    x, _ = ctx.shard_batch(arr)
    return _shard_mapped_sum(ctx.mesh)(x) / n


# --- deterministic mapreduce tier (see module docstring) ---------------------


def block_partials(x):
    """[rows, ...] → [rows / BLOCK_ROWS, ...] per-block sums, rows in order.

    The fold over each 8-row block is an explicit unrolled left chain —
    association fixed by the trace, every add elementwise — so a block's
    partial is a pure function of its 8 rows, independent of how many blocks
    sit around it. ``rows`` must be a multiple of BLOCK_ROWS (the
    train-sharding ingest discipline guarantees it).
    """
    rows = x.shape[0]
    if rows % BLOCK_ROWS:
        raise ValueError(
            f"deterministic reduce needs rows % {BLOCK_ROWS} == 0, got {rows}"
        )
    xb = x.reshape((rows // BLOCK_ROWS, BLOCK_ROWS) + x.shape[1:])
    acc = xb[:, 0]
    for r in range(1, BLOCK_ROWS):
        acc = acc + xb[:, r]
    return acc


def tree_fold_sum(blocks):
    """[G, ...] → [...] balanced pairwise tree fold over the leading axis.

    The tree's shape depends only on G — the GLOBAL block count, identical at
    every mesh width — and each level is one vectorized elementwise add
    (O(log G) ops vs the O(G) sequential chain a ``scan`` fold would issue).
    Odd levels pad one exact-zero block, which is additively inert bit-for-bit
    for finite values.
    """
    while blocks.shape[0] > 1:
        if blocks.shape[0] % 2:
            blocks = jnp.concatenate([blocks, jnp.zeros_like(blocks[:1])], axis=0)
        blocks = blocks[0::2] + blocks[1::2]
    return blocks[0]


def mapreduce_sum(x, axis_name: Optional[str] = None, axis_size: int = 1):
    """Deterministic global row-sum of a shard-local [rows, ...] batch.

    Call inside ``shard_map`` with the batch dealt block-cyclically over
    ``axis_name`` (``TrainSharding.deal_cache``): shard k holds global blocks
    k, k+N, k+2N, … in local order, so the gathered [N, L, ...] partial array
    transposes back to global block order with one swapaxes/reshape. The tree
    fold then runs replicated on every device over the same global sequence —
    the result is bit-identical across mesh widths, and identical to the
    width-1 program, by construction. With ``axis_name=None`` (width 1) the
    local blocks already ARE the global order and the gather is skipped; the
    fold structure is unchanged.
    """
    part = block_partials(x)
    if axis_name is not None and axis_size > 1:
        g = jax.lax.all_gather(part, axis_name, axis=0, tiled=False)  # [N, L, ...]
        # gathered[k, i] is global block k + i·N; swap to [L, N, ...] and
        # flatten so index i·N + k — the global block number — is restored.
        part = jnp.swapaxes(g, 0, 1).reshape((-1,) + g.shape[2:])
    return tree_fold_sum(part)
