"""Columnar in-memory DataFrame — the framework's Table analogue.

Reference: flink-ml-servable-core/.../servable/api/DataFrame.java:33 (column names +
data types + rows; ``addColumn`` at :100, ``collect`` at :119) and Row.java.

TPU-first departure: the reference stores row objects; here storage is **columnar** —
each column is either a numpy array ([n] scalars, [n, d] dense vectors) or a Python
list for ragged data (sparse vectors, strings of interest, arrays of varying length).
Columnar layout means a column can be handed to a jit'd program as a single device
array with zero per-row conversion, and batches stay large and static-shaped for XLA.
The row-oriented API (``collect`` -> Rows) is preserved at the boundary for parity.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

from flink_ml_tpu.api.types import BasicType, DataType, DataTypes, ScalarType, VectorType
from flink_ml_tpu.linalg.vectors import DenseVector, SparseVector, Vector

__all__ = ["DataFrame", "Row"]

Column = Union[np.ndarray, list]


class Row:
    """A row of values. Ref servable/api/Row.java."""

    __slots__ = ("values",)

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def get(self, index: int) -> Any:
        return self.values[index]

    def size(self) -> int:
        return len(self.values)

    def __getitem__(self, index: int) -> Any:
        return self.values[index]

    def __len__(self) -> int:
        return len(self.values)

    def __eq__(self, other) -> bool:
        if not isinstance(other, Row) or len(other) != len(self):
            return False
        for a, b in zip(self.values, other.values):
            if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
                if not np.array_equal(a, b):
                    return False
            elif a != b:
                return False
        return True

    def __repr__(self) -> str:
        return f"Row({self.values!r})"


def _column_length(col: Column) -> int:
    return int(col.shape[0]) if isinstance(col, np.ndarray) else len(col)


def _infer_type(col: Column) -> DataType:
    if isinstance(col, np.ndarray):
        if col.ndim == 2:
            return DataTypes.vector(BasicType.DOUBLE)
        if np.issubdtype(col.dtype, np.bool_):
            return DataTypes.BOOLEAN
        if np.issubdtype(col.dtype, np.integer):
            return DataTypes.LONG
        if np.issubdtype(col.dtype, np.floating):
            return DataTypes.DOUBLE
        return DataTypes.STRING
    for v in col:
        if v is None:
            continue
        if isinstance(v, Vector):
            return DataTypes.vector(BasicType.DOUBLE)
        if isinstance(v, bool):
            return DataTypes.BOOLEAN
        if isinstance(v, (int, np.integer)):
            return DataTypes.LONG
        if isinstance(v, (float, np.floating)):
            return DataTypes.DOUBLE
        if isinstance(v, str):
            return DataTypes.STRING
        break
    return DataTypes.STRING


def _normalize_column(col: Any) -> Column:
    """Canonicalize user input into a numpy array (dense/scalars) or list (ragged)."""
    if isinstance(col, np.ndarray):
        return col
    col = list(col)
    if col and isinstance(col[0], (list, tuple)):
        # Numeric lists of equal length densify to a [n, d] array; true ragged data
        # (token lists, strings, varying lengths) stays a Python list.
        try:
            arr = np.asarray(col)
            if arr.dtype.kind in "biufc" and arr.ndim == 2:
                return arr
        except (ValueError, TypeError):
            pass
        return col
    if col and isinstance(col[0], DenseVector):
        dims = {v.size() for v in col if v is not None}
        if len(dims) == 1 and not any(v is None for v in col):
            return np.stack([v.values for v in col])
        return col
    if col and isinstance(col[0], (SparseVector, str)) or any(v is None for v in col):
        return col
    try:
        arr = np.asarray(col)
        if arr.dtype != object:
            return arr
    except (ValueError, TypeError):
        pass  # ragged / mixed content stays a Python list
    return col


class DataFrame:  # graftcheck: serialized
    """Columnar table with a row-boundary API.

    Construct from columns (``DataFrame(names, types, columns)``) or rows
    (``DataFrame.from_rows``).

    Concurrency contract (the ``serialized`` mark above): a DataFrame is a
    request/response *value* — it crosses threads only through an ownership
    handoff (the batcher queue and its ``Event`` delivery, a datacache
    chunk boundary) that orders every access, and no two threads mutate one
    instance concurrently. graftcheck's shared-state-guard trusts this
    documented handoff instead of demanding a per-instance lock.
    """

    def __init__(
        self,
        column_names: Sequence[str],
        data_types: Optional[Sequence[DataType]] = None,
        columns: Sequence[Column] = (),
    ):
        self._names: List[str] = list(column_names)
        self._columns: List[Column] = [_normalize_column(c) for c in columns]
        if len(self._names) != len(self._columns):
            raise ValueError(
                f"{len(self._names)} column names but {len(self._columns)} columns"
            )
        if data_types is None:
            data_types = [_infer_type(c) for c in self._columns]
        self._types: List[DataType] = list(data_types)
        lengths = {_column_length(c) for c in self._columns}
        if len(lengths) > 1:
            raise ValueError(f"Columns have inconsistent lengths: {lengths}")

    # --- construction --------------------------------------------------------
    @staticmethod
    def from_rows(
        column_names: Sequence[str],
        rows: Iterable[Union[Row, Sequence[Any]]],
        data_types: Optional[Sequence[DataType]] = None,
    ) -> "DataFrame":
        rows = [r.values if isinstance(r, Row) else list(r) for r in rows]
        cols = (
            [_normalize_column([r[i] for r in rows]) for i in range(len(column_names))]
            if rows
            else [[] for _ in column_names]
        )
        return DataFrame(column_names, data_types, cols)

    @staticmethod
    def from_dict(data: Dict[str, Any]) -> "DataFrame":
        names = list(data.keys())
        return DataFrame(names, None, [data[n] for n in names])

    @staticmethod
    def concat(frames: Sequence["DataFrame"]) -> "DataFrame":
        """Row-concatenate DataFrames with identical schemas (column order and
        names must match; types are taken from the first frame). The serving
        micro-batcher's coalescing primitive, also behind ``serve_pending``."""
        if not frames:
            raise ValueError("concat of zero DataFrames")
        first = frames[0]
        if len(frames) == 1:
            return first.clone()
        names = first.get_column_names()
        for f in frames[1:]:
            if f.get_column_names() != names:
                raise ValueError(
                    f"schema mismatch in concat: {f.get_column_names()} != {names}"
                )
        cols: List[Column] = []
        for name in names:
            parts = [f.column(name) for f in frames]
            if all(isinstance(p, np.ndarray) for p in parts):
                cols.append(np.concatenate(parts))
            else:
                merged: list = []
                for p in parts:
                    merged.extend(p if isinstance(p, list) else list(p))
                cols.append(merged)
        return DataFrame(names, first.get_data_types(), cols)

    # --- schema --------------------------------------------------------------
    def get_column_names(self) -> List[str]:
        return list(self._names)

    @property
    def column_names(self) -> List[str]:
        return list(self._names)

    def get_data_types(self) -> List[DataType]:
        return list(self._types)

    def get_index(self, name: str) -> int:
        """Ref DataFrame.getIndex."""
        return self._names.index(name)

    def get_data_type(self, name: str) -> DataType:
        return self._types[self.get_index(name)]

    @property
    def num_rows(self) -> int:
        return _column_length(self._columns[0]) if self._columns else 0

    def __len__(self) -> int:
        return self.num_rows

    # --- column access -------------------------------------------------------
    def column(self, name: str) -> Column:
        return self._columns[self.get_index(name)]

    def __getitem__(self, name: str) -> Column:
        return self.column(name)

    def vectors(self, name: str) -> np.ndarray:
        """Column as a dense [n, d] float array (sparse vectors densified —
        use ``is_sparse``/``sparse_batch`` first when width matters)."""
        col = self.column(name)
        if isinstance(col, np.ndarray):
            if col.ndim == 1:
                return col.astype(np.float64)[:, None]
            return col
        return np.stack([v.to_array() if isinstance(v, Vector) else np.asarray(v) for v in col])

    def is_sparse(self, name: str) -> bool:
        """Whether the column holds SparseVectors (the wide-features layout)."""
        col = self.column(name)
        return isinstance(col, list) and bool(col) and isinstance(col[0], SparseVector)

    def sparse_batch(self, name: str):
        """Column as a padded-CSR SparseBatch (linalg/sparse_batch.py) — the
        layout that keeps Criteo-width features off the dense path entirely.
        A mixed column's occasional DenseVectors are converted row-wise, so
        anything ``is_sparse`` says yes to packs without error."""
        from flink_ml_tpu.linalg.sparse_batch import SparseBatch

        col = self.column(name)
        if not (isinstance(col, list) and col and all(isinstance(v, Vector) for v in col)):
            raise TypeError(f"column {name!r} is not a vector column")
        vecs = [v if isinstance(v, SparseVector) else v.to_sparse() for v in col]
        return SparseBatch.from_vectors(vecs)

    def scalars(self, name: str, dtype=np.float64) -> np.ndarray:
        col = self.column(name)
        if isinstance(col, np.ndarray):
            return col.astype(dtype)
        return np.asarray(col, dtype=dtype)

    # --- mutation-style API (returns self, ref DataFrame.addColumn:100) ------
    def add_column(self, name: str, data_type: DataType, values: Column) -> "DataFrame":
        values = _normalize_column(values)
        if self._columns and _column_length(values) != self.num_rows:
            raise ValueError(
                f"Column {name} has {_column_length(values)} rows, expected {self.num_rows}"
            )
        if name in self._names:
            idx = self.get_index(name)
            self._columns[idx] = values
            self._types[idx] = data_type
        else:
            self._names.append(name)
            self._types.append(data_type)
            self._columns.append(values)
        return self

    def with_column(self, name: str, values: Column, data_type: DataType = None) -> "DataFrame":
        """Functional variant: returns a new DataFrame with the column added/replaced."""
        values = _normalize_column(values)
        if data_type is None:
            data_type = _infer_type(values)
        out = self.clone()
        out.add_column(name, data_type, values)
        return out

    def select(self, names: Sequence[str]) -> "DataFrame":
        idxs = [self.get_index(n) for n in names]
        return DataFrame(
            [self._names[i] for i in idxs],
            [self._types[i] for i in idxs],
            [self._columns[i] for i in idxs],
        )

    def drop(self, *names: str) -> "DataFrame":
        keep = [n for n in self._names if n not in names]
        return self.select(keep)

    def take(self, indices) -> "DataFrame":
        """Row subset / reorder by integer indices or a boolean mask."""
        indices = np.asarray(indices)
        if indices.dtype == np.bool_:
            if indices.size != self.num_rows:
                raise IndexError(
                    f"boolean mask has {indices.size} entries for {self.num_rows} rows"
                )
            # normalize to positions so list (ragged) columns index correctly —
            # a raw bool mask would be treated as ints 0/1 by the list path
            indices = np.flatnonzero(indices)
        else:
            indices = indices.astype(np.int64)
        cols = [
            c[indices] if isinstance(c, np.ndarray) else [c[int(i)] for i in indices]
            for c in self._columns
        ]
        return DataFrame(list(self._names), list(self._types), cols)

    def clone(self) -> "DataFrame":
        return DataFrame(list(self._names), list(self._types), list(self._columns))

    # --- row boundary --------------------------------------------------------
    def _cell(self, col: Column, i: int) -> Any:
        if isinstance(col, np.ndarray):
            if col.ndim == 2:
                return DenseVector(col[i])
            v = col[i]
            if isinstance(v, np.integer):
                return int(v)
            if isinstance(v, np.floating):
                return float(v)
            if isinstance(v, np.bool_):
                return bool(v)
            return v
        return col[i]

    def collect(self) -> List[Row]:
        """Materialize as rows. Ref DataFrame.collect:119."""
        return [
            Row([self._cell(c, i) for c in self._columns]) for i in range(self.num_rows)
        ]

    def __repr__(self) -> str:
        return f"DataFrame(columns={self._names}, num_rows={self.num_rows})"
