"""Minimal data-type system for DataFrame schemas.

Reference: flink-ml-servable-core/.../servable/types/ (DataTypes.java, BasicType.java,
ScalarType.java, VectorType.java, MatrixType.java).
"""
from __future__ import annotations

from dataclasses import dataclass
from enum import Enum

__all__ = ["BasicType", "DataType", "ScalarType", "VectorType", "MatrixType", "DataTypes"]


class BasicType(Enum):
    BOOLEAN = "boolean"
    BYTE = "byte"
    SHORT = "short"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    STRING = "string"


class DataType:
    pass


@dataclass(frozen=True)
class ScalarType(DataType):
    element_type: BasicType


@dataclass(frozen=True)
class VectorType(DataType):
    element_type: BasicType


@dataclass(frozen=True)
class MatrixType(DataType):
    element_type: BasicType


class DataTypes:
    """Ref DataTypes.java constants/factories."""

    BOOLEAN = ScalarType(BasicType.BOOLEAN)
    INT = ScalarType(BasicType.INT)
    LONG = ScalarType(BasicType.LONG)
    FLOAT = ScalarType(BasicType.FLOAT)
    DOUBLE = ScalarType(BasicType.DOUBLE)
    STRING = ScalarType(BasicType.STRING)

    @staticmethod
    def vector(element_type: BasicType = BasicType.DOUBLE) -> VectorType:
        return VectorType(element_type)

    @staticmethod
    def matrix(element_type: BasicType = BasicType.DOUBLE) -> MatrixType:
        return MatrixType(element_type)
