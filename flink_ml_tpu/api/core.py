"""The five core stage interfaces.

Reference: flink-ml-core/src/main/java/org/apache/flink/ml/api/
  - ``Stage``        <- Stage.java:44   (WithParams + save(path) + static load(path))
  - ``Estimator``    <- Estimator.java:31,38  (fit(DataFrame...) -> Model)
  - ``AlgoOperator`` <- AlgoOperator.java:31  (transform(DataFrame...) -> DataFrame[])
  - ``Transformer``  <- Transformer.java:39   (marker for feature-engineering transforms)
  - ``Model``        <- Model.java:31,38,48   (Transformer + set/get_model_data)

Contract notes kept from the reference:
  - ``fit``/``transform`` take and return *lists* conceptually; for ergonomics the
    Python API accepts varargs and single-output stages return the single DataFrame
    (like the pyflink wrappers do, pyflink/ml/wrapper.py:221).
  - Model data is itself a DataFrame (the reference's model-data Table), so it can be
    inspected, streamed, and transferred between training and serving.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.params.param import WithParams
from flink_ml_tpu.utils import read_write as rw

__all__ = ["Stage", "Estimator", "AlgoOperator", "Transformer", "Model"]


class Stage(WithParams):
    """Base of all pipeline nodes; must be serializable via save/load. Ref Stage.java:44."""

    def save(self, path: str) -> None:
        rw.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "Stage":
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        stage = cls()
        stage.load_param_map_from_json(metadata["paramMap"])
        return stage

    def __repr__(self) -> str:
        shown = {p.name: v for p, v in self._param_map.items() if v != p.default_value}
        return f"{type(self).__name__}({shown})"


class AlgoOperator(Stage):
    """Computes outputs from inputs; the relational-algebra node. Ref AlgoOperator.java:31."""

    def transform(self, *inputs: DataFrame):
        raise NotImplementedError


class Transformer(AlgoOperator):
    """Marker: an AlgoOperator whose semantics is record-wise feature transformation.
    Ref Transformer.java:39."""

    @classmethod
    def load_servable(cls, path: str) -> "Transformer":
        """Stateless feature Transformers are their own runtime-free replica:
        params fully describe ``transform``, so the serving tier's
        ``load_servable`` dispatch (servable/api.py) restores the stage
        itself — what lets Tokenizer→HashingTF→… pipelines publish and serve
        (docs/sparse.md). Models carry model data and MUST override with a
        real servable pairing; the guard keeps a missing override the same
        hard error it always was, never a silently data-less servable."""
        if issubclass(cls, Model):
            raise RuntimeError(
                f"{cls.__name__}.load_servable(path) is not implemented."
            )
        return cls.load(path)


class Model(Transformer):
    """A Transformer with model data. Ref Model.java:31.

    ``set_model_data``/``get_model_data`` exchange model state as DataFrames
    (the reference's model-data Tables, Model.java:38,48), which is what makes
    online model streams and train/serve separation possible.
    """

    def set_model_data(self, *model_data: DataFrame) -> "Model":
        raise NotImplementedError

    def get_model_data(self) -> List[DataFrame]:
        raise NotImplementedError


class Estimator(Stage):
    """Trains a Model from data. Ref Estimator.java:31."""

    def fit(self, *inputs: DataFrame) -> Model:
        raise NotImplementedError
