"""Core API: the 5 stage interfaces + the columnar DataFrame.

Reference: flink-ml-core/.../api/ (Stage, Estimator, Model, Transformer, AlgoOperator)
and flink-ml-servable-core/.../servable/api/ (DataFrame, Row) + servable/types.
"""

from flink_ml_tpu.api.core import AlgoOperator, Estimator, Model, Stage, Transformer
from flink_ml_tpu.api.dataframe import DataFrame, Row
from flink_ml_tpu.api.types import (
    BasicType,
    DataType,
    DataTypes,
    MatrixType,
    ScalarType,
    VectorType,
)

__all__ = [
    "AlgoOperator",
    "BasicType",
    "DataFrame",
    "DataType",
    "DataTypes",
    "Estimator",
    "MatrixType",
    "Model",
    "Row",
    "ScalarType",
    "Stage",
    "Transformer",
    "VectorType",
]
