"""FleetRouter — typed-backpressure-aware dispatch over a ReplicaPool.

The router is the fleet's front door and keeps the single-server submit
contract (``submit(df, timeout_ms=..., priority=...) -> handle`` with a
blocking, typed ``handle.result()``) so the load harness and any
InferenceServer client drives a fleet unchanged. Behind it
(docs/fleet.md):

- **Policies** — ``least_loaded`` (fewest in-flight), ``hash`` (rendezvous
  hashing on the request key: session affinity, and an ejected replica only
  moves its own keys), ``priority`` (guaranteed traffic least-loaded,
  sheddable traffic concentrated on the busiest replica so sheds land there
  first).
- **Backpressure protocol** — a replica's ``ServingOverloadedError`` is a
  routing signal, not a failure: bounded jittered backoff honoring the
  replica's own ``retry_after_ms`` drain estimate, then a retry on a
  *different* replica. When every in-rotation replica has shed the same
  request in one round, the router **fails fast** with the typed overload —
  blind cross-replica retries under fleet-wide saturation are how a shed
  becomes a collapse.
- **Failover** — a dropped connection (``ReplicaUnavailableError``) retries
  immediately on another replica; each dead replica is excluded for the
  request's remaining life, so failovers are bounded by the pool size.
- **Hedging** — once the request has waited past a configured quantile of
  the router's observed latency window, a duplicate is dispatched to a
  second replica and the first response wins (the p999 protocol). Hedges
  are duplicates, never counted as fresh arrivals; the winning side is
  visible as ``ml.fleet.hedge.wins``.
- **Canary gate** — dispatches route to the canary slot only while the
  pool's counter gate admits them (``ReplicaPool.canary_allowed``), keeping
  the canary's traffic share a hard invariant.

``fleet.dispatch`` is the router's chaos seam: every primary/retry dispatch
trips it, and an injected fault surfaces typed to the caller with the pool's
in-flight accounting balanced.
"""
from __future__ import annotations

import hashlib
import random
import threading
import time
from typing import Callable, Optional, Set

import flink_ml_tpu.telemetry as telemetry
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.config import Options, config
from flink_ml_tpu.faults import faults
from flink_ml_tpu.fleet.errors import ReplicaUnavailableError
from flink_ml_tpu.fleet.pool import ReplicaPool
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.serving.errors import ServingError, ServingOverloadedError

__all__ = ["FleetRouter"]

POLICIES = ("least_loaded", "hash", "priority")


class _FailedPending:
    """A dispatch that failed synchronously (a local replica's admission
    control raises at submit) — normalized into the pending surface so every
    typed error flows through one retry path on the collector thread."""

    def __init__(self, error: ServingError):
        self._error = error

    def wait(self, timeout: Optional[float] = None) -> bool:
        return True

    def result(self):
        raise self._error


class _FleetHandle:
    """One fleet request across its dispatches (primary, retries, hedge)."""

    def __init__(self, router: "FleetRouter", df, timeout_ms, priority, key, pin):
        self._router = router
        self._df = df
        self._timeout_ms = timeout_ms
        self._priority = priority
        self._key = key
        self._pin = pin
        self._t0 = router._clock()
        #: replicas that shed this request in the current overload round
        self._shed: Set[str] = set()
        #: replicas that dropped the connection — excluded for good
        self._failed: Set[str] = set()
        self._attempts = 0
        self.hedged = False  # read by the load harness's hedge accounting
        self._pending = None
        self._idx: Optional[int] = None
        self._name: Optional[str] = None

    def result(self):  # graftcheck: hot-root
        router = self._router
        pool = router._pool
        while True:
            try:
                response = router._await(self)
            except ServingOverloadedError as e:
                pool.note_resolve(self._idx)
                router._retry_overload(self, e)  # re-dispatches or raises
            except ReplicaUnavailableError as e:
                pool.note_resolve(self._idx)
                router._failover(self, e)  # re-dispatches or raises
            except BaseException:
                pool.note_resolve(self._idx)
                raise
            else:
                pool.note_resolve(self._idx)
                router._observe_latency(self)
                return response


class FleetRouter:
    """Routes the submit contract across a :class:`ReplicaPool`."""

    def __init__(
        self,
        pool: ReplicaPool,
        *,
        policy: Optional[str] = None,
        retry_attempts: Optional[int] = None,
        retry_backoff_ms: Optional[float] = None,
        retry_backoff_max_ms: Optional[float] = None,
        retry_jitter: Optional[float] = None,
        hedge_quantile: Optional[float] = None,
        hedge_after_ms: Optional[float] = None,
        hedge_min_ms: Optional[float] = None,
        sheddable_priority: Optional[int] = None,
        seed: int = 0,
        clock: Callable[[], float] = time.perf_counter,
        sleep: Callable[[float], None] = time.sleep,
    ):
        cfg = pool.config
        self._pool = pool
        self.scope = pool.scope
        self.policy = str(policy if policy is not None else cfg.policy)
        if self.policy not in POLICIES:
            raise ValueError(f"unknown fleet router policy {self.policy!r}; one of {POLICIES}")
        self.retry_attempts = int(
            retry_attempts if retry_attempts is not None else cfg.retry_attempts
        )
        self.retry_backoff_ms = float(
            retry_backoff_ms if retry_backoff_ms is not None else cfg.retry_backoff_ms
        )
        self.retry_backoff_max_ms = float(
            retry_backoff_max_ms if retry_backoff_max_ms is not None
            else cfg.retry_backoff_max_ms
        )
        self.retry_jitter = float(
            retry_jitter if retry_jitter is not None else cfg.retry_jitter
        )
        self.hedge_quantile = (
            hedge_quantile if hedge_quantile is not None else cfg.hedge_quantile
        )
        #: Explicit trigger override (tests / fixed-SLO deployments); None =
        #: derive from the live latency window at hedge_quantile.
        self.hedge_after_ms = hedge_after_ms
        self.hedge_min_ms = float(
            hedge_min_ms if hedge_min_ms is not None else cfg.hedge_min_ms
        )
        self.sheddable_priority = int(
            sheddable_priority if sheddable_priority is not None
            else config.get(Options.SERVING_SHED_PRIORITY)
        )
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._seq = 0
        self._latency = metrics.histogram(self.scope, MLMetrics.FLEET_LATENCY_MS)

    # -- client API ------------------------------------------------------------
    def submit(
        self,
        df: DataFrame,
        timeout_ms: Optional[float] = None,
        priority: int = 0,
        *,
        key=None,
        pin: Optional[int] = None,
    ):
        """Route one request; returns a handle with blocking ``result()``.

        ``key`` is the affinity key for the ``hash`` policy (defaults to a
        router-wide sequence number). ``pin`` routes to one slot, bypassing
        policy, slice gate, retries and hedging — the canary controller's
        measurement path."""
        handle = _FleetHandle(self, df, timeout_ms, priority, key, pin)
        if pin is not None:
            candidates = [c for c in self._pool.candidates() if c[0] == pin]
            if not candidates:
                raise ReplicaUnavailableError(
                    f"pinned slot {pin} is not in rotation", replica=None
                )
            self._dispatch(handle, candidates[0], counted=False)
        else:
            choice = self._choose(priority, self._key_for(handle))
            if choice is None:
                raise ReplicaUnavailableError("no replica in rotation", replica=None)
            self._dispatch(handle, choice)
        return handle

    def predict(
        self, df: DataFrame, timeout_ms: Optional[float] = None, priority: int = 0, **kw
    ):
        return self.submit(df, timeout_ms=timeout_ms, priority=priority, **kw).result()

    # -- dispatch --------------------------------------------------------------
    def _key_for(self, handle: _FleetHandle):
        if handle._key is not None:
            return handle._key
        with self._lock:
            self._seq += 1
            return self._seq

    def _dispatch(self, handle: _FleetHandle, choice, *, counted: bool = True, trip: bool = True) -> None:  # graftcheck: hot-root
        idx, name, replica, canary, _inflight = choice
        if trip:
            faults.trip("fleet.dispatch", replica=name, priority=handle._priority)
        self._pool.note_dispatch(idx, canary=canary and counted, counted=counted)
        try:
            pending = replica.submit(
                handle._df, timeout_ms=handle._timeout_ms, priority=handle._priority
            )
        except ServingError as e:
            # Synchronous admission rejection (local replicas): normalize into
            # the pending surface so one retry path handles both isolations.
            pending = _FailedPending(e)
        handle._pending = pending
        handle._idx = idx
        handle._name = name
        handle._attempts += 1

    def _choose(self, priority: int, key, exclude: Optional[Set[str]] = None):
        """One routing decision over the current rotation snapshot."""
        exclude = exclude or set()
        candidates = [
            c for c in self._pool.candidates() if c[1] not in exclude
        ]
        non_canary = [c for c in candidates if not c[3]]
        if non_canary:
            eligible = list(non_canary)
            if self._pool.canary_allowed():
                eligible += [c for c in candidates if c[3]]
        else:
            # Degenerate rotation (only canary slots left): availability
            # outranks the slice — with zero baseline replicas there is no
            # baseline traffic to bound against.
            eligible = candidates
        if not eligible:
            return None
        if self.policy == "hash":
            return max(eligible, key=lambda c: self._rendezvous(key, c[1]))
        if self.policy == "priority" and priority >= self.sheddable_priority:
            # Sheddable traffic piles onto the busiest replica: its controller
            # sheds first while guaranteed traffic keeps headroom elsewhere.
            return max(eligible, key=lambda c: (c[4], -c[0]))
        return min(eligible, key=lambda c: (c[4], c[0]))

    @staticmethod
    def _rendezvous(key, name: str) -> int:
        digest = hashlib.md5(f"{key}|{name}".encode("utf-8")).digest()
        return int.from_bytes(digest[:8], "big")

    # -- waiting / hedging -----------------------------------------------------
    def _hedge_trigger_ms(self) -> Optional[float]:
        if self.hedge_after_ms is not None:
            return float(self.hedge_after_ms)
        if self.hedge_quantile is None:
            return None
        if self._latency.count < 32:
            return None  # window too cold to know what "tail" means
        q = self._latency.quantile(float(self.hedge_quantile))
        if q is None:
            return None
        return max(float(q), self.hedge_min_ms)

    def _await(self, handle: _FleetHandle):
        """Block on the current pending; once past the hedge trigger, race a
        duplicate on a second replica — first response wins."""
        pending = handle._pending
        trigger_ms = (
            None if (handle.hedged or handle._pin is not None)
            else self._hedge_trigger_ms()
        )
        if trigger_ms is None:
            return pending.result()
        if pending.wait(trigger_ms / 1000.0):
            return pending.result()
        choice = self._choose(
            handle._priority,
            self._key_for(handle),
            exclude={handle._name} | handle._failed,
        )
        if choice is None:
            return pending.result()  # nowhere to hedge: keep waiting
        handle.hedged = True
        metrics.counter(self.scope, MLMetrics.FLEET_HEDGES)
        primary_idx, primary_name = handle._idx, handle._name
        # A hedge is a DUPLICATE of a live request, not a new dispatch
        # decision — the chaos seam stays on the primary/retry path.
        self._dispatch(handle, choice, trip=False)
        hedge_pending, hedge_idx = handle._pending, handle._idx
        while True:
            if pending.wait(0.005):
                # Primary won: the hedge is abandoned (its replica still
                # finishes server-side; the reply is dropped at the socket).
                self._pool.note_resolve(hedge_idx)
                handle._pending, handle._idx, handle._name = (
                    pending, primary_idx, primary_name,
                )
                return pending.result()
            if hedge_pending.wait(0.0):
                metrics.counter(self.scope, MLMetrics.FLEET_HEDGE_WINS)
                self._pool.note_resolve(primary_idx)
                return hedge_pending.result()

    # -- retry / failover ------------------------------------------------------
    def _retry_overload(self, handle: _FleetHandle, e: ServingOverloadedError) -> None:
        """Backoff-and-retry on a different replica, fail fast when the whole
        fleet sheds; raises when the request is out of road."""
        if handle._pin is not None:
            raise e  # pinned measurement traffic never wanders
        handle._shed.add(handle._name)
        rotation = {c[1] for c in self._pool.candidates()}
        if rotation and rotation.issubset(handle._shed):
            metrics.counter(self.scope, MLMetrics.FLEET_FAILFAST)
            telemetry.emit(
                "fleet.failfast",
                self.scope,
                {
                    "shed_by": sorted(handle._shed),
                    "priority": handle._priority,
                    "retry_after_ms": e.retry_after_ms,
                },
            )
            raise e
        if handle._attempts >= self.retry_attempts:
            raise e
        base_ms = e.retry_after_ms if e.retry_after_ms is not None else self.retry_backoff_ms
        delay_ms = min(float(base_ms), self.retry_backoff_max_ms)
        with self._lock:
            delay_ms *= 1.0 + self.retry_jitter * self._rng.random()
        self._sleep(delay_ms / 1000.0)
        choice = self._choose(
            handle._priority,
            self._key_for(handle),
            exclude=handle._shed | handle._failed,
        )
        if choice is None:
            raise e
        metrics.counter(self.scope, MLMetrics.FLEET_RETRIES)
        self._dispatch(handle, choice)

    def _failover(self, handle: _FleetHandle, e: ReplicaUnavailableError) -> None:
        """Immediate redispatch after a connection loss — the dead replica is
        excluded for this request's remaining life, so failovers are bounded
        by the pool size (they never consume the overload retry budget)."""
        if handle._pin is not None:
            raise e
        if handle._name is not None:
            handle._failed.add(handle._name)
        choice = self._choose(
            handle._priority,
            self._key_for(handle),
            exclude=handle._failed | handle._shed,
        )
        if choice is None:
            raise ReplicaUnavailableError(
                f"no replica left in rotation after {sorted(handle._failed)} failed",
                replica=None,
            )
        metrics.counter(self.scope, MLMetrics.FLEET_FAILOVERS)
        self._dispatch(handle, choice)

    def _observe_latency(self, handle: _FleetHandle) -> None:
        self._latency.observe((self._clock() - handle._t0) * 1000.0)
