"""Replica handles: one serving process (or in-process server) behind one API.

Two isolations, one contract (``docs/fleet.md``):

- :class:`ProcessReplica` — a real OS process running ``fleet/worker.py``
  (its own ``InferenceServer``, /healthz endpoint, flight-recorder journal
  and plancache-warmed mesh), reached over a ``multiprocessing.connection``
  socket with one connection per outstanding request. A hard kill surfaces
  as :class:`~flink_ml_tpu.fleet.errors.ReplicaUnavailableError` on every
  in-flight and future call — the router's failover signal.
- :class:`LocalReplica` — the same surface over an in-process
  ``InferenceServer``, for deterministic fleet tests without process spawn
  cost; ``kill()`` simulates the hard death (in-flight requests resolve as
  ``ReplicaUnavailableError``, exactly like a dropped socket).

Both expose: ``submit`` (async; pending supports ``wait(timeout)`` — the
router's hedging primitive), ``predict``, ``swap``/``rollback`` by published
version path, ``rollback_bad`` (the RollbackController path for canary
quarantine), ``health_check``, ``stats``, ``close``, ``kill``.

Cross-process payloads carry columnar data as plain numpy (device arrays are
pulled host-side before pickling) and serving errors as structured
descriptors (``encode_error``/``decode_error``) — a replica's typed
rejection stays the *same type* in the parent, so the whole fleet keeps the
typed-error contract end to end.
"""
from __future__ import annotations

import json
import os
import pickle
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from multiprocessing.connection import Client
from typing import Any, Dict, Optional, Tuple

import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.faults import InjectedFault
from flink_ml_tpu.fleet.errors import ReplicaUnavailableError
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.serving.errors import (
    NoModelError,
    ServingClosedError,
    ServingDeadlineError,
    ServingError,
    ServingOverloadedError,
)

__all__ = [
    "LocalReplica",
    "ProcessReplica",
    "encode_df",
    "decode_df",
    "encode_error",
    "decode_error",
]

#: Env var carrying the fleet's connection authkey (hex) to worker processes.
AUTHKEY_ENV = "FLINK_ML_TPU_FLEET_AUTHKEY"


# -- wire helpers (shared with fleet/worker.py) --------------------------------
def encode_df(df: DataFrame) -> Dict[str, Any]:
    """A picklable columnar payload: numpy arrays host-side (a response
    column may be a device array — pull it before it crosses the socket),
    object columns (sparse vectors, strings) as plain lists."""
    columns = []
    for name in df.column_names:
        col = df.column(name)
        if isinstance(col, list):
            columns.append(col)
        else:
            columns.append(np.asarray(col))
    return {"names": df.column_names, "columns": columns}


def decode_df(payload: Dict[str, Any]) -> DataFrame:
    return DataFrame(payload["names"], None, payload["columns"])


def encode_error(e: BaseException) -> Dict[str, Any]:
    """A structured descriptor of a worker-side failure — reconstructable to
    the same typed exception in the parent (plain pickling loses keyword-only
    constructor fields like ``retry_after_ms``)."""
    if isinstance(e, ServingOverloadedError):
        return {
            "type": "overloaded",
            "queued_rows": e.queued_rows,
            "capacity_rows": e.capacity_rows,
            "retry_after_ms": e.retry_after_ms,
            "shed": e.shed,
            "priority": e.priority,
        }
    if isinstance(e, ServingDeadlineError):
        return {
            "type": "deadline",
            "phase": e.phase,
            "queued_ms": e.queued_ms,
            "retry_after_ms": e.retry_after_ms,
        }
    if isinstance(e, InjectedFault):
        return {"type": "injected", "point": e.point, "hit": e.hit, "context": e.context}
    if isinstance(e, ServingClosedError):
        return {"type": "closed", "message": str(e)}
    if isinstance(e, NoModelError):
        return {"type": "no_model", "message": str(e)}
    if isinstance(e, ServingError):
        return {"type": "serving", "message": str(e)}
    return {"type": "unexpected", "error_type": type(e).__name__, "message": str(e)}


def decode_error(d: Dict[str, Any]) -> BaseException:
    kind = d.get("type")
    if kind == "overloaded":
        return ServingOverloadedError(
            d["queued_rows"],
            d["capacity_rows"],
            retry_after_ms=d.get("retry_after_ms"),
            shed=bool(d.get("shed")),
            priority=d.get("priority"),
        )
    if kind == "deadline":
        return ServingDeadlineError(
            phase=d.get("phase", "queued"),
            queued_ms=d.get("queued_ms"),
            retry_after_ms=d.get("retry_after_ms"),
        )
    if kind == "injected":
        return InjectedFault(d["point"], d["hit"], d.get("context"))
    if kind == "closed":
        return ServingClosedError(d.get("message", "server is closed"))
    if kind == "no_model":
        return NoModelError(d.get("message", "no model version loaded yet"))
    if kind == "serving":
        return ServingError(d.get("message", "serving error"))
    return RuntimeError(
        f"replica-side {d.get('error_type', 'error')}: {d.get('message', '')}"
    )


class _ReplicaResponse:
    """A fleet-side serving response (the ``ServingResponse`` surface
    reconstructed from the wire payload)."""

    __slots__ = ("dataframe", "model_version", "latency_ms", "bucket")

    def __init__(self, dataframe, model_version, latency_ms, bucket):
        self.dataframe = dataframe
        self.model_version = model_version
        self.latency_ms = latency_ms
        self.bucket = bucket


# -- in-process replica --------------------------------------------------------
class _LocalPending:
    """Wraps a server handle behind a ``wait(timeout)``-capable pending: one
    resolver thread blocks on the inner ``result()`` and publishes the
    outcome through an Event (the batcher handle has no timed public wait)."""

    def __init__(self, replica: "LocalReplica", inner):
        self._replica = replica
        self._done = threading.Event()
        # Outcome fields cross from the resolver thread to whichever router
        # thread awaits: lock-guarded (the Event orders them too, but a
        # consistent lockset is the contract shared-state-guard verifies).
        self._lock = threading.Lock()
        self._response = None
        self._error: Optional[BaseException] = None
        thread = threading.Thread(
            target=self._resolve, args=(inner,), daemon=True,
            name=f"fleet-local-pending[{replica.name}]",
        )
        thread.start()

    def _resolve(self, inner) -> None:
        try:
            response = inner.result()
        except BaseException as e:  # noqa: BLE001 — republished via result()
            # A killed local replica fails its queued requests with
            # ServingClosedError; a killed *process* replica drops the
            # socket. Same event, same typed signal to the router.
            if isinstance(e, ServingClosedError) and self._replica.killed:
                e = ReplicaUnavailableError(
                    f"replica {self._replica.name!r} died mid-request",
                    replica=self._replica.name,
                )
            with self._lock:
                self._error = e
        else:
            with self._lock:
                self._response = response
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self):
        self._done.wait()
        with self._lock:
            error = self._error
            response = self._response
        if error is not None:
            raise error
        return response


class LocalReplica:
    """The replica contract over an in-process ``InferenceServer``."""

    def __init__(self, name: str, server, *, publish_dir: Optional[str] = None, loader=None):
        if loader is None:
            from flink_ml_tpu.servable.api import load_servable

            loader = load_servable
        self.name = name
        self.server = server
        self.publish_dir = publish_dir
        self.loader = loader
        self._killed = False

    @property
    def killed(self) -> bool:
        return self._killed

    @property
    def alive(self) -> bool:
        return not self._killed

    def submit(self, df: DataFrame, timeout_ms: Optional[float] = None, priority: int = 0):
        if self._killed:
            raise ReplicaUnavailableError(
                f"replica {self.name!r} is dead", replica=self.name
            )
        inner = self.server.submit(df, timeout_ms=timeout_ms, priority=priority)
        return _LocalPending(self, inner)

    def predict(self, df: DataFrame, timeout_ms: Optional[float] = None, priority: int = 0):
        return self.submit(df, timeout_ms=timeout_ms, priority=priority).result()

    def swap(self, version: int, path: str) -> None:
        self.server.swap(version, self.loader(path))

    def rollback(self, version: int, path: str) -> None:
        self.server.rollback(version, self.loader(path))

    def rollback_bad(self, bad_version: int) -> int:
        """Quarantine ``bad_version`` and restore the newest intact older one
        on this replica — the RollbackController path (loop/rollback.py)."""
        from flink_ml_tpu.loop.rollback import RollbackController

        if self.publish_dir is None:
            raise RuntimeError(f"replica {self.name!r} has no publish_dir")
        controller = RollbackController(
            self.server, self.publish_dir, loader=self.loader,
            scope=f"{MLMetrics.FLEET_GROUP}[{self.name}]",
        )
        return controller.rollback(bad_version)

    def health_check(self, timeout_s: float = 2.0) -> Tuple[bool, Dict[str, Any]]:
        if self._killed:
            return False, {"status": "dead", "name": self.name}
        return self.server.health()

    def stats(self) -> Dict[str, Dict[str, Any]]:
        return {
            "serving": _numeric(metrics.scope(self.server.scope)),
            "plancache": _numeric(metrics.scope(MLMetrics.PLANCACHE_GROUP)),
        }

    def kill(self) -> None:
        """Simulated hard death: future submits refuse, queued requests
        resolve as ``ReplicaUnavailableError`` (see ``_LocalPending``)."""
        self._killed = True
        self.server.close(drain=False)

    def close(self, drain: bool = True) -> None:
        if not self._killed:
            self.server.close(drain=drain)

    def __repr__(self) -> str:
        return f"LocalReplica({self.name!r}, alive={self.alive})"


def _numeric(scope: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in scope.items() if isinstance(v, (int, float))}


# -- process replica -----------------------------------------------------------
class _ProcessPending:
    """One in-flight request on its own connection: ``wait`` polls the
    socket, ``result`` receives exactly one reply. A dropped socket (worker
    hard-killed) resolves as ``ReplicaUnavailableError``."""

    def __init__(self, replica: "ProcessReplica", conn):
        self._replica = replica
        self._conn = conn
        self._outcome: Optional[Tuple[Optional[object], Optional[BaseException]]] = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._outcome is not None:
            return True
        try:
            return self._conn.poll(timeout)
        except (OSError, EOFError):
            return True  # dead socket: result() will surface the typed error

    def result(self):
        if self._outcome is None:
            try:
                reply = self._conn.recv()
            except (EOFError, OSError, ConnectionResetError) as e:
                self._outcome = (
                    None,
                    ReplicaUnavailableError(
                        f"replica {self._replica.name!r} dropped the connection "
                        f"mid-request ({type(e).__name__})",
                        replica=self._replica.name,
                    ),
                )
            else:
                if reply.get("ok"):
                    self._outcome = (
                        _ReplicaResponse(
                            decode_df(reply["df"]),
                            reply["model_version"],
                            reply["latency_ms"],
                            reply["bucket"],
                        ),
                        None,
                    )
                else:
                    self._outcome = (None, decode_error(reply["error"]))
            finally:
                try:
                    self._conn.close()
                except OSError:
                    pass
        response, error = self._outcome
        if error is not None:
            raise error
        return response


class ProcessReplica:
    """The replica contract over a spawned ``fleet/worker.py`` process."""

    def __init__(self, name: str, proc, address, authkey: bytes, info: Dict[str, Any]):
        self.name = name
        self._proc = proc
        self.address = tuple(address)
        self._authkey = authkey
        self.info = info
        self.pid = info.get("pid")
        self.telemetry_port = info.get("telemetry_port")

    # -- spawn ----------------------------------------------------------------
    @classmethod
    def spawn(
        cls,
        name: str,
        workdir: str,
        *,
        publish_dir: Optional[str] = None,
        load_version: Optional[int] = None,
        template: Optional[DataFrame] = None,
        env: Optional[Dict[str, str]] = None,
        ready_timeout_s: float = 180.0,
    ) -> "ProcessReplica":
        """Start a worker, wait for its ready file, return the handle.

        ``env`` entries override the inherited environment — the fleet's
        plancache dir, journal dir and serving knobs ride here as the
        ``FLINK_ML_TPU_*`` vars the config tier already resolves.
        """
        os.makedirs(workdir, exist_ok=True)
        authkey_hex = os.urandom(16).hex()
        full_env = dict(os.environ)
        full_env.update(env or {})
        full_env[AUTHKEY_ENV] = authkey_hex
        # The worker must import this package even when the parent was
        # launched from elsewhere.
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        full_env["PYTHONPATH"] = repo_root + (
            os.pathsep + full_env["PYTHONPATH"] if full_env.get("PYTHONPATH") else ""
        )
        cmd = [
            sys.executable, "-m", "flink_ml_tpu.fleet.worker",
            "--name", name, "--workdir", workdir,
        ]
        if publish_dir is not None:
            cmd += ["--publish-dir", publish_dir]
        if load_version is not None:
            cmd += ["--load-version", str(int(load_version))]
        if template is not None:
            template_path = os.path.join(workdir, "template.pkl")
            with open(template_path, "wb") as f:
                pickle.dump(encode_df(template), f)
            cmd += ["--template", template_path]
        log_path = os.path.join(workdir, "worker.log")
        log_file = open(log_path, "ab")
        try:
            proc = subprocess.Popen(cmd, env=full_env, stdout=log_file, stderr=subprocess.STDOUT)
        finally:
            log_file.close()
        ready_path = os.path.join(workdir, "ready.json")
        deadline = time.monotonic() + ready_timeout_s
        while not os.path.exists(ready_path):
            if proc.poll() is not None:
                raise RuntimeError(
                    f"replica worker {name!r} died before ready "
                    f"(exit {proc.returncode}); see {log_path}"
                )
            if time.monotonic() > deadline:
                proc.kill()
                raise TimeoutError(
                    f"replica worker {name!r} not ready within {ready_timeout_s}s; "
                    f"see {log_path}"
                )
            time.sleep(0.05)
        with open(ready_path, "r", encoding="utf-8") as f:
            info = json.load(f)
        return cls(name, proc, info["address"], bytes.fromhex(authkey_hex), info)

    # -- plumbing -------------------------------------------------------------
    @property
    def alive(self) -> bool:
        return self._proc.poll() is None

    def _connect(self):
        try:
            return Client(self.address, authkey=self._authkey)
        except (ConnectionRefusedError, ConnectionResetError, OSError, EOFError) as e:
            raise ReplicaUnavailableError(
                f"replica {self.name!r} unreachable at {self.address} "
                f"({type(e).__name__})",
                replica=self.name,
            ) from e

    def _call(self, payload: Dict[str, Any], timeout_s: float) -> Dict[str, Any]:
        conn = self._connect()
        try:
            try:
                conn.send(payload)
                if not conn.poll(timeout_s):
                    raise ReplicaUnavailableError(
                        f"replica {self.name!r}: no {payload.get('op')!r} reply "
                        f"within {timeout_s}s",
                        replica=self.name,
                    )
                reply = conn.recv()
            except (BrokenPipeError, EOFError, ConnectionResetError, OSError) as e:
                raise ReplicaUnavailableError(
                    f"replica {self.name!r} dropped the connection "
                    f"({type(e).__name__})",
                    replica=self.name,
                ) from e
        finally:
            try:
                conn.close()
            except OSError:
                pass
        if not reply.get("ok"):
            raise decode_error(reply["error"])
        return reply

    # -- the replica contract -------------------------------------------------
    def submit(self, df: DataFrame, timeout_ms: Optional[float] = None, priority: int = 0):
        conn = self._connect()
        try:
            conn.send(
                {
                    "op": "predict",
                    "df": encode_df(df),
                    "timeout_ms": timeout_ms,
                    "priority": int(priority),
                }
            )
        except (BrokenPipeError, OSError) as e:
            try:
                conn.close()
            except OSError:
                pass
            raise ReplicaUnavailableError(
                f"replica {self.name!r} dropped the connection at submit "
                f"({type(e).__name__})",
                replica=self.name,
            ) from e
        return _ProcessPending(self, conn)

    def predict(self, df: DataFrame, timeout_ms: Optional[float] = None, priority: int = 0):
        return self.submit(df, timeout_ms=timeout_ms, priority=priority).result()

    def swap(self, version: int, path: str, timeout_s: float = 300.0) -> None:
        self._call({"op": "swap", "version": int(version), "path": path}, timeout_s)

    def rollback(self, version: int, path: str, timeout_s: float = 300.0) -> None:
        self._call({"op": "rollback", "version": int(version), "path": path}, timeout_s)

    def rollback_bad(self, bad_version: int, timeout_s: float = 300.0) -> int:
        reply = self._call({"op": "rollback_bad", "version": int(bad_version)}, timeout_s)
        return reply["restored"]

    def health_check(self, timeout_s: float = 2.0) -> Tuple[bool, Dict[str, Any]]:
        """The /healthz probe — over the worker's HTTP endpoint, exactly what
        an external load balancer would see (200 = in service, 503 =
        draining/closed, unreachable = dead)."""
        url = f"http://127.0.0.1:{self.telemetry_port}/healthz"
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                return True, json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode("utf-8"))
            except Exception:  # noqa: BLE001 — body is best-effort evidence
                payload = {}
            payload.setdefault("status", f"http-{e.code}")
            return False, payload
        except Exception as e:  # noqa: BLE001 — any probe failure = unhealthy
            return False, {"status": "unreachable", "error": type(e).__name__}

    def stats(self, timeout_s: float = 30.0) -> Dict[str, Dict[str, Any]]:
        return self._call({"op": "stats"}, timeout_s)["stats"]

    def kill(self) -> None:
        """Hard kill — no drain, no goodbye; the crash the fleet must survive."""
        if self._proc.poll() is None:
            self._proc.kill()
        self._proc.wait(timeout=30)

    def close(self, drain: bool = True) -> None:
        try:
            self._call({"op": "close", "drain": bool(drain)}, timeout_s=60.0)
        except ReplicaUnavailableError:
            pass  # already gone
        try:
            self._proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            self.kill()

    def __repr__(self) -> str:
        return f"ProcessReplica({self.name!r}, pid={self.pid}, alive={self.alive})"
