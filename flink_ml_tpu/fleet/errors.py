"""Typed failures of the fleet tier.

Both subclass :class:`~flink_ml_tpu.serving.errors.ServingError` so every
failure a fleet client can see stays inside the typed-error contract the
load harness bins exhaustively (loadgen/generator.py) — a replica crash or
a whole-fleet outage is a routable, typed event, never an untyped surprise.
"""
from __future__ import annotations

from typing import Optional

from flink_ml_tpu.serving.errors import ServingError

__all__ = ["ReplicaUnavailableError", "FleetQuorumError"]


class ReplicaUnavailableError(ServingError):
    """A replica could not be reached (connection refused, hard-killed
    mid-request, or no replica in rotation at all). The router retries these
    on a different replica; when none is left the error surfaces to the
    caller with ``replica=None``."""

    def __init__(self, message: str, *, replica: Optional[str] = None):
        self.replica = replica
        super().__init__(message)


class FleetQuorumError(ServingError):
    """A rolling operation (promotion) would drop the in-rotation replica
    count below the fleet's quorum — deferred, never forced."""

    def __init__(self, message: str, *, healthy: int, quorum: int):
        self.healthy = healthy
        self.quorum = quorum
        super().__init__(message)
