"""CanaryController — drift-gated rollout of new model versions across a fleet.

The fleet's promotion protocol (docs/fleet.md): a newly published version
never goes fleet-wide on faith. It first serves a **bounded traffic slice**
on one designated canary replica (the pool's counter gate keeps the slice a
hard invariant — see ``ReplicaPool.canary_allowed``), while labelled tail
traffic is scored live on both the canary and a baseline replica through the
real serving path (pinned router dispatches, so the scores measure exactly
what users would see). The ``DriftMonitor`` renders the verdict:

- **promote** — the canary is not regressed after ``min_scores``
  observations per side: the version rolls out **one replica at a time**,
  each step gated on the fleet holding quorum (``FleetQuorumError`` defers,
  never forces), then becomes the fleet version.
- **quarantine** — the canary regressed: the ``RollbackController`` path
  moves the version's published dir aside (``v-N.quarantined`` — the
  idempotent rename in serving/registry.py, safe under concurrent
  rollbacks), restores the fleet version on the canary replica, and the
  version is remembered as failed so it is never re-canaried.

Every start / score / promote-step / promote / quarantine decision is
journaled with its evidence under the fleet scope — ``tools/fleetview.py``
reconstructs the full rollout history from these records.

``fleet.promote`` is the chaos seam: it trips before any replica flips, so
an injected fault leaves nothing half-promoted, and a retried promotion
completes exactly once (already-flipped replicas are skipped by the
progress ledger).
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Set

import numpy as np

import flink_ml_tpu.telemetry as telemetry
from flink_ml_tpu.faults import faults
from flink_ml_tpu.fleet.errors import FleetQuorumError
from flink_ml_tpu.fleet.pool import ReplicaPool
from flink_ml_tpu.loop.drift import DriftMonitor
from flink_ml_tpu.loop.loop import default_scorer
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.serving.registry import VERSION_PREFIX, _METADATA_MARKER

__all__ = ["CanaryController"]


class CanaryController:
    """Scan → canary → score → promote-or-quarantine, over one pool."""

    def __init__(
        self,
        pool: ReplicaPool,
        router,
        publish_dir: str,
        *,
        monitor: Optional[DriftMonitor] = None,
        scorer: Optional[Callable] = None,
        label_col: str = "label",
        min_scores: Optional[int] = None,
        quorum: Optional[int] = None,
    ):
        cfg = pool.config
        self._pool = pool
        self._router = router
        self.publish_dir = publish_dir
        self.scope = pool.scope
        self.label_col = label_col
        self.scorer = scorer or default_scorer
        self.min_scores = int(
            min_scores if min_scores is not None else cfg.canary_min_scores
        )
        self.quorum = int(quorum if quorum is not None else cfg.quorum)
        self.monitor = monitor or DriftMonitor(
            scope=self.scope, min_scores=self.min_scores
        )
        #: Versions that failed to load or were quarantined — never re-canaried.
        self._failed: Set[int] = set()
        #: Per-version slots already flipped, so a retried promotion (the
        #: fleet.promote seam) completes exactly once.
        self._promoted: Dict[int, Set[int]] = {}

    # -- start -----------------------------------------------------------------
    def _version_path(self, version: int) -> str:
        return os.path.join(self.publish_dir, f"{VERSION_PREFIX}{version}")

    def maybe_start(self) -> Optional[int]:
        """Designate the newest eligible published version as the canary on
        one serving replica. No-op while a canary is already running."""
        pool = self._pool
        if pool.canary_version is not None:
            return None
        from flink_ml_tpu.checkpoint import scan_numbered_dirs

        versions = scan_numbered_dirs(
            self.publish_dir, VERSION_PREFIX, _METADATA_MARKER
        )
        fleet_version = pool.fleet_version
        candidates = pool.candidates()
        if len(candidates) < 2:
            return None  # a 1-replica rotation has no baseline to score against
        for version in reversed(versions):
            if fleet_version is not None and version <= fleet_version:
                break
            if version in self._failed:
                continue
            # Last in-rotation slot by index: deterministic, and keeps slot 0
            # (the hash policy's densest keyspace) on the baseline side.
            index, name = candidates[-1][0], candidates[-1][1]
            replica = candidates[-1][2]
            try:
                replica.swap(version, self._version_path(version))
            except Exception as e:  # noqa: BLE001 — a bad version must not loop
                self._failed.add(version)
                telemetry.emit(
                    "fleet.canary.failed",
                    self.scope,
                    {
                        "version": version,
                        "replica": name,
                        "error": type(e).__name__,
                        "detail": str(e)[:200],
                    },
                )
                continue
            pool.set_canary(index, version)
            metrics.counter(self.scope, MLMetrics.FLEET_CANARY_STARTED)
            telemetry.emit(
                "fleet.canary.start",
                self.scope,
                {
                    "version": version,
                    "replica": name,
                    "slot": index,
                    "baseline": fleet_version,
                    "slice": pool.config.canary_slice,
                },
            )
            return version
        return None

    # -- scoring ---------------------------------------------------------------
    def observe(self, df) -> Optional[Dict[str, float]]:
        """Score one labelled tail batch on the canary AND a baseline replica
        (pinned dispatches — measurement traffic, outside the slice gate)."""
        pool = self._pool
        canary_index = pool.canary_slot()
        canary_version = pool.canary_version
        if canary_index is None or canary_version is None:
            return None
        baselines = [
            c for c in pool.candidates() if not c[3] and c[0] != canary_index
        ]
        if not baselines:
            return None
        baseline = min(baselines, key=lambda c: (c[4], c[0]))
        labels = np.asarray(df.column(self.label_col), np.float64)
        features = df.drop(self.label_col)
        canary_resp = self._router.predict(features, pin=canary_index)
        baseline_resp = self._router.predict(features, pin=baseline[0])
        canary_score = self.scorer(canary_resp.dataframe, labels)
        baseline_score = self.scorer(baseline_resp.dataframe, labels)
        self.monitor.observe(canary_resp.model_version, canary_score)
        self.monitor.observe(baseline_resp.model_version, baseline_score)
        telemetry.emit(
            "fleet.canary.score",
            self.scope,
            {
                "version": canary_resp.model_version,
                "score": canary_score,
                "baseline_version": baseline_resp.model_version,
                "baseline_score": baseline_score,
                "rows": int(labels.size),
            },
        )
        return {"canary": canary_score, "baseline": baseline_score}

    # -- verdict ---------------------------------------------------------------
    def verdict(self) -> Optional[str]:
        """``"promote"`` / ``"quarantine"`` once the evidence suffices, else
        None. Both sides need ``min_scores`` observations — the drift
        monitor's no-baseline conservatism must gate *promotion* here too, or
        a regressed canary could ride out an empty baseline window."""
        pool = self._pool
        canary_version = pool.canary_version
        if canary_version is None:
            return None
        if self.monitor.count(canary_version) < self.min_scores:
            return None
        fleet_version = pool.fleet_version
        if fleet_version is not None and self.monitor.count(fleet_version) < self.min_scores:
            return None
        if self.monitor.regressed(canary_version, fleet_version):
            return "quarantine"
        return "promote"

    # -- promote ---------------------------------------------------------------
    def promote(self) -> int:  # graftcheck: cold
        """Roll the canary version across the fleet, one replica at a time,
        quorum-gated; finishes by making it the fleet version."""
        pool = self._pool
        version = pool.canary_version
        canary_index = pool.canary_slot()
        if version is None or canary_index is None:
            raise RuntimeError("no canary to promote")
        # The seam trips BEFORE any flip: an injected fault here leaves the
        # fleet exactly as it was, and the retry finds the ledger empty.
        faults.trip("fleet.promote", version=version, canary=canary_index)
        done = self._promoted.setdefault(version, {canary_index})
        path = self._version_path(version)
        rolled = []
        for index, name, replica, _canary, _inflight in pool.candidates():
            if index in done:
                continue
            healthy = pool.healthy_count
            if healthy < self.quorum:
                raise FleetQuorumError(
                    f"promotion of v{version} deferred: {healthy} healthy "
                    f"replicas < quorum {self.quorum}",
                    healthy=healthy,
                    quorum=self.quorum,
                )
            replica.swap(version, path)
            done.add(index)
            rolled.append(name)
            telemetry.emit(
                "fleet.promote.step",
                self.scope,
                {"version": version, "replica": name, "slot": index},
            )
        previous = pool.fleet_version
        pool.set_fleet_version(version)
        pool.clear_canary()
        self._promoted.pop(version, None)
        metrics.counter(self.scope, MLMetrics.FLEET_CANARY_PROMOTED)
        telemetry.emit(
            "fleet.promote",
            self.scope,
            {"version": version, "from": previous, "rolled": rolled},
        )
        return version

    # -- quarantine ------------------------------------------------------------
    def quarantine(self) -> Optional[int]:  # graftcheck: cold
        """Roll the canary replica back and quarantine the bad version's
        published dir; returns the restored version."""
        pool = self._pool
        version = pool.canary_version
        canary_index = pool.canary_slot()
        if version is None or canary_index is None:
            raise RuntimeError("no canary to quarantine")
        replica = pool.slot(canary_index).replica
        name = pool.slot(canary_index).name
        restored = replica.rollback_bad(version)
        self._failed.add(version)
        pool.clear_canary()
        metrics.counter(self.scope, MLMetrics.FLEET_CANARY_QUARANTINED)
        evidence = {
            "version": version,
            "replica": name,
            "restored": restored,
            "canary_mean": self.monitor.mean(version),
            "baseline_mean": (
                self.monitor.mean(pool.fleet_version)
                if pool.fleet_version is not None
                else None
            ),
        }
        telemetry.emit("fleet.quarantine", self.scope, evidence)
        telemetry.incident("canary-quarantine", self.scope, evidence)
        return restored

    # -- one turn --------------------------------------------------------------
    def step(self, eval_df=None) -> Dict[str, object]:
        """One controller turn: start a canary if one is due, score a tail
        batch if given, act on the verdict once it lands."""
        started = self.maybe_start()
        scores = self.observe(eval_df) if eval_df is not None else None
        verdict = self.verdict()
        outcome: Dict[str, object] = {
            "started": started,
            "scores": scores,
            "verdict": verdict,
            "canary_version": self._pool.canary_version,
        }
        if verdict == "promote":
            outcome["promoted"] = self.promote()
        elif verdict == "quarantine":
            outcome["restored"] = self.quarantine()
        return outcome
