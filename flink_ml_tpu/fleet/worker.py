"""Replica worker — the process half of :class:`ProcessReplica`.

Entry point (``python -m flink_ml_tpu.fleet.worker``): builds one
``InferenceServer`` with its own flight-recorder journal (under
``<workdir>/journal``), an ephemeral /healthz + /metrics endpoint, and —
through the inherited ``FLINK_ML_TPU_PLANCACHE_DIR`` — the fleet's shared
plan cache, so a respawned replica warms from serialized executables with
zero serving-path compiles (docs/plancache.md).

Protocol: a ``multiprocessing.connection.Listener`` on an ephemeral
localhost port (authkey from ``FLINK_ML_TPU_FLEET_AUTHKEY``); the parent
opens one connection per outstanding request and the worker answers each
with exactly one reply. Once the server is warmed and listening, the worker
atomically publishes ``<workdir>/ready.json`` (pid, address, telemetry
port) — the parent's spawn barrier. Ops: ``predict``, ``swap``,
``rollback``, ``rollback_bad`` (RollbackController — the canary quarantine
path), ``health``, ``stats``, ``close``.

An abandoned connection (the parent hedged the request elsewhere, or died)
only ends that connection's thread; the serving loop is untouched.
"""
from __future__ import annotations

import argparse
import json
import os
import pickle
import threading
from multiprocessing.connection import Listener
from typing import Any, Dict, Optional

from flink_ml_tpu.fleet.replica import AUTHKEY_ENV, decode_df, encode_df, encode_error

__all__ = ["main"]


class _Worker:
    def __init__(self, args):
        import flink_ml_tpu.telemetry as telemetry
        from flink_ml_tpu.metrics import MLMetrics, metrics
        from flink_ml_tpu.serving.server import InferenceServer, ServingConfig

        self._telemetry = telemetry
        self._metrics = metrics
        self._plancache_group = MLMetrics.PLANCACHE_GROUP
        self.args = args
        self.workdir = args.workdir
        os.makedirs(self.workdir, exist_ok=True)
        telemetry.configure(os.path.join(self.workdir, "journal"))
        template = None
        if args.template:
            with open(args.template, "rb") as f:
                template = decode_df(pickle.load(f))
        self.server = InferenceServer(
            name=args.name,
            serving_config=ServingConfig(http_port=0),
            warmup_template=template,
        )
        if args.publish_dir and args.load_version is not None:
            from flink_ml_tpu.serving.registry import VERSION_PREFIX
            from flink_ml_tpu.servable.api import load_servable

            path = os.path.join(args.publish_dir, f"{VERSION_PREFIX}{args.load_version}")
            self.server.swap(int(args.load_version), load_servable(path))
        self._stop = threading.Event()
        telemetry.emit(
            "fleet.replica.up",
            self.server.scope,
            {
                "name": args.name,
                "pid": os.getpid(),
                "version": self.server.model_version,
            },
        )

    # -- one RPC --------------------------------------------------------------
    def handle(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        op = msg.get("op")
        if op == "predict":
            resp = self.server.predict(
                decode_df(msg["df"]),
                timeout_ms=msg.get("timeout_ms"),
                priority=int(msg.get("priority") or 0),
            )
            return {
                "ok": True,
                "df": encode_df(resp.dataframe),
                "model_version": resp.model_version,
                "latency_ms": resp.latency_ms,
                "bucket": resp.bucket,
            }
        if op == "swap":
            from flink_ml_tpu.servable.api import load_servable

            self.server.swap(int(msg["version"]), load_servable(msg["path"]))
            return {"ok": True, "version": int(msg["version"])}
        if op == "rollback":
            from flink_ml_tpu.servable.api import load_servable

            self.server.rollback(int(msg["version"]), load_servable(msg["path"]))
            return {"ok": True, "version": int(msg["version"])}
        if op == "rollback_bad":
            from flink_ml_tpu.loop.rollback import RollbackController
            from flink_ml_tpu.metrics import MLMetrics

            if not self.args.publish_dir:
                raise RuntimeError("worker has no --publish-dir; cannot rollback_bad")
            controller = RollbackController(
                self.server,
                self.args.publish_dir,
                scope=f"{MLMetrics.FLEET_GROUP}[{self.args.name}]",
            )
            return {"ok": True, "restored": controller.rollback(int(msg["version"]))}
        if op == "health":
            ok, payload = self.server.health()
            return {"ok": True, "healthy": ok, "payload": payload}
        if op == "stats":
            serving = self._metrics.scope(self.server.scope)
            plancache = self._metrics.scope(self._plancache_group)
            numeric = lambda d: {  # noqa: E731
                k: v for k, v in d.items() if isinstance(v, (int, float))
            }
            return {
                "ok": True,
                "stats": {"serving": numeric(serving), "plancache": numeric(plancache)},
            }
        if op == "close":
            self._stop.set()
            return {"ok": True}
        raise ValueError(f"unknown fleet worker op {op!r}")

    def serve_connection(self, conn) -> None:
        try:
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                try:
                    reply = self.handle(msg)
                except BaseException as e:  # noqa: BLE001 — typed on the wire
                    reply = {"ok": False, "error": encode_error(e)}
                try:
                    conn.send(reply)
                except (BrokenPipeError, OSError):
                    return  # parent hedged elsewhere or died; drop the reply
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- lifecycle ------------------------------------------------------------
    def run(self) -> int:
        authkey = bytes.fromhex(os.environ[AUTHKEY_ENV])
        listener = Listener(("127.0.0.1", 0), authkey=authkey)
        ready = {
            "pid": os.getpid(),
            "address": list(listener.address),
            "telemetry_port": self.server.telemetry.port,
            "scope": self.server.scope,
            "name": self.args.name,
            "version": self.server.model_version,
        }
        ready_path = os.path.join(self.workdir, "ready.json")
        tmp_path = ready_path + ".tmp"
        with open(tmp_path, "w", encoding="utf-8") as f:
            json.dump(ready, f)
        os.rename(tmp_path, ready_path)  # atomic: existence implies complete

        def closer() -> None:
            self._stop.wait()
            try:
                listener.close()  # unblocks accept()
            except OSError:
                pass

        threading.Thread(target=closer, daemon=True, name="fleet-worker-closer").start()
        while not self._stop.is_set():
            try:
                conn = listener.accept()
            except (OSError, EOFError):
                break
            threading.Thread(
                target=self.serve_connection, args=(conn,), daemon=True,
                name="fleet-worker-conn",
            ).start()
        self.server.close(drain=True)
        self._telemetry.emit(
            "fleet.replica.down", self.server.scope, {"name": self.args.name}
        )
        self._telemetry.get_recorder().close()
        return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="fleet replica worker")
    parser.add_argument("--name", required=True)
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--publish-dir", default=None)
    parser.add_argument("--load-version", type=int, default=None)
    parser.add_argument("--template", default=None)
    args = parser.parse_args(argv)
    return _Worker(args).run()


if __name__ == "__main__":
    raise SystemExit(main())
