"""Fleet serving: a supervised pool of replicas behind one routing front door.

One ``InferenceServer`` already survives bad versions (rollback), overload
(typed shed + retry_after) and restarts (plan cache). This package makes a
*set* of them survive each other (docs/fleet.md):

- :class:`ReplicaPool` — membership, rotation state and the canary slice
  gate over N process-isolated (or in-process) replicas;
- :class:`FleetRouter` — consistent-hash / least-loaded / priority dispatch
  that treats ``ServingOverloadedError.retry_after_ms`` as the backpressure
  protocol: bounded jittered retries on a *different* replica, hedged
  requests past a latency quantile, fail-fast when the whole fleet sheds;
- :class:`ReplicaSupervisor` — /healthz-driven eject → respawn (through
  ``execution.Supervisor`` restart strategies, plancache making the respawn
  O(load) not O(XLA)) → health-gated re-admission;
- :class:`CanaryController` — new versions serve a bounded slice on a
  canary replica, scored live by ``DriftMonitor``; promoted rolling
  replica-by-replica (never below quorum) or quarantined via the
  ``RollbackController`` path.

Every decision is journaled by the flight recorder; ``tools/fleetview.py``
aggregates the per-replica journals into one fleet timeline.
"""
from flink_ml_tpu.fleet.canary import CanaryController
from flink_ml_tpu.fleet.errors import FleetQuorumError, ReplicaUnavailableError
from flink_ml_tpu.fleet.pool import FleetConfig, ReplicaPool, ReplicaSlot
from flink_ml_tpu.fleet.replica import LocalReplica, ProcessReplica
from flink_ml_tpu.fleet.router import FleetRouter
from flink_ml_tpu.fleet.supervisor import ReplicaSupervisor

__all__ = [
    "CanaryController",
    "FleetConfig",
    "FleetQuorumError",
    "FleetRouter",
    "LocalReplica",
    "ProcessReplica",
    "ReplicaPool",
    "ReplicaSlot",
    "ReplicaSupervisor",
    "ReplicaUnavailableError",
]
