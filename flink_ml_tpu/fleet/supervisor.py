"""ReplicaSupervisor — health-checks the pool, ejects, respawns, re-admits.

The fleet's failure-handling loop, built on the same restart machinery the
training tier uses (``execution.Supervisor`` + ``RestartStrategies``): a
replica that fails ``/healthz`` ``fleet.health.failures`` times in a row is
**ejected** from rotation (the router stops sending it traffic immediately),
then respawned through a per-slot restart strategy. Each respawn attempt
kills the old process, re-invokes the pool's replica factory at the current
fleet version, and — the re-admission gate — must pass a live health check
before the slot returns to ``serving``. The shared plan cache makes the
respawn O(model load), not O(XLA compile): the replacement warms from
serialized executables, which fleet_smoke proves by asserting zero
serving-path compiles on the rejoined replica (docs/plancache.md).

When the restart budget is exhausted the slot is marked ``dead`` and the
fleet keeps serving on the survivors — capacity degrades, correctness does
not. Every eject / respawn attempt / readmit / dead transition is journaled
with its evidence (consecutive failure count, last health payload, attempt
number) via the pool's ledger plus the execution supervisor's own
``execution.restart`` records.

``fleet.respawn`` is the chaos seam: it trips at the head of every respawn
attempt, the restart strategy absorbs injected faults (``InjectedFault`` is
retryable by construction), and a slot is only ever re-admitted after an
attempt that ran the health gate clean.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import flink_ml_tpu.telemetry as telemetry
from flink_ml_tpu.execution.classify import ErrorClassifier
from flink_ml_tpu.execution.restart import RestartStrategy, RestartStrategies
from flink_ml_tpu.execution.supervisor import Supervisor
from flink_ml_tpu.faults import faults
from flink_ml_tpu.fleet.errors import ReplicaUnavailableError
from flink_ml_tpu.fleet.pool import ReplicaPool
from flink_ml_tpu.metrics import MLMetrics, metrics

__all__ = ["ReplicaSupervisor"]


class ReplicaSupervisor:
    """Health loop + eject/respawn/readmit state machine over a pool."""

    def __init__(
        self,
        pool: ReplicaPool,
        *,
        factory: Optional[Callable] = None,
        interval_ms: Optional[float] = None,
        fail_threshold: Optional[int] = None,
        strategy_factory: Optional[Callable[[], RestartStrategy]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        cfg = pool.config
        self._pool = pool
        self._factory = factory if factory is not None else pool.factory
        self.interval_s = float(
            interval_ms if interval_ms is not None else cfg.health_interval_ms
        ) / 1000.0
        self.fail_threshold = int(
            fail_threshold if fail_threshold is not None else cfg.health_failures
        )
        # Per-respawn restart budget: 3 immediate attempts by default, same
        # CI-friendly default as the training supervisor.
        self._strategy_factory = strategy_factory or (
            lambda: RestartStrategies.fixed_delay_restart(3, 0.0)
        )
        self._classifier = ErrorClassifier(extra_retryable=(ReplicaUnavailableError,))
        self._clock = clock
        self._sleep = sleep
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one sweep -------------------------------------------------------------
    def check_once(self) -> None:
        """Probe every serving slot once; eject-and-respawn any slot past the
        consecutive-failure threshold. Deterministic unit of the health loop —
        tests drive it directly, the background thread just paces it."""
        pool = self._pool
        for index in range(pool.size):
            slot = pool.slot(index)
            with pool._lock:
                if slot.state != "serving":
                    continue
                replica = slot.replica
                name = slot.name
            try:
                ok, payload = replica.health_check()
            except Exception as e:  # noqa: BLE001 — a probe crash IS unhealth
                ok, payload = False, {"status": "probe-error", "error": type(e).__name__}
            with pool._lock:
                if slot.state != "serving":
                    continue  # membership changed under us; skip this round
                if ok:
                    slot.consecutive_failures = 0
                    continue
                slot.consecutive_failures += 1
                failures = slot.consecutive_failures
                should_eject = failures >= self.fail_threshold
            if should_eject:
                self._eject_and_respawn(
                    index, name, failures=failures, payload=payload
                )

    def _eject_and_respawn(self, index: int, name: str, *, failures: int, payload) -> bool:  # graftcheck: cold
        pool = self._pool
        old = pool.slot(index).replica
        pool.eject(
            index,
            reason="health-check",
            evidence={
                "consecutive_failures": failures,
                "threshold": self.fail_threshold,
                "health": payload if isinstance(payload, dict) else {"status": str(payload)},
            },
        )

        def reap(replica, stage: str) -> None:
            """Kill a replica that is already being replaced; its failure to
            die cleanly is evidence, not a new failure mode."""
            try:
                replica.kill()
            except Exception as e:  # noqa: BLE001 — already dead is fine here
                telemetry.emit(
                    "fleet.reap.error",
                    pool.scope,
                    {"replica": name, "stage": stage, "error": type(e).__name__},
                )

        def attempt():
            faults.trip("fleet.respawn", replica=name, slot=index)
            reap(old, "pre-respawn")  # idempotent; frees the port/pid first
            metrics.counter(pool.scope, MLMetrics.FLEET_RESPAWNS)
            replacement = self._factory(index, name, pool.fleet_version)
            ok, health = replacement.health_check()
            if not ok:
                reap(replacement, "failed-readmission")
                raise ReplicaUnavailableError(
                    f"respawned replica {name} failed the re-admission health "
                    f"check: {health}",
                    replica=name,
                )
            return replacement

        supervisor = Supervisor(
            strategy=self._strategy_factory(),
            classifier=self._classifier,
            name=f"fleet-respawn[{name}]",
            clock=self._clock,
            sleep=self._sleep,
        )
        try:
            replacement = supervisor.run(attempt)
        except Exception as e:  # noqa: BLE001 — budget exhausted or fatal
            pool.mark_dead(index, e)
            return False
        pool.readmit(index, replacement)
        telemetry.emit(
            "fleet.respawn",
            pool.scope,
            {
                "replica": name,
                "slot": index,
                "attempts": supervisor.attempts,
                "version": pool.fleet_version,
            },
        )
        return True

    # -- background loop -------------------------------------------------------
    def start(self) -> "ReplicaSupervisor":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"fleet-supervisor[{self._pool.name}]"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception as e:  # noqa: BLE001 — the health loop must not die
                telemetry.emit(
                    "fleet.supervisor.error",
                    self._pool.scope,
                    {"error": type(e).__name__, "detail": str(e)[:200]},
                )

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)

    def __enter__(self) -> "ReplicaSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
