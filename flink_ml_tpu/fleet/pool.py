"""ReplicaPool — the fleet's membership, rotation and canary bookkeeping.

One pool owns N replica slots. A slot holds a replica handle
(:mod:`fleet/replica`) plus its rotation state: ``serving`` (dispatchable),
``ejected`` (out of rotation, being respawned), or ``dead`` (restart budget
exhausted). The router reads rotation snapshots per dispatch; the supervisor
moves slots between states; the canary controller designates at most one
slot as the canary and the pool's **counter gate** enforces the traffic
slice as a hard invariant: a canary dispatch is admitted only while
``canary_dispatches + 1 <= slice * (total_dispatches + 1)``, which keeps
``canary_dispatches <= slice * total_dispatches`` at every instant — the
bound fleet_smoke asserts, not a best-effort target.

Every membership decision (eject / readmit / dead) is journaled with its
evidence by the flight recorder under the fleet scope, and mirrored into the
``ml.fleet.*`` metrics (docs/fleet.md).

Replica construction is a ``factory(slot_index, name, version)`` callable —
``LocalReplica`` factories give tier-1 tests thread-isolated fleets;
``ProcessReplica.spawn`` factories give CI real process isolation. The pool
never cares which.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import flink_ml_tpu.telemetry as telemetry
from flink_ml_tpu.config import Options, config
from flink_ml_tpu.metrics import MLMetrics, metrics

__all__ = ["FleetConfig", "ReplicaSlot", "ReplicaPool"]


class FleetConfig:
    """Resolved fleet knobs — every unset field falls back to the runtime
    config tier (``fleet.*`` options, docs/configuration.md), mirroring
    ``ServingConfig``."""

    def __init__(
        self,
        replicas: Optional[int] = None,
        *,
        policy: Optional[str] = None,
        retry_attempts: Optional[int] = None,
        retry_backoff_ms: Optional[float] = None,
        retry_backoff_max_ms: Optional[float] = None,
        retry_jitter: Optional[float] = None,
        hedge_quantile: Optional[float] = None,
        hedge_min_ms: Optional[float] = None,
        health_interval_ms: Optional[float] = None,
        health_failures: Optional[int] = None,
        quorum: Optional[int] = None,
        respawn_timeout_ms: Optional[float] = None,
        canary_slice: Optional[float] = None,
        canary_min_scores: Optional[int] = None,
    ):
        self.replicas = int(
            replicas if replicas is not None else config.get(Options.FLEET_REPLICAS)
        )
        self.policy = str(
            policy if policy is not None else config.get(Options.FLEET_ROUTER_POLICY)
        )
        self.retry_attempts = int(
            retry_attempts if retry_attempts is not None
            else config.get(Options.FLEET_RETRY_ATTEMPTS)
        )
        self.retry_backoff_ms = float(
            retry_backoff_ms if retry_backoff_ms is not None
            else config.get(Options.FLEET_RETRY_BACKOFF_MS)
        )
        self.retry_backoff_max_ms = float(
            retry_backoff_max_ms if retry_backoff_max_ms is not None
            else config.get(Options.FLEET_RETRY_BACKOFF_MAX_MS)
        )
        self.retry_jitter = float(
            retry_jitter if retry_jitter is not None
            else config.get(Options.FLEET_RETRY_JITTER)
        )
        hq = (
            hedge_quantile if hedge_quantile is not None
            else config.get(Options.FLEET_HEDGE_QUANTILE)
        )
        self.hedge_quantile = float(hq) if hq is not None else None
        self.hedge_min_ms = float(
            hedge_min_ms if hedge_min_ms is not None
            else config.get(Options.FLEET_HEDGE_MIN_MS)
        )
        self.health_interval_ms = float(
            health_interval_ms if health_interval_ms is not None
            else config.get(Options.FLEET_HEALTH_INTERVAL_MS)
        )
        self.health_failures = int(
            health_failures if health_failures is not None
            else config.get(Options.FLEET_HEALTH_FAILURES)
        )
        q = quorum if quorum is not None else config.get(Options.FLEET_QUORUM)
        # Default quorum: a strict majority of the pool.
        self.quorum = int(q) if q is not None else (self.replicas // 2 + 1)
        self.respawn_timeout_ms = float(
            respawn_timeout_ms if respawn_timeout_ms is not None
            else config.get(Options.FLEET_RESPAWN_TIMEOUT_MS)
        )
        self.canary_slice = float(
            canary_slice if canary_slice is not None
            else config.get(Options.FLEET_CANARY_SLICE)
        )
        self.canary_min_scores = int(
            canary_min_scores if canary_min_scores is not None
            else config.get(Options.FLEET_CANARY_MIN_SCORES)
        )

    def __repr__(self) -> str:
        return (
            f"FleetConfig(replicas={self.replicas}, policy={self.policy!r}, "
            f"retry_attempts={self.retry_attempts}, quorum={self.quorum}, "
            f"canary_slice={self.canary_slice})"
        )


class ReplicaSlot:
    """One pool position and its rotation state. All fields are guarded by
    the owning pool's lock — slots are bookkeeping, not handles; the replica
    object itself is only ever *called* outside the lock."""

    __slots__ = (
        "index", "name", "replica", "state", "canary", "consecutive_failures",
        "inflight", "last_error",
    )

    def __init__(self, index: int, name: str, replica):
        self.index = index
        self.name = name
        self.replica = replica
        self.state = "serving"  # serving | ejected | dead
        self.canary = False
        self.consecutive_failures = 0
        self.inflight = 0
        self.last_error: Optional[str] = None


class ReplicaPool:
    """N replicas, one membership ledger, one canary slice gate."""

    def __init__(
        self,
        factory: Callable[[int, str, Optional[int]], Any],
        n: Optional[int] = None,
        *,
        name: str = "fleet",
        fleet_config: Optional[FleetConfig] = None,
        initial_version: Optional[int] = None,
    ):
        self.name = name
        self.scope = f"{MLMetrics.FLEET_GROUP}[{name}]"
        self.config = fleet_config or FleetConfig(replicas=n)
        if n is not None:
            self.config.replicas = int(n)
        self.factory = factory
        self._lock = threading.RLock()
        self._fleet_version = initial_version
        self._total_dispatches = 0
        self._canary_dispatches = 0
        self._canary_version: Optional[int] = None
        self._slots: List[ReplicaSlot] = []
        for i in range(self.config.replicas):
            replica_name = f"{name}-r{i}"
            replica = factory(i, replica_name, initial_version)
            self._slots.append(ReplicaSlot(i, replica_name, replica))
        metrics.gauge(self.scope, MLMetrics.FLEET_SIZE, len(self._slots))
        self._refresh_live_gauge()

    # -- reads -----------------------------------------------------------------
    @property
    def size(self) -> int:
        return len(self._slots)

    @property
    def fleet_version(self) -> Optional[int]:
        with self._lock:
            return self._fleet_version

    def set_fleet_version(self, version: int) -> None:
        with self._lock:
            self._fleet_version = int(version)

    @property
    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._slots if s.state == "serving")

    def slot(self, index: int) -> ReplicaSlot:
        return self._slots[index]

    def replica(self, index: int):
        with self._lock:
            return self._slots[index].replica

    def candidates(self) -> List[Tuple[int, str, Any, bool, int]]:
        """Rotation snapshot for one routing decision:
        ``(index, name, replica, is_canary, inflight)`` per serving slot."""
        with self._lock:
            return [
                (s.index, s.name, s.replica, s.canary, s.inflight)
                for s in self._slots
                if s.state == "serving"
            ]

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {s.name: s.state for s in self._slots}

    # -- dispatch accounting (router-driven) -----------------------------------
    def note_dispatch(self, index: int, *, canary: bool, counted: bool = True) -> None:
        """``counted=False`` is pinned measurement traffic (canary scoring):
        it holds an in-flight slot but never moves the slice counters."""
        with self._lock:
            self._slots[index].inflight += 1
            if counted:
                self._total_dispatches += 1
                if canary:
                    self._canary_dispatches += 1
        if counted:
            metrics.counter(self.scope, MLMetrics.FLEET_DISPATCHES)
            if canary:
                metrics.counter(self.scope, MLMetrics.FLEET_CANARY_DISPATCHES)

    def note_resolve(self, index: int) -> None:
        with self._lock:
            slot = self._slots[index]
            if slot.inflight > 0:
                slot.inflight -= 1

    def canary_allowed(self) -> bool:
        """The hard slice gate: admit a canary dispatch only if the share
        stays <= ``canary_slice`` *after* admitting it."""
        with self._lock:
            if self._canary_version is None:
                return False
            return (self._canary_dispatches + 1) <= self.config.canary_slice * (
                self._total_dispatches + 1
            )

    def dispatch_counts(self) -> Tuple[int, int]:
        """(total, canary) dispatches so far — the slice-invariant evidence."""
        with self._lock:
            return self._total_dispatches, self._canary_dispatches

    # -- membership (supervisor-driven) ----------------------------------------
    def eject(self, index: int, *, reason: str, evidence: Optional[dict] = None) -> None:
        with self._lock:
            slot = self._slots[index]
            name = slot.name
            was_canary = slot.canary
            slot.state = "ejected"
            slot.canary = False
            if was_canary:
                self._canary_version = None
        metrics.counter(self.scope, MLMetrics.FLEET_EJECTS)
        self._refresh_live_gauge()
        data = {"replica": name, "slot": index, "reason": reason}
        data.update(evidence or {})
        telemetry.emit("fleet.eject", self.scope, data)
        telemetry.incident("replica-eject", self.scope, data)

    def readmit(self, index: int, replica) -> None:
        with self._lock:
            slot = self._slots[index]
            name = slot.name
            slot.replica = replica
            slot.state = "serving"
            slot.consecutive_failures = 0
            slot.inflight = 0
            slot.last_error = None
        metrics.counter(self.scope, MLMetrics.FLEET_READMITS)
        self._refresh_live_gauge()
        telemetry.emit(
            "fleet.readmit",
            self.scope,
            {"replica": name, "slot": index, "version": self.fleet_version},
        )

    def mark_dead(self, index: int, error: Optional[BaseException] = None) -> None:
        error_name = type(error).__name__ if error is not None else None
        with self._lock:
            slot = self._slots[index]
            name = slot.name
            slot.state = "dead"
            slot.last_error = error_name
        metrics.counter(self.scope, MLMetrics.FLEET_DEAD)
        self._refresh_live_gauge()
        data = {
            "replica": name,
            "slot": index,
            "error": error_name,
        }
        telemetry.emit("fleet.dead", self.scope, data)
        telemetry.incident("replica-dead", self.scope, data)

    def _refresh_live_gauge(self) -> None:
        metrics.gauge(self.scope, MLMetrics.FLEET_LIVE, self.healthy_count)

    # -- canary designation (controller-driven) --------------------------------
    def set_canary(self, index: int, version: int) -> None:
        with self._lock:
            for s in self._slots:
                s.canary = False
            self._slots[index].canary = True
            self._canary_version = int(version)

    def clear_canary(self) -> None:
        with self._lock:
            for s in self._slots:
                s.canary = False
            self._canary_version = None

    def canary_slot(self) -> Optional[int]:
        with self._lock:
            for s in self._slots:
                if s.canary:
                    return s.index
            return None

    @property
    def canary_version(self) -> Optional[int]:
        with self._lock:
            return self._canary_version

    # -- lifecycle -------------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        with self._lock:
            replicas = [s.replica for s in self._slots if s.state != "dead"]
        for replica in replicas:
            try:
                replica.close(drain=drain)
            except Exception as e:  # noqa: BLE001 — best-effort fleet shutdown
                telemetry.emit(
                    "fleet.close.error",
                    self.scope,
                    {"replica": getattr(replica, "name", None), "error": type(e).__name__},
                )

    def __enter__(self) -> "ReplicaPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)

    def __repr__(self) -> str:
        return (
            f"ReplicaPool({self.name!r}, size={self.size}, "
            f"healthy={self.healthy_count}, version={self.fleet_version})"
        )
