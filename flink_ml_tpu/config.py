"""Runtime configuration tier — typed flags beyond per-stage params.

Reference: Flink's ``ConfigOption`` system as used by
``iteration/config/IterationOptions.java`` (``iteration.data-cache.path``) and
the cluster-level options stage params never cover (parallelism, temp dirs).
Stage hyperparameters stay in ``params/``; this tier holds *runtime* knobs —
spill locations, memory budgets, mesh shape, streaming window size.

Resolution order per option: programmatic ``set()`` > environment variable >
default. The env name is derived from the key
(``datacache.spill.dir`` → ``FLINK_ML_TPU_DATACACHE_SPILL_DIR``), so
deployments configure the runtime without code changes — the role Flink's
``flink-conf.yaml`` plays.

    from flink_ml_tpu.config import config, Options
    config.set(Options.DATACACHE_SPILL_DIR, "/mnt/ssd/spill")
    ...
    cache = HostDataCache()   # spills under /mnt/ssd/spill
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

__all__ = [
    "ConfigOption",
    "Configuration",
    "Options",
    "config",
    "resolve_cache_config",
]


class ConfigOption:
    """A typed runtime option (ref ConfigOptions.key(...).xxxType())."""

    def __init__(self, key: str, type_: Callable, default, description: str):
        self.key = key
        self.type = type_
        self.default = default
        self.description = description

    @property
    def env_var(self) -> str:
        return "FLINK_ML_TPU_" + self.key.upper().replace(".", "_").replace("-", "_")

    def __repr__(self) -> str:
        return f"ConfigOption({self.key!r}, default={self.default!r})"


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "true", "yes", "on")


class Options:
    """The framework's runtime options (one place, like IterationOptions)."""

    DATACACHE_SPILL_DIR = ConfigOption(
        "datacache.spill.dir",
        str,
        None,
        "Base path for capacity-tier cache spill files "
        "(ref iteration.data-cache.path). Default: none — past-budget chunks "
        "stay in host RAM unless a spill dir is configured.",
    )
    DATACACHE_MEMORY_BUDGET_BYTES = ConfigOption(
        "datacache.memory.budget.bytes",
        int,
        1 << 30,
        "Host RAM a capacity-tier cache may hold before spilling to disk "
        "(the managed-memory fraction role of the reference's MemorySegment pool).",
    )
    TRAIN_STREAM_WINDOW_ROWS = ConfigOption(
        "train.stream.window.rows",
        int,
        65_536,
        "Per-shard HBM window size (rows) for streamed larger-than-HBM training.",
    )
    TRAIN_MESH = ConfigOption(
        "train.mesh",
        int,
        None,
        "Data-axis width of the sharded TRAINING mesh "
        "(parallel/train_sharding.py). Unset: legacy single-mesh training. "
        "Set — including 1 — training runs the deterministic sharded tier: "
        "block-cyclic data deal, mapreduce collectives, epochs bit-identical "
        "across mesh widths (docs/distributed_training.md).",
    )
    TRAIN_MESH_MODEL = ConfigOption(
        "train.mesh.model",
        int,
        1,
        "Model-axis width of the sharded training mesh (tensor parallelism "
        "for wide coefficients; rides the non-deterministic psum seam).",
    )
    TRAIN_MESH_HOSTS = ConfigOption(
        "train.mesh.hosts",
        int,
        1,
        "Host count of a multi-host training run. >1 arms the one guarded "
        "jax.distributed.initialize() call (coordinator/process env per the "
        "standard JAX contract); 1 — the default — never touches the "
        "distributed runtime.",
    )
    MESH_DATA_AXIS_SIZE = ConfigOption(
        "mesh.data.axis.size",
        int,
        None,
        "Data-parallel axis size of the default mesh (the job-parallelism "
        "role). Default: all visible devices / model axis size.",
    )
    MESH_MODEL_AXIS_SIZE = ConfigOption(
        "mesh.model.axis.size",
        int,
        1,
        "Model-parallel axis size of the default mesh.",
    )
    FAULT_INJECTION = ConfigOption(
        "faults.spec",
        str,
        None,
        "Deterministic fault-injection spec, e.g. "
        "'checkpoint.save:at=2;iteration.epoch:prob=0.05,seed=7' "
        "(see flink_ml_tpu.faults). Default: no faults armed.",
    )
    SERVING_MAX_BATCH_SIZE = ConfigOption(
        "serving.max.batch.size",
        int,
        64,
        "Largest micro-batch (rows) the serving batcher coalesces — also the "
        "largest padded bucket, so it bounds the jit-compiled shape set.",
    )
    SERVING_MAX_DELAY_MS = ConfigOption(
        "serving.max.delay.ms",
        float,
        2.0,
        "How long the micro-batcher may hold the first queued request while "
        "coalescing more (the batching-latency budget).",
    )
    SERVING_QUEUE_CAPACITY_ROWS = ConfigOption(
        "serving.queue.capacity.rows",
        int,
        1024,
        "Admission-control bound: rows that may wait in the serving queue "
        "before new requests are rejected with ServingOverloadedError.",
    )
    SERVING_DEFAULT_TIMEOUT_MS = ConfigOption(
        "serving.default.timeout.ms",
        float,
        10_000.0,
        "Per-request deadline when the caller does not pass one; a request "
        "not completed by its deadline raises ServingDeadlineError.",
    )
    SERVING_POLL_INTERVAL_MS = ConfigOption(
        "serving.poll.interval.ms",
        float,
        1000.0,
        "How often ModelVersionPoller re-scans the model directory for a "
        "newer published version.",
    )
    SERVING_POLL_BACKOFF_MAX_MS = ConfigOption(
        "serving.poll.backoff.max.ms",
        float,
        30_000.0,
        "Ceiling of the ModelVersionPoller's jittered exponential backoff on "
        "consecutive scan failures (an unreadable publish directory must not "
        "be hammered at full cadence forever); one successful scan resets "
        "the cadence to serving.poll.interval.ms.",
    )
    SERVING_FASTPATH = ConfigOption(
        "serving.fastpath",
        _parse_bool,
        True,
        "Serve through CompiledServingPlan when the servable exposes kernel "
        "specs: fused per-bucket AOT executables with device-resident model "
        "arrays (docs/serving.md). Off = always the per-stage transform path.",
    )
    SERVING_PIPELINE_DEPTH = ConfigOption(
        "serving.pipeline.depth",
        int,
        2,
        "Micro-batcher dispatch window: how many batches may be dispatched to "
        "the device before the oldest is finalized. 2 overlaps host-side "
        "claim/pad/scatter of batch N+1 with device execution of batch N; "
        "1 = strict sequential. Only effective on the fast path.",
    )
    SERVING_CONTROLLER = ConfigOption(
        "serving.controller",
        _parse_bool,
        True,
        "SLO-adaptive serving controller (serving/controller.py, "
        "docs/serving.md): priority-aware load shedding under sustained "
        "overload, deadline-aware bucket downshift, and pipeline-depth "
        "stepping driven by the live goodput ledger. Off = admission control "
        "is the bounded queue alone (pre-PR-11 behavior).",
    )
    SERVING_SHED_WATERMARK = ConfigOption(
        "serving.shed.watermark",
        float,
        0.75,
        "Queue-occupancy fraction (queued rows / capacity) above which the "
        "adaptive controller begins shedding sheddable-priority requests — "
        "strictly below 1.0 so sheds happen BEFORE the bounded queue "
        "hard-rejects everything indiscriminately.",
    )
    SERVING_SHED_SUSTAIN_MS = ConfigOption(
        "serving.shed.sustain.ms",
        float,
        20.0,
        "How long the queue must stay above serving.shed.watermark before "
        "priority shedding starts — a single coalescing burst should not "
        "shed anybody; sustained overload should.",
    )
    SERVING_SHED_PRIORITY = ConfigOption(
        "serving.shed.priority",
        int,
        1,
        "Lowest priority value the controller may shed (requests carry an "
        "integer priority, 0 = most important). Priorities >= this value are "
        "sheddable under sustained overload; priorities below it are only "
        "ever rejected by the hard queue bound.",
    )
    SERVING_CONTROLLER_WINDOW_MS = ConfigOption(
        "serving.controller.window.ms",
        float,
        2000.0,
        "Rolling window of the controller's live goodput ledger — the queue/"
        "productive/padding second totals its decisions read are sums over "
        "the last this-many milliseconds.",
    )
    SERVING_CONTROLLER_QUEUE_FRACTION = ConfigOption(
        "serving.controller.queue.fraction",
        float,
        0.5,
        "Queue-category share of the goodput ledger above which the "
        "controller steps serving.pipeline.depth up (and, at the depth "
        "ceiling, recommends the next mesh width on the PR 9 ladder); the "
        "depth steps back down when the share falls below a quarter of this.",
    )
    SERVING_CONTROLLER_DEPTH_MAX = ConfigOption(
        "serving.controller.depth.max",
        int,
        4,
        "Ceiling of the controller's pipeline-depth ladder: "
        "serving.pipeline.depth is stepped within [configured depth, this].",
    )
    SERVING_DEADLINE_SAFETY = ConfigOption(
        "serving.deadline.safety",
        float,
        2.0,
        "Safety factor of the deadline-aware bucket downshift: a batch is "
        "capped to the largest bucket whose EWMA service time x this factor "
        "fits the head request's remaining deadline.",
    )
    SERVING_MESH = ConfigOption(
        "serving.mesh",
        int,
        1,
        "Data-parallel mesh width of the serving fast path: fused per-bucket "
        "executables compile as SPMD programs with micro-batch rows sharded "
        "over N devices, model arrays device-put per shard at swap time. "
        "1 (default) = today's single-device path, unchanged. Buckets become "
        "multiples of N with at least MIN_SHARD_ROWS rows per shard so "
        "per-row results stay bit-identical to mesh=1 (docs/serving.md).",
    )
    SERVING_MESH_MODEL = ConfigOption(
        "serving.mesh.model",
        int,
        1,
        "OPTIONAL tensor-parallel axis of the serving mesh: wide 2-D model "
        "heads (e.g. MLP W{i}) additionally shard their output dim over this "
        "many devices. NOT covered by the bit-exactness contract — partial "
        "products may reassociate; results carry a documented ulp envelope "
        "(docs/serving.md). 1 (default) = no tensor parallelism.",
    )
    BATCH_MESH = ConfigOption(
        "batch.mesh",
        int,
        1,
        "Data-parallel mesh width of the batch transform fast path: chunk "
        "ingest device-puts one shard per device and fused programs run "
        "SPMD over N devices. Ragged final chunks round up to a multiple of "
        "N (pad rows sliced off, counted by ml.batch.shard.pad.rows); tails "
        "too small to shard run replicated so per-row results stay "
        "bit-identical to mesh=1 (docs/batch_transform.md). 1 = today's path.",
    )
    BATCH_MESH_MODEL = ConfigOption(
        "batch.mesh.model",
        int,
        1,
        "Optional tensor-parallel axis of the batch transform mesh — same "
        "wide-head sharding and ulp caveat as serving.mesh.model. 1 = off.",
    )
    PLANCACHE_ENABLED = ConfigOption(
        "plancache.enabled",
        _parse_bool,
        True,
        "Whether the compiled plans may use the persistent plan cache "
        "(servable/plancache.py, docs/plancache.md) when plancache.dir is "
        "configured: fused chain executables are serialized to disk at "
        "compile time and loaded back on the next (re)build — a restarted "
        "or hot-swapped incarnation reaches first response in O(load) "
        "instead of O(XLA compile). Off = always compile live.",
    )
    PLANCACHE_DIR = ConfigOption(
        "plancache.dir",
        str,
        None,
        "Directory of the persistent compiled-plan cache. Default: none — "
        "the cache is inactive and every plan compiles live (unchanged "
        "behavior). Configure a stable path in deployments so supervisor "
        "restarts, hot swaps, and rollbacks reuse the serialized "
        "executables (docs/plancache.md has the key schema and the "
        "corruption/fallback contract).",
    )
    PLANCACHE_MAX_BYTES = ConfigOption(
        "plancache.max.bytes",
        int,
        256 << 20,
        "LRU bound of the plan-cache entry tier: past this many bytes of "
        "*.plan entries the least-recently-loaded entries are evicted "
        "(ml.plancache.evicted). The second tier (JAX's own persistent "
        "compilation cache under <dir>/xla) is governed by JAX's knobs.",
    )
    FUSION_MODE = ConfigOption(
        "fusion.mode",
        str,
        "exact",
        "Fusion tier of the compiled plans (docs/fusion.md). 'exact' "
        "(default) = per-stage programs with elementwise-only merges — "
        "bit-identical to the per-stage transform path. 'fast' = fuse across "
        "reduction boundaries into single XLA programs and, for chains the "
        "cost model marks hottest, hand-fused Pallas megakernels keeping "
        "intermediates VMEM-resident; results carry a documented per-chain "
        "ulp envelope instead of bit-equality.",
    )
    FUSION_MEGAKERNEL = ConfigOption(
        "fusion.megakernel",
        _parse_bool,
        True,
        "Whether fusion.mode=fast may lower Pallas megakernels for hot "
        "chains (servable/megakernels.py; pallas.interpret on CPU). Off = "
        "fast mode still merges across reductions but only into XLA "
        "programs. No effect in exact mode.",
    )
    FUSION_MEGAKERNEL_MIN_SCORE = ConfigOption(
        "fusion.megakernel.min.score",
        float,
        1e6,
        "Cost-model hotness bar for the megakernel lowering: a chain lowers "
        "as a Pallas megakernel only when rows x estimated-FLOPs-per-row "
        "(from stage shapes) reaches this score at compile time "
        "(docs/fusion.md has the model). Below the bar, fast mode uses the "
        "merged XLA program.",
    )
    PRECISION_MODE = ConfigOption(
        "precision.mode",
        str,
        "f32",
        "Precision tier of the compiled plans (docs/precision.md). 'f32' "
        "(default) = every transport and accumulation in float32, "
        "bit-identical to pre-precision behavior. 'bf16' = bfloat16 "
        "transport with float32 accumulation: inputs round to the bf16 grid "
        "at ingest and at every stage boundary, reductions stay f32; results "
        "carry the documented per-chain within-tier ulp envelope "
        "(servable/precision.py). 'int8' = bf16 transport plus post-training "
        "int8 weight quantization applied at publish_servable time only — "
        "the quantized artifact is just another published version.",
    )
    PRECISION_FALLBACK_AUTO = ConfigOption(
        "precision.fallback.auto",
        _parse_bool,
        True,
        "Whether a drift-regressed verdict on a low-precision serving tier "
        "automatically falls back to the warm f32 plan of the SAME version "
        "(a fallback, not a rollback: the model version does not change; "
        "docs/precision.md). Off = drift regressions follow the normal "
        "rollback path regardless of tier.",
    )
    SPARSE_FASTPATH = ConfigOption(
        "sparse.fastpath",
        _parse_bool,
        True,
        "Let sparse/ragged columns ride the compiled plans through the sparse "
        "calling convention (docs/sparse.md): values/ids/segment-ids as dense "
        "device arrays on the power-of-two nnz-cap ladder, segment-reduce "
        "kernels, sparse-aware fusion costing. Off = every sparse column "
        "falls back to the bit-exact per-stage path (pre-sparse behavior).",
    )
    SPARSE_NNZ_CAP_MAX = ConfigOption(
        "sparse.nnz.cap.max",
        int,
        64,
        "Top rung of the sparse nnz-per-row bucket ladder. A batch whose "
        "rows carry more entries than this is off-ladder and serves through "
        "the per-stage fallback (counted under the 'off_ladder' fallback "
        "reason) instead of compiling an unbounded executable set.",
    )
    SPARSE_WARMUP_CAPS = ConfigOption(
        "sparse.warmup.caps",
        str,
        None,
        "Comma-separated nnz caps the serving warmup AOT-compiles per bucket "
        "for sparse segments (each rounds up to its ladder rung). Default: "
        "the full power-of-two ladder up to sparse.nnz.cap.max — zero "
        "post-warmup compiles for every on-ladder batch.",
    )
    RETRIEVAL_K_CAP_MAX = ConfigOption(
        "retrieval.k.cap.max",
        int,
        128,
        "Top rung of the retrieval top-K output-width ladder (docs/"
        "retrieval.md). A per-request K rounds up to the next power of two "
        "(the K rung joins the compiled-plan key next to the row bucket and "
        "the nnz cap); a batch asking for more than this serves through the "
        "per-stage fallback (counted under the 'off_ladder' fallback reason) "
        "instead of compiling an unbounded executable set.",
    )
    RETRIEVAL_WARMUP_KS = ConfigOption(
        "retrieval.warmup.ks",
        str,
        None,
        "Comma-separated per-request K values the serving warmup AOT-compiles "
        "per (bucket, nnz cap) for retrieval segments (each rounds up to its "
        "ladder rung). Default: the full power-of-two ladder up to "
        "retrieval.k.cap.max — zero post-warmup compiles for every on-ladder "
        "K. Deployments serving only a couple of Ks narrow this to cut "
        "warmup wall time.",
    )
    RETRIEVAL_LSH_PRUNE_CAP = ConfigOption(
        "retrieval.lsh.prune.cap",
        int,
        1024,
        "Static candidate count the LSH bucket-prune phase hands to the exact "
        "1-Jaccard rank phase (the two-phase retrieve-then-rank plan, docs/"
        "retrieval.md). Queries whose bucket-sharing candidate set exceeds "
        "this are approximated: only the cap candidates with the most shared "
        "hash tables reach the exact rank. Raising it trades device FLOPs "
        "for recall; parity with the host reference holds whenever the true "
        "candidate set fits the cap.",
    )
    BATCH_FASTPATH = ConfigOption(
        "batch.fastpath",
        _parse_bool,
        True,
        "Run PipelineModel.transform through CompiledBatchPlan when stages "
        "expose kernel specs: fused per-stage AOT programs with columns "
        "device-resident between stages, chunked for larger-than-HBM inputs "
        "(docs/batch_transform.md). Off = always the per-stage transform path.",
    )
    BATCH_CHUNK_ROWS = ConfigOption(
        "batch.chunk.rows",
        int,
        65_536,
        "Rows per device chunk for the batch transform fast path — the "
        "datacache-window role: inputs larger than one chunk stream through "
        "the compiled plan chunk by chunk (one ingest + one readback each).",
    )
    BATCH_PREFETCH_DEPTH = ConfigOption(
        "batch.prefetch.depth",
        int,
        2,
        "Chunks that may be dispatched to the device before the oldest is "
        "read back. 2 overlaps host gather + device_put of chunk j+1 with "
        "device execution of chunk j (the streamed-SGD prefetch-gap design); "
        "1 = strict sequential.",
    )
    LOOP_PUBLISH_EVERY_VERSIONS = ConfigOption(
        "loop.publish.every.versions",
        int,
        1,
        "Continuous-learning publish cadence: every Nth trained model version "
        "is published as a servable (docs/continuous.md). 1 = every version.",
    )
    LOOP_PUBLISH_EVERY_SECONDS = ConfigOption(
        "loop.publish.every.seconds",
        float,
        None,
        "Additional time-based publish trigger: a trained-but-unpublished "
        "version older than this is published even before the Nth-version "
        "cadence is due. Default: none — cadence only.",
    )
    LOOP_DRIFT_WINDOW = ConfigOption(
        "loop.drift.window",
        int,
        4,
        "Rolling window (number of scored evaluation batches) the drift "
        "monitor averages per model version before comparing against the "
        "baseline version.",
    )
    LOOP_DRIFT_REL_THRESHOLD = ConfigOption(
        "loop.drift.rel.threshold",
        float,
        0.25,
        "Relative regression threshold: the live version regresses when its "
        "rolling score is worse than the baseline's by more than this "
        "fraction (loss: mean > baseline * (1 + t); AUC-style metrics: "
        "mean < baseline * (1 - t)).",
    )
    LOOP_DRIFT_ABS_THRESHOLD = ConfigOption(
        "loop.drift.abs.threshold",
        float,
        0.0,
        "Absolute slack added on top of the relative drift threshold — a "
        "live score within this distance of the baseline never regresses "
        "(guards near-zero baselines).",
    )
    LOOP_DRIFT_MIN_SCORES = ConfigOption(
        "loop.drift.min.scores",
        int,
        1,
        "Minimum scored batches for the live version before a drift verdict "
        "may fire (a single noisy window should not roll back a model).",
    )
    OBSERVABILITY_TRACE = ConfigOption(
        "observability.trace",
        _parse_bool,
        False,
        "Record structured spans (flink_ml_tpu.trace) across serving, batch "
        "transform, iteration and the continuous loop. Off = the tracer is a "
        "single attribute check on every instrumented site — no spans, no "
        "allocation, no lock (docs/observability.md).",
    )
    OBSERVABILITY_TRACE_CAPACITY = ConfigOption(
        "observability.trace.capacity",
        int,
        65_536,
        "Bounded-ring capacity of the span recorder: the newest N finished "
        "spans are retained; older ones drop off (SpanRecorder.dropped counts "
        "them).",
    )
    OBSERVABILITY_JOURNAL = ConfigOption(
        "observability.journal",
        _parse_bool,
        True,
        "Always-on flight recorder (flink_ml_tpu.telemetry): every runtime "
        "decision (swap, rollback, shed, controller action, plan choice, "
        "fault trip, restart) appends one structured JSONL record to a "
        "crash-safe on-disk journal, written by a dedicated writer thread — "
        "the hot path pays one bounded-queue enqueue. Off = emit() is a "
        "single attribute check (docs/observability.md).",
    )
    OBSERVABILITY_JOURNAL_DIR = ConfigOption(
        "observability.journal.dir",
        str,
        None,
        "Directory of the flight-recorder journal (and, by default, its "
        "incident bundles). Default: none — a fresh per-process directory "
        "under the system temp dir. Configure a stable path in deployments "
        "so a new incarnation resumes the sequence after a crash and "
        "crash-resume itself emits an incident bundle.",
    )
    OBSERVABILITY_JOURNAL_QUEUE = ConfigOption(
        "observability.journal.queue",
        int,
        8192,
        "Bounded queue between event emitters and the journal writer thread "
        "(records). On overflow new events are dropped and counted "
        "(FlightRecorder.dropped / ml.telemetry.journal.dropped) — the hot "
        "path never blocks on telemetry.",
    )
    OBSERVABILITY_JOURNAL_MAX_BYTES = ConfigOption(
        "observability.journal.max.bytes",
        int,
        64 << 20,
        "Rotation bound of one journal file: past this many bytes the writer "
        "rotates to a new part file (oldest parts beyond "
        "observability.journal.keep.files are deleted).",
    )
    OBSERVABILITY_JOURNAL_KEEP_FILES = ConfigOption(
        "observability.journal.keep.files",
        int,
        4,
        "Journal part files kept after rotation (bounded retention; the "
        "sequence numbers stay monotone across parts and incarnations).",
    )
    OBSERVABILITY_HTTP_PORT = ConfigOption(
        "observability.http.port",
        int,
        None,
        "Port of the live telemetry endpoint (/metrics, /healthz, "
        "/events?n=) an InferenceServer starts alongside itself. Default: "
        "none — no HTTP thread. 0 = bind an ephemeral port (tests read "
        "server.telemetry.port).",
    )
    OBSERVABILITY_INCIDENT_WINDOW_S = ConfigOption(
        "observability.incident.window.s",
        float,
        30.0,
        "How many trailing seconds of the journal an incident bundle "
        "snapshots (from the writer's in-memory tail ring).",
    )
    OBSERVABILITY_INCIDENT_KEEP = ConfigOption(
        "observability.incident.keep",
        int,
        8,
        "Incident bundles retained per journal directory — oldest bundles "
        "beyond this are deleted (bounded retention).",
    )
    OBSERVABILITY_INCIDENT_MIN_INTERVAL_S = ConfigOption(
        "observability.incident.min.interval.s",
        float,
        30.0,
        "Per-kind incident rate limit: a second incident of the same kind "
        "within this window is counted (ml.telemetry.incidents.suppressed) "
        "but writes no bundle — a shedding storm yields one bundle, not "
        "thousands.",
    )
    OBSERVABILITY_TRACE_XPROF = ConfigOption(
        "observability.trace.xprof",
        _parse_bool,
        False,
        "Mirror every traced span into jax.profiler.TraceAnnotation so spans "
        "nest inside XLA profiler dumps captured around the traced region "
        "(e.g. benchmark --profile). Only meaningful while a profile is "
        "active; adds per-span overhead, so it is a separate switch.",
    )
    FLEET_REPLICAS = ConfigOption(
        "fleet.replicas",
        int,
        3,
        "Replica count of a ReplicaPool (flink_ml_tpu/fleet) — the serving "
        "parallelism of one fleet (docs/fleet.md).",
    )
    FLEET_ROUTER_POLICY = ConfigOption(
        "fleet.router.policy",
        str,
        "least_loaded",
        "FleetRouter dispatch policy: 'least_loaded' (fewest in-flight "
        "requests), 'hash' (rendezvous-hash on the request key — session "
        "affinity, minimal movement on replica loss), or 'priority' "
        "(guaranteed traffic least-loaded, sheddable traffic concentrated "
        "on the busiest replica so sheds hit it first).",
    )
    FLEET_RETRY_ATTEMPTS = ConfigOption(
        "fleet.retry.attempts",
        int,
        3,
        "Total dispatch attempts per request the FleetRouter may spend "
        "across replicas before surfacing the last typed error.",
    )
    FLEET_RETRY_BACKOFF_MS = ConfigOption(
        "fleet.retry.backoff.ms",
        float,
        10.0,
        "Base retry backoff when an overloaded replica supplies no "
        "retry_after_ms drain estimate.",
    )
    FLEET_RETRY_BACKOFF_MAX_MS = ConfigOption(
        "fleet.retry.backoff.max.ms",
        float,
        1000.0,
        "Ceiling on one router retry backoff — retry_after_ms is honored "
        "but never past this bound.",
    )
    FLEET_RETRY_JITTER = ConfigOption(
        "fleet.retry.jitter",
        float,
        0.5,
        "Jitter fraction on router retry backoff (delay *= 1 + jitter*U) so "
        "a fleet-wide shed does not re-synchronize the retries it shed.",
    )
    FLEET_HEDGE_QUANTILE = ConfigOption(
        "fleet.hedge.quantile",
        float,
        0.99,
        "Latency quantile of the router's observed distribution after which "
        "a still-pending request is hedged to a second replica (first "
        "response wins — the p999 tail-cutting protocol). None disables "
        "hedging.",
    )
    FLEET_HEDGE_MIN_MS = ConfigOption(
        "fleet.hedge.min.ms",
        float,
        25.0,
        "Floor on the hedge trigger delay — with a cold or very fast "
        "latency window, never hedge earlier than this.",
    )
    FLEET_HEALTH_INTERVAL_MS = ConfigOption(
        "fleet.health.interval.ms",
        float,
        250.0,
        "ReplicaSupervisor /healthz polling cadence per replica.",
    )
    FLEET_HEALTH_FAILURES = ConfigOption(
        "fleet.health.failures",
        int,
        3,
        "Consecutive failed /healthz probes before the supervisor ejects a "
        "replica from rotation and starts its respawn.",
    )
    FLEET_QUORUM = ConfigOption(
        "fleet.quorum",
        int,
        None,
        "Minimum in-rotation replicas a rolling promotion must preserve. "
        "Default: a strict majority of the pool (n // 2 + 1).",
    )
    FLEET_RESPAWN_TIMEOUT_MS = ConfigOption(
        "fleet.respawn.timeout.ms",
        float,
        120_000.0,
        "How long one respawn attempt may take to produce a healthy, warmed "
        "replica before the attempt is counted failed and the restart "
        "strategy decides on another.",
    )
    FLEET_CANARY_SLICE = ConfigOption(
        "fleet.canary.slice",
        float,
        0.25,
        "Upper bound on the fraction of fleet dispatches a canary version "
        "may serve while under evaluation — enforced as a hard counter gate "
        "at the router, so the slice is an invariant, not a target.",
    )
    FLEET_CANARY_MIN_SCORES = ConfigOption(
        "fleet.canary.min.scores",
        int,
        3,
        "Evaluation scores each side (canary and baseline) must accumulate "
        "before the CanaryController renders a promote/quarantine verdict.",
    )
    NATIVE_DATACACHE_ENABLED = ConfigOption(
        "native.datacache.enabled",
        _parse_bool,
        True,
        "Whether HostDataCache construction through the config tier may use "
        "the C++ chunk store when the native toolchain is available.",
    )

    @classmethod
    def all(cls) -> Dict[str, ConfigOption]:
        return {
            v.key: v
            for v in vars(cls).values()
            if isinstance(v, ConfigOption)
        }


class Configuration:
    """Resolved option values: set() > environment > default."""

    def __init__(self):
        self._values: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def set(self, option: ConfigOption, value) -> "Configuration":
        if value is None:  # setting None means "no override" — same as unset
            return self.unset(option)
        with self._lock:
            self._values[option.key] = option.type(value)
        return self

    def unset(self, option: ConfigOption) -> "Configuration":
        with self._lock:
            self._values.pop(option.key, None)
        return self

    def get(self, option: ConfigOption):
        with self._lock:
            if option.key in self._values:
                return self._values[option.key]
        env = os.environ.get(option.env_var)
        if env is not None:
            return option.type(env)
        return option.default

    def to_dict(self) -> Dict[str, Any]:
        """Every known option's resolved value (for logging/debugging)."""
        return {key: self.get(opt) for key, opt in Options.all().items()}


config = Configuration()


def resolve_cache_config(memory_budget_bytes, spill_dir):
    """Resolve capacity-cache construction args against the config tier —
    lives here (not in iteration/) so the dependency-light native tier can
    use it without importing the jax/mesh stack."""
    if memory_budget_bytes is None:
        memory_budget_bytes = config.get(Options.DATACACHE_MEMORY_BUDGET_BYTES)
    if spill_dir is None:
        spill_dir = config.get(Options.DATACACHE_SPILL_DIR)
    return memory_budget_bytes, spill_dir
