"""CountVectorizer.

Reference: ``flink-ml-lib/.../feature/countvectorizer/`` — learn a vocabulary
from token lists (document frequency filtered by ``minDF``/``maxDF``, absolute
when ≥ 1 else fraction of documents; kept terms ordered by frequency descending,
capped at ``vocabularySize``) and transform documents into term-count sparse
vectors (``minTF`` per-document filter, absolute or fraction of the document's
token count; ``binary`` maps all counts to 1).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.ops.kernels import (
    sparse_combine_fn,
    sparse_combine_kernel,
    sparse_threshold_fn,
    sparse_threshold_kernel,
)
from flink_ml_tpu.params.param import BoolParam, FloatParam, IntParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec
from flink_ml_tpu.servable.sparse import (
    entries_names,
    pack_entry_rows,
    rebuild_sparse_column,
    sparse_names,
)
from flink_ml_tpu.utils import read_write as rw

__all__ = ["CountVectorizer", "CountVectorizerModel"]


class _CvParams(HasInputCol, HasOutputCol):
    VOCABULARY_SIZE = IntParam(
        "vocabularySize", "Max size of the vocabulary.", 1 << 18, ParamValidators.gt(0)
    )
    MIN_DF = FloatParam(
        "minDF",
        "Minimum number (>=1) or fraction (<1) of documents a term must appear in.",
        1.0,
        ParamValidators.gt_eq(0),
    )
    MAX_DF = FloatParam(
        "maxDF",
        "Maximum number (>=1) or fraction (<1) of documents a term may appear in.",
        float(2**63 - 1),
        ParamValidators.gt_eq(0),
    )
    MIN_TF = FloatParam(
        "minTF",
        "Minimum count (>=1) or fraction of the document's token count (<1) to include a term.",
        1.0,
        ParamValidators.gt_eq(0),
    )
    BINARY = BoolParam("binary", "Binary toggle for the output counts.", False)

    def get_vocabulary_size(self) -> int:
        return self.get(self.VOCABULARY_SIZE)

    def set_vocabulary_size(self, value: int):
        return self.set(self.VOCABULARY_SIZE, value)

    def get_min_df(self) -> float:
        return self.get(self.MIN_DF)

    def set_min_df(self, value: float):
        return self.set(self.MIN_DF, value)

    def get_max_df(self) -> float:
        return self.get(self.MAX_DF)

    def set_max_df(self, value: float):
        return self.set(self.MAX_DF, value)

    def get_min_tf(self) -> float:
        return self.get(self.MIN_TF)

    def set_min_tf(self, value: float):
        return self.set(self.MIN_TF, value)

    def get_binary(self) -> bool:
        return self.get(self.BINARY)

    def set_binary(self, value: bool):
        return self.set(self.BINARY, value)


class CountVectorizerModel(Model, _CvParams):
    """Ref CountVectorizerModel.java — vocabulary-indexed term counts."""

    def __init__(self):
        super().__init__()
        self.vocabulary: Optional[List[str]] = None

    def _featurize(self, col):
        """Host half of the featurize: vocabulary lookup per token (strings
        cannot run on device), out-of-vocabulary tokens dropped, duplicates
        preserved for the device ``sparse_combine`` segment reduce. Shared by
        ``transform`` and the fused spec's host ingest."""
        vocab = {term: i for i, term in enumerate(self.vocabulary)}
        rows = []
        lengths = []
        for tokens in col:
            rows.append([(vocab[t], 1.0) for t in tokens if t in vocab])
            lengths.append(len(tokens))
        return rows, lengths

    def _min_tf_threshold(self, lengths: np.ndarray) -> np.ndarray:
        """Per-row minTF bar: absolute when ≥ 1, else a fraction of the
        document's raw token count (ref CountVectorizerModel.java)."""
        min_tf = float(self.get_min_tf())
        lengths = np.asarray(lengths, np.float32)
        if min_tf >= 1.0:
            return np.full(lengths.shape, min_tf, np.float32)
        return (min_tf * lengths).astype(np.float32)

    def transform(self, *inputs):
        (df,) = inputs
        in_col, out_col = self.get_input_col(), self.get_output_col()
        rows, lengths = self._featurize(df.column(in_col))
        arrays, _cap, _total = pack_entry_rows(out_col, rows, lengths)
        vn, idn, zn, _ln = entries_names(out_col)
        # Device segment reduce + minTF filter — the SAME bodies the fused
        # sparse spec composes (counts and thresholds are exact in f32 up to
        # the documented fractional-minTF rounding, shared by both paths).
        values, ids, nnz = sparse_combine_kernel()(arrays[vn], arrays[idn], arrays[zn])
        values, ids, nnz = sparse_threshold_kernel()(
            values, ids, nnz, self._min_tf_threshold(np.asarray(lengths))
        )
        values = np.asarray(values)
        if self.get_binary():
            values = np.minimum(values, 1.0)
        vectors = rebuild_sparse_column(
            len(self.vocabulary), values, np.asarray(ids), np.asarray(nnz)
        )
        out = df.clone()
        out.add_column(out_col, DataTypes.vector(BasicType.DOUBLE), vectors)
        return out

    def sparse_kernel_spec(self, known):
        """Sparse-convention spec (docs/sparse.md): host vocabulary lookup
        at ingest, device ``sparse_combine`` + ``sparse_threshold`` segment
        reduce — the bodies ``transform`` jits — with the fractional-minTF
        bar computed from the raw document length the entries quadruple
        carries."""
        if self.vocabulary is None:
            raise RuntimeError("set_model_data must be called before kernel_spec")
        binary = self.get_binary()
        min_tf = float(self.get_min_tf())
        dim = len(self.vocabulary)
        in_col, out_col = self.get_input_col(), self.get_output_col()
        vn, idn, zn, ln = entries_names(in_col)
        out_v, out_i, out_z = sparse_names(out_col)

        def host_ingest(df, cap, cap_max, truncate):
            rows, lengths = self._featurize(df.column(in_col))
            return pack_entry_rows(
                in_col, rows, lengths, cap=cap, cap_max=cap_max, truncate=truncate
            )

        def kernel_fn(model, cols):
            import jax.numpy as jnp

            values, ids, nnz = sparse_combine_fn(cols[vn], cols[idn], cols[zn])
            if min_tf >= 1.0:
                thr = jnp.full(nnz.shape, min_tf, jnp.float32)
            else:
                thr = (min_tf * cols[ln]).astype(jnp.float32)
            values, ids, nnz = sparse_threshold_fn(values, ids, nnz, thr)
            if binary:
                values = jnp.minimum(values, 1.0)
            return {out_v: values, out_i: ids, out_z: nnz}

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
            input_kinds={in_col: "entries"},
            host_ingests={in_col: host_ingest},
            sparse_outputs={out_col: int(dim)},
        )

    # model data = the ordered vocabulary
    def get_model_data(self):
        return [DataFrame(["vocabulary"], None, [[list(self.vocabulary)]])]

    def set_model_data(self, *model_data: DataFrame):
        self.vocabulary = list(model_data[0].column("vocabulary")[0])
        return self

    def save(self, path: str) -> None:
        rw.save_metadata(self, path)
        rw.save_model_arrays(path, {"vocabulary": np.asarray(self.vocabulary, dtype=str)})

    @classmethod
    def load(cls, path: str):
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        model.vocabulary = [str(s) for s in rw.load_model_arrays(path)["vocabulary"]]
        return model

    @classmethod
    def load_servable(cls, path: str) -> "CountVectorizerModel":
        """The fitted model is its own runtime-free replica (state = the
        vocabulary) — published text pipelines load it directly on the
        serving tier (docs/sparse.md)."""
        return cls.load(path)


class CountVectorizer(Estimator, _CvParams):
    """Ref CountVectorizer.java."""

    def fit(self, *inputs) -> CountVectorizerModel:
        (df,) = inputs
        col = df.column(self.get_input_col())
        num_docs = len(col)
        doc_freq = {}
        term_count = {}
        for tokens in col:
            for t in set(tokens):
                doc_freq[t] = doc_freq.get(t, 0) + 1
            for t in tokens:
                term_count[t] = term_count.get(t, 0) + 1
        min_df = self.get_min_df()
        max_df = self.get_max_df()
        lo = min_df if min_df >= 1.0 else min_df * num_docs
        hi = max_df if max_df >= 1.0 else max_df * num_docs
        if lo > hi:
            raise ValueError("maxDF must be >= minDF")
        kept = [t for t, dfreq in doc_freq.items() if lo <= dfreq <= hi]
        kept.sort(key=lambda t: (-term_count[t], t))
        vocab = kept[: self.get_vocabulary_size()]
        if not vocab:
            raise RuntimeError("The vocabulary is empty; check minDF/maxDF settings.")
        model = CountVectorizerModel()
        update_existing_params(model, self)
        model.vocabulary = vocab
        return model
