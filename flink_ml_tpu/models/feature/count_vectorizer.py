"""CountVectorizer.

Reference: ``flink-ml-lib/.../feature/countvectorizer/`` — learn a vocabulary
from token lists (document frequency filtered by ``minDF``/``maxDF``, absolute
when ≥ 1 else fraction of documents; kept terms ordered by frequency descending,
capped at ``vocabularySize``) and transform documents into term-count sparse
vectors (``minTF`` per-document filter, absolute or fraction of the document's
token count; ``binary`` maps all counts to 1).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.params.param import BoolParam, FloatParam, IntParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.utils import read_write as rw

__all__ = ["CountVectorizer", "CountVectorizerModel"]


class _CvParams(HasInputCol, HasOutputCol):
    VOCABULARY_SIZE = IntParam(
        "vocabularySize", "Max size of the vocabulary.", 1 << 18, ParamValidators.gt(0)
    )
    MIN_DF = FloatParam(
        "minDF",
        "Minimum number (>=1) or fraction (<1) of documents a term must appear in.",
        1.0,
        ParamValidators.gt_eq(0),
    )
    MAX_DF = FloatParam(
        "maxDF",
        "Maximum number (>=1) or fraction (<1) of documents a term may appear in.",
        float(2**63 - 1),
        ParamValidators.gt_eq(0),
    )
    MIN_TF = FloatParam(
        "minTF",
        "Minimum count (>=1) or fraction of the document's token count (<1) to include a term.",
        1.0,
        ParamValidators.gt_eq(0),
    )
    BINARY = BoolParam("binary", "Binary toggle for the output counts.", False)

    def get_vocabulary_size(self) -> int:
        return self.get(self.VOCABULARY_SIZE)

    def set_vocabulary_size(self, value: int):
        return self.set(self.VOCABULARY_SIZE, value)

    def get_min_df(self) -> float:
        return self.get(self.MIN_DF)

    def set_min_df(self, value: float):
        return self.set(self.MIN_DF, value)

    def get_max_df(self) -> float:
        return self.get(self.MAX_DF)

    def set_max_df(self, value: float):
        return self.set(self.MAX_DF, value)

    def get_min_tf(self) -> float:
        return self.get(self.MIN_TF)

    def set_min_tf(self, value: float):
        return self.set(self.MIN_TF, value)

    def get_binary(self) -> bool:
        return self.get(self.BINARY)

    def set_binary(self, value: bool):
        return self.set(self.BINARY, value)


class CountVectorizerModel(Model, _CvParams):
    """Ref CountVectorizerModel.java — vocabulary-indexed term counts."""

    def __init__(self):
        super().__init__()
        self.vocabulary: Optional[List[str]] = None

    def transform(self, *inputs):
        (df,) = inputs
        vocab = {term: i for i, term in enumerate(self.vocabulary)}
        min_tf = self.get_min_tf()
        binary = self.get_binary()
        vectors = []
        for tokens in df.column(self.get_input_col()):
            counts = {}
            for t in tokens:
                if t in vocab:
                    counts[vocab[t]] = counts.get(vocab[t], 0) + 1
            threshold = min_tf if min_tf >= 1.0 else min_tf * len(tokens)
            items = [(i, c) for i, c in sorted(counts.items()) if c >= threshold]
            indices = np.asarray([i for i, _ in items], np.int64)
            values = np.asarray([1.0 if binary else float(c) for _, c in items])
            vectors.append(SparseVector(len(vocab), indices, values))
        out = df.clone()
        out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), vectors)
        return out

    # model data = the ordered vocabulary
    def get_model_data(self):
        return [DataFrame(["vocabulary"], None, [[list(self.vocabulary)]])]

    def set_model_data(self, *model_data: DataFrame):
        self.vocabulary = list(model_data[0].column("vocabulary")[0])
        return self

    def save(self, path: str) -> None:
        rw.save_metadata(self, path)
        rw.save_model_arrays(path, {"vocabulary": np.asarray(self.vocabulary, dtype=str)})

    @classmethod
    def load(cls, path: str):
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        model.vocabulary = [str(s) for s in rw.load_model_arrays(path)["vocabulary"]]
        return model


class CountVectorizer(Estimator, _CvParams):
    """Ref CountVectorizer.java."""

    def fit(self, *inputs) -> CountVectorizerModel:
        (df,) = inputs
        col = df.column(self.get_input_col())
        num_docs = len(col)
        doc_freq = {}
        term_count = {}
        for tokens in col:
            for t in set(tokens):
                doc_freq[t] = doc_freq.get(t, 0) + 1
            for t in tokens:
                term_count[t] = term_count.get(t, 0) + 1
        min_df = self.get_min_df()
        max_df = self.get_max_df()
        lo = min_df if min_df >= 1.0 else min_df * num_docs
        hi = max_df if max_df >= 1.0 else max_df * num_docs
        if lo > hi:
            raise ValueError("maxDF must be >= minDF")
        kept = [t for t, dfreq in doc_freq.items() if lo <= dfreq <= hi]
        kept.sort(key=lambda t: (-term_count[t], t))
        vocab = kept[: self.get_vocabulary_size()]
        if not vocab:
            raise RuntimeError("The vocabulary is empty; check minDF/maxDF settings.")
        model = CountVectorizerModel()
        update_existing_params(model, self)
        model.vocabulary = vocab
        return model
