"""Interaction.

Reference: ``flink-ml-lib/.../feature/interaction/Interaction.java`` — output
vector of all cross-products across the input columns (numeric columns act as
1-dim vectors): out[i,j,...] = col1[i]·col2[j]·…  The first column's index varies
slowest (row-major over columns left to right). The batched outer product is
the shared ``interaction`` kernel (``ops/kernels.py``).
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.ops.kernels import interaction_fn, interaction_kernel
from flink_ml_tpu.params.shared import HasInputCols, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec

__all__ = ["Interaction"]


class Interaction(Transformer, HasInputCols, HasOutputCol):
    """Ref Interaction.java."""

    def transform(self, *inputs):
        (df,) = inputs
        mats = []
        for name in self.get_input_cols():
            col = df.column(name)
            if isinstance(col, np.ndarray) and col.ndim == 2:
                mats.append(col.astype(np.float64))
            else:
                mats.append(df.vectors(name).astype(np.float64))
        vals = interaction_kernel()(*mats)
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(vals, np.float64),
        )
        return out

    def kernel_spec(self):
        """Cross-products as a fusable spec — ``interaction_fn``, the body
        ``transform``'s jitted kernel wraps. Inputs ingest as vectors
        (scalars widen to [n, 1], exactly like ``transform``)."""
        in_cols, out_col = tuple(self.get_input_cols() or ()), self.get_output_col()
        if not in_cols:
            return None

        def kernel_fn(model, cols):
            return {out_col: interaction_fn(*(cols[n] for n in in_cols))}

        return KernelSpec(
            input_cols=in_cols,
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
            elementwise=True,  # broadcast products only: no FP accumulation
        )
