"""Interaction.

Reference: ``flink-ml-lib/.../feature/interaction/Interaction.java`` — output
vector of all cross-products across the input columns (numeric columns act as
1-dim vectors): out[i,j,...] = col1[i]·col2[j]·…  The first column's index varies
slowest (row-major over columns left to right). The batched outer product is
the shared ``interaction`` kernel (``ops/kernels.py``).
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.ops.kernels import (
    interaction_fn,
    interaction_kernel,
    sparse_interaction_fn,
    sparse_interaction_kernel,
)
from flink_ml_tpu.params.shared import HasInputCols, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec
from flink_ml_tpu.servable.sparse import rebuild_sparse_column, sparse_names

__all__ = ["Interaction"]

#: Sparse cross-product ids live in int32 on device — the product of the
#: input dims must stay addressable.
_MAX_SPARSE_DIM = 1 << 31


class Interaction(Transformer, HasInputCols, HasOutputCol):
    """Ref Interaction.java."""

    def transform(self, *inputs):
        (df,) = inputs
        in_cols = list(self.get_input_cols())
        if len(in_cols) >= 2 and all(df.is_sparse(name) for name in in_cols):
            out = self._transform_sparse(df, in_cols)
            if out is not None:
                return out
        mats = []
        for name in in_cols:
            col = df.column(name)
            if isinstance(col, np.ndarray) and col.ndim == 2:
                mats.append(col.astype(np.float64))
            else:
                mats.append(df.vectors(name).astype(np.float64))
        vals = interaction_kernel()(*mats)
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(vals, np.float64),
        )
        return out

    def _transform_sparse(self, df, in_cols):
        """All-sparse inputs (the one-hot CTR shape) stay sparse: pairwise
        device cross products through the SAME ``sparse_interaction`` body
        the fused sparse spec composes — nnz multiplies instead of the dim
        product the densified path would materialize (docs/sparse.md).
        Returns None when the cross dim overflows int32 addressing (the
        densified path would be equally infeasible, but fail the same way
        as before)."""
        batches = [df.sparse_batch(name) for name in in_cols]
        total_dim = 1
        for b in batches:
            total_dim *= b.dim
        if total_dim >= _MAX_SPARSE_DIM:
            return None
        acc = batches[0]
        av, ai, az = acc.values, acc.indices, acc.nnz
        dim = acc.dim
        for b in batches[1:]:
            av, ai, az = sparse_interaction_kernel(b.dim)(
                av, ai, az, b.values, b.indices, b.nnz
            )
            dim *= b.dim
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            rebuild_sparse_column(dim, np.asarray(av), np.asarray(ai), np.asarray(az)),
        )
        return out

    def sparse_kernel_spec(self, known):
        """Sparse-convention spec (docs/sparse.md): when every input column
        is statically known sparse, the cross product folds pairwise through
        ``sparse_interaction_fn`` (the body the per-stage sparse path jits),
        output sparse at the product dim — the interior of the
        one-hot→interaction→head CTR chain. Compaction sorts, so the spec is
        a reduction spec, never elementwise."""
        in_cols = tuple(self.get_input_cols() or ())
        out_col = self.get_output_col()
        if len(in_cols) < 2 or any(name not in known for name in in_cols):
            return None
        dims = [int(known[name]) for name in in_cols]
        total_dim = 1
        for d in dims:
            total_dim *= d
        if total_dim >= _MAX_SPARSE_DIM:
            return None
        out_v, out_i, out_z = sparse_names(out_col)

        def kernel_fn(model, cols):
            v0, i0, z0 = sparse_names(in_cols[0])
            av, ai, az = cols[v0], cols[i0], cols[z0]
            for name, d in zip(in_cols[1:], dims[1:]):
                vn, idn, zn = sparse_names(name)
                av, ai, az = sparse_interaction_fn(
                    av, ai, az, cols[vn], cols[idn], cols[zn], d
                )
            return {out_v: av, out_i: ai, out_z: az}

        return KernelSpec(
            input_cols=in_cols,
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
            input_kinds={name: "sparse" for name in in_cols},
            sparse_input_dims={name: d for name, d in zip(in_cols, dims)},
            sparse_outputs={out_col: total_dim},
        )

    def kernel_spec(self):
        """Cross-products as a fusable spec — ``interaction_fn``, the body
        ``transform``'s jitted kernel wraps. Inputs ingest as vectors
        (scalars widen to [n, 1], exactly like ``transform``)."""
        in_cols, out_col = tuple(self.get_input_cols() or ()), self.get_output_col()
        if not in_cols:
            return None

        def kernel_fn(model, cols):
            return {out_col: interaction_fn(*(cols[n] for n in in_cols))}

        return KernelSpec(
            input_cols=in_cols,
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
            elementwise=True,  # broadcast products only: no FP accumulation
        )
