"""Interaction.

Reference: ``flink-ml-lib/.../feature/interaction/Interaction.java`` — output
vector of all cross-products across the input columns (numeric columns act as
1-dim vectors): out[i,j,...] = col1[i]·col2[j]·…  The first column's index varies
slowest (row-major over columns left to right).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.params.shared import HasInputCols, HasOutputCol

__all__ = ["Interaction"]


@functools.cache
def _kernel(dims: tuple):
    @jax.jit
    def interact(*cols):
        # batched outer product across columns: [n, d1] x [n, d2] ... -> [n, d1*d2*...]
        acc = cols[0]
        for c in cols[1:]:
            acc = acc[:, :, None] * c[:, None, :]
            acc = acc.reshape(acc.shape[0], -1)
        return acc

    return interact


class Interaction(Transformer, HasInputCols, HasOutputCol):
    """Ref Interaction.java."""

    def transform(self, *inputs):
        (df,) = inputs
        mats = []
        for name in self.get_input_cols():
            col = df.column(name)
            if isinstance(col, np.ndarray) and col.ndim == 2:
                mats.append(col.astype(np.float64))
            else:
                mats.append(df.vectors(name).astype(np.float64))
        vals = _kernel(tuple(m.shape[1] for m in mats))(*mats)
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(vals, np.float64),
        )
        return out
