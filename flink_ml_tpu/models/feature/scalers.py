"""MaxAbsScaler, MinMaxScaler, RobustScaler.

Reference: ``flink-ml-lib/.../feature/maxabsscaler/`` (model = per-dim max |x|;
transform x / maxAbs, dims with maxAbs 0 untouched), ``minmaxscaler/`` (model =
per-dim min/max; transform x·scale + offset with scale = (max'−min')/(eMax−eMin),
constant dims (|eMin−eMax| < 1e-5) map to the range midpoint —
MinMaxScalerModel.java:97-108), ``robustscaler/`` (model = per-dim quantiles at
``lower``/``upper`` (default quartiles) + median; transform optionally centers by
median and scales by 1/IQR, zero-range dims map to 0).

Fit statistics (min/max/|max|/quantiles) are single-pass host reductions — these
are ingestion-time O(n·d) scans dominated by data movement, not FLOPs, so there
is nothing for the MXU to win; transforms are affine maps applied columnar.
Quantiles are exact (the reference approximates with Greenwald-Khanna sketches,
QuantileSummary.java:42, because it must merge streamed partitions).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.params.param import BoolParam, FloatParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol, HasRelativeError

__all__ = [
    "MaxAbsScaler",
    "MaxAbsScalerModel",
    "MinMaxScaler",
    "MinMaxScalerModel",
    "RobustScaler",
    "RobustScalerModel",
]


def _apply_affine(df, input_col, output_col, scale, offset):
    X = df.vectors(input_col).astype(np.float64)
    vals = X * scale[None, :] + offset[None, :]
    out = df.clone()
    out.add_column(output_col, DataTypes.vector(BasicType.DOUBLE), vals)
    return out


# --- MaxAbsScaler ------------------------------------------------------------


class MaxAbsScalerModel(ModelArraysMixin, Model, HasInputCol, HasOutputCol):
    """Ref MaxAbsScalerModel.java."""

    _MODEL_ARRAY_NAMES = ("max_abs",)

    def __init__(self):
        super().__init__()
        self.max_abs: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        scale = np.where(self.max_abs == 0.0, 1.0, 1.0 / np.where(self.max_abs == 0, 1, self.max_abs))
        return _apply_affine(
            df, self.get_input_col(), self.get_output_col(), scale, np.zeros_like(scale)
        )


class MaxAbsScaler(Estimator, HasInputCol, HasOutputCol):
    """Ref MaxAbsScaler.java."""

    def fit(self, *inputs) -> MaxAbsScalerModel:
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        model = MaxAbsScalerModel()
        update_existing_params(model, self)
        model.max_abs = np.abs(X).max(axis=0) if len(X) else np.zeros(X.shape[1])
        return model


# --- MinMaxScaler ------------------------------------------------------------


class _MinMaxParams(HasInputCol, HasOutputCol):
    MIN = FloatParam("min", "Lower bound of the output feature range.", 0.0)
    MAX = FloatParam("max", "Upper bound of the output feature range.", 1.0)

    def get_min(self) -> float:
        return self.get(self.MIN)

    def set_min(self, value: float):
        return self.set(self.MIN, value)

    def get_max(self) -> float:
        return self.get(self.MAX)

    def set_max(self, value: float):
        return self.set(self.MAX, value)


class MinMaxScalerModel(ModelArraysMixin, Model, _MinMaxParams):
    """Ref MinMaxScalerModel.java:97-108."""

    _MODEL_ARRAY_NAMES = ("e_min", "e_max")

    def __init__(self):
        super().__init__()
        self.e_min: Optional[np.ndarray] = None
        self.e_max: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        lo, hi = self.get_min(), self.get_max()
        if hi <= lo:
            raise ValueError(f"MinMaxScaler requires min < max, got [{lo}, {hi}]")
        span = self.e_max - self.e_min
        constant = np.abs(span) < 1e-5
        scale = np.where(constant, 0.0, (hi - lo) / np.where(constant, 1.0, span))
        offset = np.where(constant, (hi + lo) / 2.0, lo - self.e_min * scale)
        return _apply_affine(df, self.get_input_col(), self.get_output_col(), scale, offset)


class MinMaxScaler(Estimator, _MinMaxParams):
    """Ref MinMaxScaler.java."""

    def fit(self, *inputs) -> MinMaxScalerModel:
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        if len(X) == 0:
            raise RuntimeError("The training set is empty.")
        model = MinMaxScalerModel()
        update_existing_params(model, self)
        model.e_min = X.min(axis=0)
        model.e_max = X.max(axis=0)
        return model


# --- RobustScaler ------------------------------------------------------------


class _RobustParams(HasInputCol, HasOutputCol, HasRelativeError):
    LOWER = FloatParam(
        "lower", "Lower quantile to calculate quantile range.", 0.25, ParamValidators.in_range(0, 1, False, False)
    )
    UPPER = FloatParam(
        "upper", "Upper quantile to calculate quantile range.", 0.75, ParamValidators.in_range(0, 1, False, False)
    )
    WITH_CENTERING = BoolParam(
        "withCentering", "Whether to center the data with median before scaling.", False
    )
    WITH_SCALING = BoolParam("withScaling", "Whether to scale the data to quantile range.", True)

    def get_lower(self) -> float:
        return self.get(self.LOWER)

    def set_lower(self, value: float):
        return self.set(self.LOWER, value)

    def get_upper(self) -> float:
        return self.get(self.UPPER)

    def set_upper(self, value: float):
        return self.set(self.UPPER, value)

    def get_with_centering(self) -> bool:
        return self.get(self.WITH_CENTERING)

    def set_with_centering(self, value: bool):
        return self.set(self.WITH_CENTERING, value)

    def get_with_scaling(self) -> bool:
        return self.get(self.WITH_SCALING)

    def set_with_scaling(self, value: bool):
        return self.set(self.WITH_SCALING, value)


class RobustScalerModel(ModelArraysMixin, Model, _RobustParams):
    """Ref RobustScalerModel.java."""

    _MODEL_ARRAY_NAMES = ("medians", "ranges")

    def __init__(self):
        super().__init__()
        self.medians: Optional[np.ndarray] = None
        self.ranges: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        scale = (
            np.where(self.ranges == 0.0, 0.0, 1.0 / np.where(self.ranges == 0, 1, self.ranges))
            if self.get_with_scaling()
            else np.ones_like(self.ranges)
        )
        offset = -self.medians * scale if self.get_with_centering() else np.zeros_like(scale)
        return _apply_affine(df, self.get_input_col(), self.get_output_col(), scale, offset)


class RobustScaler(Estimator, _RobustParams):
    """Ref RobustScaler.java — per-dim quantiles via the distributed
    Greenwald-Khanna sketch (QuantileSummary.java:42): every partition sketches
    independently, the sketches merge (parallel/quantile.py), so the fit scales
    to streams that never fit one host. On inputs below the sketch's compress
    threshold the result is exact."""

    def fit(self, *inputs) -> RobustScalerModel:
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        if len(X) == 0:
            raise RuntimeError("The training set is empty.")
        from flink_ml_tpu.parallel.datastream_utils import distributed_quantiles

        lo, hi = self.get_lower(), self.get_upper()
        q = distributed_quantiles(X, [lo, 0.5, hi])
        model = RobustScalerModel()
        update_existing_params(model, self)
        model.medians = q[1]
        model.ranges = q[2] - q[0]
        return model
