"""Binarizer.

Reference: ``flink-ml-lib/.../feature/binarizer/Binarizer.java`` — multi-column
transformer; per input column i, values > thresholds[i] → 1.0 else 0.0; works on
numeric columns and on vectors (element-wise, sparse kept sparse).
"""
from __future__ import annotations

import functools

import jax
import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector, Vector
from flink_ml_tpu.params.param import FloatArrayParam, ParamValidators
from flink_ml_tpu.params.shared import HasInputCols, HasOutputCols

__all__ = ["Binarizer"]


@functools.cache
def _kernel(threshold: float):
    return jax.jit(lambda x: (x > threshold).astype(x.dtype))


class Binarizer(Transformer, HasInputCols, HasOutputCols):
    """Ref Binarizer.java."""

    THRESHOLDS = FloatArrayParam(
        "thresholds",
        "The thresholds used to binarize continuous features; one per input column.",
        None,
        ParamValidators.non_empty_array(),
    )

    def get_thresholds(self):
        return self.get(self.THRESHOLDS)

    def set_thresholds(self, *values: float):
        return self.set(self.THRESHOLDS, list(values))

    def transform(self, *inputs):
        (df,) = inputs
        in_cols, out_cols = self.get_input_cols(), self.get_output_cols()
        thresholds = self.get_thresholds()
        if len(in_cols) != len(thresholds):
            raise ValueError(
                "Binarizer: number of thresholds must match number of input columns"
            )
        out = df.clone()
        for name, out_name, thr in zip(in_cols, out_cols, thresholds):
            col = df.column(name)
            if isinstance(col, np.ndarray):
                vals = np.asarray(_kernel(float(thr))(col.astype(np.float64)))
                dtype = (
                    DataTypes.vector(BasicType.DOUBLE) if vals.ndim == 2 else DataTypes.DOUBLE
                )
                out.add_column(out_name, dtype, vals)
            else:  # ragged (sparse vectors): binarize stored values, keep sparsity
                new_col = []
                for v in col:
                    if isinstance(v, SparseVector):
                        kept = v.values > thr
                        new_col.append(
                            SparseVector(v.size(), v.indices[kept], np.ones(kept.sum()))
                        )
                    elif isinstance(v, Vector):
                        new_col.append((v.to_array() > thr).astype(np.float64))
                    else:
                        new_col.append(1.0 if v > thr else 0.0)
                out.add_column(out_name, DataTypes.vector(BasicType.DOUBLE), new_col)
        return out
