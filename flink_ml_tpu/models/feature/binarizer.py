"""Binarizer.

Reference: ``flink-ml-lib/.../feature/binarizer/Binarizer.java`` — multi-column
transformer; per input column i, values > thresholds[i] → 1.0 else 0.0; works on
numeric columns and on vectors (element-wise, sparse kept sparse).

Dense columns run through the shared ``binarize`` kernel (``ops/kernels.py``)
in the column's OWN dtype — no float64 upcast before the kernel (it would
double host memory/bandwidth only for jit to truncate back to float32) — and
float columns come back in their input dtype.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector, Vector
from flink_ml_tpu.ops.kernels import binarize_fn, binarize_kernel
from flink_ml_tpu.params.param import FloatArrayParam, ParamValidators
from flink_ml_tpu.params.shared import HasInputCols, HasOutputCols
from flink_ml_tpu.servable.kernel_spec import KernelSpec

__all__ = ["Binarizer"]


class Binarizer(Transformer, HasInputCols, HasOutputCols):
    """Ref Binarizer.java."""

    THRESHOLDS = FloatArrayParam(
        "thresholds",
        "The thresholds used to binarize continuous features; one per input column.",
        None,
        ParamValidators.non_empty_array(),
    )

    def get_thresholds(self):
        return self.get(self.THRESHOLDS)

    def set_thresholds(self, *values: float):
        return self.set(self.THRESHOLDS, list(values))

    def transform(self, *inputs):
        (df,) = inputs
        in_cols, out_cols = self.get_input_cols(), self.get_output_cols()
        thresholds = self.get_thresholds()
        if len(in_cols) != len(thresholds):
            raise ValueError(
                "Binarizer: number of thresholds must match number of input columns"
            )
        out = df.clone()
        for name, out_name, thr in zip(in_cols, out_cols, thresholds):
            col = df.column(name)
            if isinstance(col, np.ndarray):
                # Run in the column's dtype: floats go to the device as-is
                # (jit canonicalizes f64→f32; no host-side upcast copy),
                # integers/bools widen once.
                x = col if col.dtype.kind == "f" else col.astype(np.float64)
                vals = np.asarray(binarize_kernel(float(thr))(x))
                if col.dtype.kind == "f":
                    vals = vals.astype(col.dtype, copy=False)
                dtype = (
                    DataTypes.vector(BasicType.DOUBLE) if vals.ndim == 2 else DataTypes.DOUBLE
                )
                out.add_column(out_name, dtype, vals)
            else:  # ragged (sparse vectors): binarize stored values, keep sparsity
                new_col = []
                for v in col:
                    if isinstance(v, SparseVector):
                        kept = v.values > thr
                        new_col.append(
                            SparseVector(v.size(), v.indices[kept], np.ones(kept.sum()))
                        )
                    elif isinstance(v, Vector):
                        new_col.append((v.to_array() > thr).astype(np.float64))
                    else:
                        new_col.append(1.0 if v > thr else 0.0)
                out.add_column(out_name, DataTypes.vector(BasicType.DOUBLE), new_col)
        return out

    def kernel_spec(self):
        """Fusable per-column thresholding — ``binarize_fn``, the body
        ``transform``'s jitted kernel wraps. List (sparse-vector) columns are
        per-stage territory, so inputs ingest as ``dense`` and anything
        ragged falls the segment back. Output DataTypes follow the input
        shape at readback (scalar vs vector), like ``transform``."""
        in_cols, out_cols = self.get_input_cols(), self.get_output_cols()
        thresholds = self.get_thresholds()
        if not in_cols or thresholds is None or len(in_cols) != len(thresholds):
            return None  # transform raises the param error on the classic path
        bindings = tuple(zip(in_cols, out_cols, [float(t) for t in thresholds]))

        def kernel_fn(model, cols):
            return {o: binarize_fn(cols[n], t) for n, o, t in bindings}

        return KernelSpec(
            input_cols=in_cols,
            outputs=tuple((o, None) for o in out_cols),
            model_arrays={},
            kernel_fn=kernel_fn,
            input_kinds={n: "dense" for n in in_cols},
            elementwise=True,  # threshold compare: no FP accumulation
            fusion_op="binarize",  # megakernel-safe
        )
