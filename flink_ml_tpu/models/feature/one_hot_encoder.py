"""OneHotEncoder.

Reference: ``flink-ml-lib/.../feature/onehotencoder/`` — multi-column encoding of
non-negative integer indices into sparse binary vectors; model data = max index
per column; ``dropLast`` (default true) drops the last category (its index maps
to the all-zeros vector); with handleInvalid 'keep' an extra category is added
(OneHotEncoderModel.java:166-169), 'error' raises on out-of-range values.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.params.param import BoolParam, update_existing_params
from flink_ml_tpu.params.shared import HasHandleInvalid, HasInputCols, HasOutputCols

__all__ = ["OneHotEncoder", "OneHotEncoderModel"]


class _OheParams(HasInputCols, HasOutputCols, HasHandleInvalid):
    DROP_LAST = BoolParam("dropLast", "Whether to drop the last category.", True)

    def get_drop_last(self) -> bool:
        return self.get(self.DROP_LAST)

    def set_drop_last(self, value: bool):
        return self.set(self.DROP_LAST, value)


class OneHotEncoderModel(ModelArraysMixin, Model, _OheParams):
    """Ref OneHotEncoderModel.java."""

    _MODEL_ARRAY_NAMES = ("category_sizes",)

    def __init__(self):
        super().__init__()
        self.category_sizes: Optional[np.ndarray] = None  # num categories per column

    def transform(self, *inputs):
        (df,) = inputs
        drop_last = self.get_drop_last()
        handle = self.get_handle_invalid()
        n = len(df)
        keep_mask = np.ones(n, bool)
        out = df.clone()
        new_cols = []
        for i, name in enumerate(self.get_input_cols()):
            idx = df.scalars(name)
            size = int(self.category_sizes[i]) + (1 if handle == "keep" else 0)
            vec_len = size - 1 if drop_last else size
            invalid = (idx < 0) | (idx != np.floor(idx)) | (idx >= size)
            if handle == "error" and invalid.any():
                raise ValueError(
                    f"The input contains invalid index {idx[invalid][0]} for column {name}."
                )
            if handle == "keep":
                idx = np.where(invalid, size - 1, idx)
            else:
                keep_mask &= ~invalid
            vectors = [
                SparseVector(vec_len, np.asarray([], np.int64), np.asarray([]))
                if int(j) >= vec_len
                else SparseVector(vec_len, np.asarray([int(j)]), np.asarray([1.0]))
                for j in idx
            ]
            new_cols.append(vectors)
        for out_name, vectors in zip(self.get_output_cols(), new_cols):
            out.add_column(out_name, DataTypes.vector(BasicType.DOUBLE), vectors)
        if not keep_mask.all():
            out = out.take(np.nonzero(keep_mask)[0])
        return out


class OneHotEncoder(Estimator, _OheParams):
    """Ref OneHotEncoder.java — model data is maxIndex+1 per column."""

    def fit(self, *inputs) -> OneHotEncoderModel:
        (df,) = inputs
        sizes = []
        for name in self.get_input_cols():
            idx = df.scalars(name)
            if (idx < 0).any() or (idx != np.floor(idx)).any():
                raise ValueError(f"Column {name} must contain non-negative integers.")
            sizes.append(int(idx.max()) + 1)
        model = OneHotEncoderModel()
        update_existing_params(model, self)
        model.category_sizes = np.asarray(sizes, np.int64)
        return model
