"""OneHotEncoder.

Reference: ``flink-ml-lib/.../feature/onehotencoder/`` — multi-column encoding of
non-negative integer indices into sparse binary vectors; model data = max index
per column; ``dropLast`` (default true) drops the last category (its index maps
to the all-zeros vector); with handleInvalid 'keep' an extra category is added
(OneHotEncoderModel.java:166-169), 'error' raises on out-of-range values.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.ops.kernels import onehot_encode_fn, onehot_encode_kernel
from flink_ml_tpu.params.param import BoolParam, update_existing_params
from flink_ml_tpu.params.shared import HasHandleInvalid, HasInputCols, HasOutputCols
from flink_ml_tpu.servable.kernel_spec import KernelSpec
from flink_ml_tpu.servable.sparse import rebuild_sparse_column, sparse_names

__all__ = ["OneHotEncoder", "OneHotEncoderModel"]


class _OheParams(HasInputCols, HasOutputCols, HasHandleInvalid):
    DROP_LAST = BoolParam("dropLast", "Whether to drop the last category.", True)

    def get_drop_last(self) -> bool:
        return self.get(self.DROP_LAST)

    def set_drop_last(self, value: bool):
        return self.set(self.DROP_LAST, value)


class OneHotEncoderModel(ModelArraysMixin, Model, _OheParams):
    """Ref OneHotEncoderModel.java."""

    _MODEL_ARRAY_NAMES = ("category_sizes",)

    def __init__(self):
        super().__init__()
        self.category_sizes: Optional[np.ndarray] = None  # num categories per column

    @classmethod
    def load_servable(cls, path: str) -> "OneHotEncoderModel":
        """The fitted model is its own runtime-free replica (state = the
        per-column category sizes) — published CTR pipelines load it directly
        on the serving tier (docs/sparse.md)."""
        return cls.load(path)

    def _layout(self, i: int):
        """(size, vec_len) of input column ``i`` under the current params —
        the static category layout both paths encode against."""
        handle = self.get_handle_invalid()
        size = int(self.category_sizes[i]) + (1 if handle == "keep" else 0)
        vec_len = size - 1 if self.get_drop_last() else size
        return size, vec_len

    def transform(self, *inputs):
        (df,) = inputs
        handle = self.get_handle_invalid()
        n = len(df)
        keep_mask = np.ones(n, bool)
        out = df.clone()
        new_cols = []
        for i, name in enumerate(self.get_input_cols()):
            idx = df.scalars(name)
            size, vec_len = self._layout(i)
            invalid = (idx < 0) | (idx != np.floor(idx)) | (idx >= size)
            if handle == "error" and invalid.any():
                raise ValueError(
                    f"The input contains invalid index {idx[invalid][0]} for column {name}."
                )
            if handle != "keep":
                keep_mask &= ~invalid
            # Device encode — the SAME ``onehot_encode`` body the fused
            # sparse spec composes ('keep' maps invalid to the extra
            # category; rows masked out under 'skip' drop below, so their
            # encoded value is never observed).
            values, ids, nnz = onehot_encode_kernel(size, vec_len)(
                idx.astype(np.float32)
            )
            new_cols.append(
                rebuild_sparse_column(
                    vec_len, np.asarray(values), np.asarray(ids), np.asarray(nnz)
                )
            )
        for out_name, vectors in zip(self.get_output_cols(), new_cols):
            out.add_column(out_name, DataTypes.vector(BasicType.DOUBLE), vectors)
        if not keep_mask.all():
            out = out.take(np.nonzero(keep_mask)[0])
        return out

    def sparse_kernel_spec(self, known):
        """Sparse-convention spec (docs/sparse.md): each scalar index column
        encodes on device as at-most-one sparse entry (``onehot_encode_fn``,
        the body ``transform`` jits) — the head of the one-hot→interaction
        CTR chain. Only ``handleInvalid='keep'`` fuses: 'error' must raise on
        the host and 'skip' changes the row count."""
        if self.category_sizes is None:
            raise RuntimeError("set_model_data must be called before kernel_spec")
        if self.get_handle_invalid() != "keep":
            return None
        in_cols = tuple(self.get_input_cols())
        out_cols = tuple(self.get_output_cols())
        layouts = [self._layout(i) for i in range(len(in_cols))]

        def kernel_fn(model, cols):
            outs = {}
            for name, out_name, (size, vec_len) in zip(in_cols, out_cols, layouts):
                ov, oi, oz = sparse_names(out_name)
                values, ids, nnz = onehot_encode_fn(cols[name], size, vec_len)
                outs[ov], outs[oi], outs[oz] = values, ids, nnz
            return outs

        return KernelSpec(
            input_cols=in_cols,
            outputs=tuple(
                (name, DataTypes.vector(BasicType.DOUBLE)) for name in out_cols
            ),
            model_arrays={},
            kernel_fn=kernel_fn,
            input_kinds={name: "scalar" for name in in_cols},
            sparse_outputs={
                out_name: vec_len
                for out_name, (_size, vec_len) in zip(out_cols, layouts)
            },
            elementwise=True,  # compare + where per row: no accumulation
        )


class OneHotEncoder(Estimator, _OheParams):
    """Ref OneHotEncoder.java — model data is maxIndex+1 per column."""

    def fit(self, *inputs) -> OneHotEncoderModel:
        (df,) = inputs
        sizes = []
        for name in self.get_input_cols():
            idx = df.scalars(name)
            if (idx < 0).any() or (idx != np.floor(idx)).any():
                raise ValueError(f"Column {name} must contain non-negative integers.")
            sizes.append(int(idx.max()) + 1)
        model = OneHotEncoderModel()
        update_existing_params(model, self)
        model.category_sizes = np.asarray(sizes, np.int64)
        return model
