"""KBinsDiscretizer.

Reference: ``flink-ml-lib/.../feature/kbinsdiscretizer/`` — bin each dimension of
the input vector into integer bin ids. Strategies (KBinsDiscretizerParams):
'uniform' (equal widths min..max), 'quantile' (equal counts; duplicate edges
collapsed, which may yield fewer bins), 'kmeans' (1D k-means; edges are midpoints
between sorted centroids). Transform clamps out-of-range values into the first /
last bin (KBinsDiscretizerModel's binary search with clipping).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.ops.kernels import kbins_transform_fn, kbins_transform_kernel
from flink_ml_tpu.params.param import IntParam, ParamValidators, StringParam, update_existing_params
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec
from flink_ml_tpu.utils import read_write as rw

__all__ = ["KBinsDiscretizer", "KBinsDiscretizerModel"]

UNIFORM, QUANTILE, KMEANS = "uniform", "quantile", "kmeans"


class _KbdParams(HasInputCol, HasOutputCol):
    STRATEGY = StringParam(
        "strategy",
        "Strategy used to define the width of the bin.",
        QUANTILE,
        ParamValidators.in_array([UNIFORM, QUANTILE, KMEANS]),
    )
    NUM_BINS = IntParam("numBins", "Number of bins to produce.", 5, ParamValidators.gt_eq(2))
    SUB_SAMPLES = IntParam(
        "subSamples",
        "Maximum number of samples used to fit the model.",
        200_000,
        ParamValidators.gt_eq(2),
    )

    def get_strategy(self) -> str:
        return self.get(self.STRATEGY)

    def set_strategy(self, value: str):
        return self.set(self.STRATEGY, value)

    def get_num_bins(self) -> int:
        return self.get(self.NUM_BINS)

    def set_num_bins(self, value: int):
        return self.set(self.NUM_BINS, value)

    def get_sub_samples(self) -> int:
        return self.get(self.SUB_SAMPLES)

    def set_sub_samples(self, value: int):
        return self.set(self.SUB_SAMPLES, value)


class KBinsDiscretizerModel(Model, _KbdParams):
    """Ref KBinsDiscretizerModel.java — per-dim bin edges; the binary search
    with clipping is the shared ``kbins_transform`` kernel (``ops/kernels.py``),
    which takes the ragged per-dim edges right-padded to [d, E] with +inf."""

    def __init__(self):
        super().__init__()
        self.bin_edges: Optional[List[np.ndarray]] = None

    def _packed_edges(self):
        """(edges [d, E] +inf-padded, n_edges [d]) — the kernel's layout."""
        max_e = max(len(e) for e in self.bin_edges)
        edges = np.full((len(self.bin_edges), max_e), np.inf, np.float64)
        n_edges = np.zeros(len(self.bin_edges), np.int32)
        for d, e in enumerate(self.bin_edges):
            edges[d, : len(e)] = e
            n_edges[d] = len(e)
        return edges, n_edges

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        edges, n_edges = self._packed_edges()
        out_vals = kbins_transform_kernel()(X, edges, n_edges)
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(out_vals, np.float64),
        )
        return out

    def kernel_spec(self):
        """Bin search as a fusable spec — ``kbins_transform_fn``, the body
        ``transform``'s jitted kernel wraps, with the packed edges as
        committed device buffers."""
        if self.bin_edges is None:
            raise RuntimeError("set_model_data must be called before kernel_spec")
        in_col, out_col = self.get_input_col(), self.get_output_col()
        edges, n_edges = self._packed_edges()

        def kernel_fn(model, cols):
            return {
                out_col: kbins_transform_fn(cols[in_col], model["edges"], model["n_edges"])
            }

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={"edges": edges.astype(np.float32), "n_edges": n_edges},
            kernel_fn=kernel_fn,
            elementwise=True,  # searchsorted + clip: no FP accumulation
        )

    def get_model_data(self):
        from flink_ml_tpu.api.dataframe import DataFrame

        return [DataFrame(["binEdges"], None, [[list(map(np.asarray, self.bin_edges))]])]

    def set_model_data(self, *model_data):
        self.bin_edges = [np.asarray(e) for e in model_data[0].column("binEdges")[0]]
        return self

    def save(self, path: str) -> None:
        rw.save_metadata(self, path)
        arrays = {f"dim{i}": np.asarray(e) for i, e in enumerate(self.bin_edges)}
        arrays["__num_dims__"] = np.asarray([len(self.bin_edges)])
        rw.save_model_arrays(path, arrays)

    @classmethod
    def load(cls, path: str):
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        arrays = rw.load_model_arrays(path)
        model.bin_edges = [
            arrays[f"dim{i}"] for i in range(int(arrays["__num_dims__"][0]))
        ]
        return model


def _kmeans_1d(x: np.ndarray, k: int, iters: int = 30) -> np.ndarray:
    centers = np.quantile(x, np.linspace(0, 1, k))
    for _ in range(iters):
        assign = np.argmin(np.abs(x[:, None] - centers[None, :]), axis=1)
        for j in range(k):
            sel = x[assign == j]
            if sel.size:
                centers[j] = sel.mean()
    return np.sort(centers)


class KBinsDiscretizer(Estimator, _KbdParams):
    """Ref KBinsDiscretizer.java."""

    def fit(self, *inputs) -> KBinsDiscretizerModel:
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        if len(X) == 0:
            raise RuntimeError("The training set is empty.")
        if len(X) > self.get_sub_samples():
            # Ref KBinsDiscretizer.java:117 — DataStreamUtils.sample (reservoir).
            from flink_ml_tpu.parallel.datastream_utils import sample

            X = sample({"x": X}, self.get_sub_samples(), seed=0)["x"]
        k = self.get_num_bins()
        strategy = self.get_strategy()
        quantile_edges = None
        if strategy == QUANTILE:
            # Distributed GK sketches per dim (exact below the compress threshold).
            from flink_ml_tpu.parallel.datastream_utils import distributed_quantiles

            quantile_edges = distributed_quantiles(X, np.linspace(0, 1, k + 1))
        edges_per_dim: List[np.ndarray] = []
        for d in range(X.shape[1]):
            x = X[:, d]
            if strategy == UNIFORM:
                edges = np.linspace(x.min(), x.max(), k + 1)
            elif strategy == QUANTILE:
                edges = quantile_edges[:, d]
            else:
                centers = _kmeans_1d(x, k)
                mids = (centers[:-1] + centers[1:]) / 2.0
                edges = np.concatenate([[x.min()], mids, [x.max()]])
            # Collapse duplicate edges for every strategy (constant dims would
            # otherwise bin into the LAST bucket; ref KBinsDiscretizer.java:192-196
            # maps them to a single bin 0).
            edges = np.unique(edges)
            if len(edges) < 2:
                edges = np.asarray([x.min(), x.max() + 1e-12])
            edges_per_dim.append(edges)
        model = KBinsDiscretizerModel()
        update_existing_params(model, self)
        model.bin_edges = edges_per_dim
        return model
