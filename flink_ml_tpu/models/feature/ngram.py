"""NGram.

Reference: ``flink-ml-lib/.../feature/ngram/NGram.java`` — convert a token list
into n-grams joined by spaces; fewer than n tokens → empty output.
"""
from __future__ import annotations

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.params.param import IntParam, ParamValidators
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol

__all__ = ["NGram"]


class NGram(Transformer, HasInputCol, HasOutputCol):
    """Ref NGram.java."""

    N = IntParam("n", "Number of elements per n-gram (>=1).", 2, ParamValidators.gt_eq(1))

    def get_n(self) -> int:
        return self.get(self.N)

    def set_n(self, value: int):
        return self.set(self.N, value)

    def transform(self, *inputs):
        (df,) = inputs
        n = self.get_n()
        col = df.column(self.get_input_col())
        grams = [
            [" ".join(tokens[i : i + n]) for i in range(len(tokens) - n + 1)]
            for tokens in col
        ]
        out = df.clone()
        out.add_column(self.get_output_col(), DataTypes.STRING, grams)
        return out
