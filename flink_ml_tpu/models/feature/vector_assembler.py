"""VectorAssembler.

Reference: ``flink-ml-lib/.../feature/vectorassembler/VectorAssembler.java`` —
concatenate numeric and vector input columns into one vector; ``inputSizes``
declares each column's width (used to fill nulls); handleInvalid: 'error' raises
on null/NaN/size mismatch, 'skip' drops the row, 'keep' fills nulls with NaN.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import Vector
from flink_ml_tpu.ops.kernels import (
    assemble_fn,
    assemble_kernel,
    sparse_to_dense_fn,
    sparse_to_dense_kernel,
)
from flink_ml_tpu.params.param import IntArrayParam, ParamValidators
from flink_ml_tpu.params.shared import HasHandleInvalid, HasInputCols, HasOutputCol
from flink_ml_tpu.servable.sparse import sparse_names
from flink_ml_tpu.servable.kernel_spec import KernelSpec

__all__ = ["VectorAssembler"]


class VectorAssembler(Transformer, HasInputCols, HasOutputCol, HasHandleInvalid):
    """Ref VectorAssembler.java."""

    INPUT_SIZES = IntArrayParam(
        "inputSizes",
        "Sizes of the input elements to be assembled (one per input column).",
        None,
        lambda v: v is not None and all(int(s) > 0 for s in v),
    )

    def get_input_sizes(self):
        return self.get(self.INPUT_SIZES)

    def set_input_sizes(self, *values: int):
        return self.set(self.INPUT_SIZES, list(values))

    def transform(self, *inputs):
        (df,) = inputs
        in_cols = self.get_input_cols()
        declared = self.get_input_sizes()
        if declared is None:
            # The reference's inputSizes defaults to null — sizes are then
            # taken from the data itself (scalars are width 1).
            sizes = []
            for name in in_cols:
                col = df.column(name)
                if isinstance(col, np.ndarray) and col.ndim == 2:
                    sizes.append(int(col.shape[1]))
                elif isinstance(col, np.ndarray):
                    sizes.append(1)
                else:
                    first = next((v for v in col if v is not None), None)
                    sizes.append(int(first.size()) if isinstance(first, Vector) else 1)
        else:
            sizes = [int(s) for s in declared]
        handle = self.get_handle_invalid()
        if len(sizes) != len(in_cols):
            raise ValueError("VectorAssembler: one input size per input column required")
        n = len(df)
        invalid = np.zeros(n, bool)

        # Size-mismatch semantics (VectorAssembler.java:120-126, 183-186): 'error'
        # raises, 'skip' drops the row, 'keep' keeps it (the reference then emits a
        # ragged output vector; the columnar layout here fills NaN instead — the
        # one documented deviation).
        blocks = []
        for name, size in zip(in_cols, sizes):
            col = df.column(name)
            block = np.full((n, size), np.nan)
            if df.is_sparse(name):
                # Sparse input: densify on device through the SAME
                # ``sparse_to_dense`` scatter the fused sparse spec composes
                # (per-entry set, no accumulation — docs/sparse.md). A
                # malformed column (None rows, dim mismatch) falls through
                # to the per-row loop's invalid handling below.
                try:
                    batch = df.sparse_batch(name)
                except (TypeError, ValueError):
                    batch = None
                if batch is not None and batch.dim == size:
                    blocks.append(
                        np.asarray(
                            sparse_to_dense_kernel(size)(
                                batch.values, batch.indices, batch.nnz
                            ),
                            np.float64,
                        )
                    )
                    continue
            if isinstance(col, np.ndarray):
                vals = col if col.ndim == 2 else col[:, None].astype(np.float64)
                if vals.shape[1] != size:
                    if handle == "error":
                        raise ValueError(
                            f"Input column {name} has size {vals.shape[1]} but "
                            f"expected {size}."
                        )
                    invalid[:] = True
                else:
                    block = vals.astype(np.float64)
            else:
                for i, v in enumerate(col):
                    if v is None:
                        invalid[i] = True
                        continue
                    arr = v.to_array() if isinstance(v, Vector) else np.asarray([v], np.float64)
                    if arr.shape[0] != size:
                        if handle == "error":
                            raise ValueError(
                                f"Input column {name} has size {arr.shape[0]} but "
                                f"expected {size}."
                            )
                        invalid[i] = True
                        continue
                    block[i] = arr
            blocks.append(block)
        # The concat is the shared ``assemble`` kernel, so per-stage and fused
        # outputs agree bitwise (device f32, stored as DOUBLE like every stage).
        assembled = np.asarray(assemble_kernel()(*blocks), np.float64)

        nan_rows = np.isnan(assembled).any(axis=1)
        if handle == "error":
            if invalid.any() or nan_rows.any():
                raise ValueError(
                    "Vector assembler failed: encountered null/NaN with handleInvalid = "
                    "'error'. Consider handleInvalid = 'keep' or 'skip'."
                )
        elif handle == "skip":
            keep = ~(invalid | nan_rows)
            df = df.take(np.nonzero(keep)[0])
            assembled = assembled[keep]
        out = df.clone()
        out.add_column(
            self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), assembled
        )
        return out

    def kernel_spec(self):
        """Concatenation as a fusable spec — ``assemble_fn``, the body
        ``transform``'s jitted kernel wraps. Only 'keep' mode fuses: 'error'
        must raise on runtime NaN (a host decision) and 'skip' changes the
        row count. Inputs ingest as ``dense`` (null-bearing list columns fall
        the segment back to the per-stage path); a declared-size mismatch is
        static at trace time and fills NaN, exactly the 'keep' semantics."""
        in_cols = self.get_input_cols()
        if self.get_handle_invalid() != "keep" or not in_cols:
            return None
        out_col = self.get_output_col()
        declared = self.get_input_sizes()
        sizes = [int(s) for s in declared] if declared is not None else [None] * len(in_cols)
        if len(sizes) != len(in_cols):
            return None  # transform raises the param error on the classic path
        bindings = tuple(zip(in_cols, sizes))

        def kernel_fn(model, cols):
            blocks = []
            for name, size in bindings:
                arr = cols[name]
                if arr.ndim == 1:
                    arr = arr[:, None]
                if size is not None and arr.shape[1] != size:
                    arr = jnp.full((arr.shape[0], size), jnp.nan, arr.dtype)
                blocks.append(arr)
            return {out_col: assemble_fn(*blocks)}

        return KernelSpec(
            input_cols=in_cols,
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
            input_kinds={n: "dense" for n in in_cols},
            elementwise=True,  # reshape + concat: no FP arithmetic at all
        )

    def sparse_kernel_spec(self, known):
        """Sparse-convention spec (docs/sparse.md): input columns that arrive
        sparse densify on device (``sparse_to_dense_fn`` — the per-entry
        scatter ``transform``'s sparse branch jits) before the shared
        ``assemble`` concat; dense inputs ingest as usual. The output is the
        same dense vector the per-stage path emits, bit for bit. Requires
        declared or known sizes for the sparse inputs ('keep' mode only,
        like the dense spec)."""
        in_cols = tuple(self.get_input_cols() or ())
        if self.get_handle_invalid() != "keep" or not in_cols:
            return None
        if not any(name in known for name in in_cols):
            return None  # nothing sparse here: the dense spec serves
        declared = self.get_input_sizes()
        sizes = [int(s) for s in declared] if declared is not None else [None] * len(in_cols)
        if len(sizes) != len(in_cols):
            return None
        out_col = self.get_output_col()
        bindings = []
        sparse_dims = {}
        for name, size in zip(in_cols, sizes):
            if name in known:
                dim = int(known[name])
                if size is not None and size != dim:
                    return None  # size-mismatched sparse input: per-stage path
                bindings.append((name, dim, True))
                sparse_dims[name] = dim
            else:
                bindings.append((name, size, False))

        def kernel_fn(model, cols):
            blocks = []
            for name, size, is_sp in bindings:
                if is_sp:
                    vn, idn, zn = sparse_names(name)
                    blocks.append(
                        sparse_to_dense_fn(cols[vn], cols[idn], cols[zn], size)
                    )
                    continue
                arr = cols[name]
                if arr.ndim == 1:
                    arr = arr[:, None]
                if size is not None and arr.shape[1] != size:
                    arr = jnp.full((arr.shape[0], size), jnp.nan, arr.dtype)
                blocks.append(arr)
            return {out_col: assemble_fn(*blocks)}

        return KernelSpec(
            input_cols=in_cols,
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
            input_kinds={
                name: ("sparse" if is_sp else "dense")
                for name, _size, is_sp in bindings
            },
            sparse_input_dims=sparse_dims,
            elementwise=True,  # scatter-set + reshape + concat: no accumulation
        )
