"""Imputer.

Reference: ``flink-ml-lib/.../feature/imputer/Imputer.java`` — multi-column
completion of missing values (``missingValue``, default NaN) with the column's
mean / median / most_frequent surrogate computed over non-missing entries.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.params.param import FloatParam, ParamValidators, StringParam, update_existing_params
from flink_ml_tpu.params.shared import HasInputCols, HasOutputCols, HasRelativeError

__all__ = ["Imputer", "ImputerModel"]


class _ImputerParams(HasInputCols, HasOutputCols, HasRelativeError):
    MEAN, MEDIAN, MOST_FREQUENT = "mean", "median", "most_frequent"

    STRATEGY = StringParam(
        "strategy",
        "The imputation strategy.",
        "mean",
        ParamValidators.in_array(["mean", "median", "most_frequent"]),
    )
    MISSING_VALUE = FloatParam(
        "missingValue", "The placeholder for the missing values.", float("nan")
    )

    def get_strategy(self) -> str:
        return self.get(self.STRATEGY)

    def set_strategy(self, value: str):
        return self.set(self.STRATEGY, value)

    def get_missing_value(self) -> float:
        return self.get(self.MISSING_VALUE)

    def set_missing_value(self, value: float):
        return self.set(self.MISSING_VALUE, value)


def _is_missing(x: np.ndarray, missing: float) -> np.ndarray:
    return np.isnan(x) if np.isnan(missing) else (x == missing)


class ImputerModel(ModelArraysMixin, Model, _ImputerParams):
    """Ref ImputerModel.java — surrogate per input column."""

    _MODEL_ARRAY_NAMES = ("surrogates",)

    def __init__(self):
        super().__init__()
        self.surrogates: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        missing = self.get_missing_value()
        out = df.clone()
        for i, (in_name, out_name) in enumerate(
            zip(self.get_input_cols(), self.get_output_cols())
        ):
            x = df.scalars(in_name)
            filled = np.where(_is_missing(x, missing), self.surrogates[i], x)
            out.add_column(out_name, DataTypes.DOUBLE, filled)
        return out


class Imputer(Estimator, _ImputerParams):
    """Ref Imputer.java."""

    def fit(self, *inputs) -> ImputerModel:
        (df,) = inputs
        strategy = self.get_strategy()
        missing = self.get_missing_value()
        surrogates = []
        for name in self.get_input_cols():
            x = df.scalars(name)
            valid = x[~_is_missing(x, missing) & ~np.isnan(x)]
            if valid.size == 0:
                raise RuntimeError(f"Imputer: column {name} has no valid values to fit.")
            if strategy == self.MEAN:
                surrogates.append(valid.mean())
            elif strategy == self.MEDIAN:
                surrogates.append(np.median(valid))
            else:  # most_frequent: smallest among the modes, like the reference's map
                vals, counts = np.unique(valid, return_counts=True)
                surrogates.append(vals[np.argmax(counts)])
        model = ImputerModel()
        update_existing_params(model, self)
        model.surrogates = np.asarray(surrogates)
        return model
