"""Imputer.

Reference: ``flink-ml-lib/.../feature/imputer/Imputer.java`` — multi-column
completion of missing values (``missingValue``, default NaN) with the column's
mean / median / most_frequent surrogate computed over non-missing entries.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.ops.kernels import impute_fn, impute_kernel
from flink_ml_tpu.params.param import FloatParam, ParamValidators, StringParam, update_existing_params
from flink_ml_tpu.params.shared import HasInputCols, HasOutputCols, HasRelativeError
from flink_ml_tpu.servable.kernel_spec import KernelSpec

__all__ = ["Imputer", "ImputerModel"]


class _ImputerParams(HasInputCols, HasOutputCols, HasRelativeError):
    MEAN, MEDIAN, MOST_FREQUENT = "mean", "median", "most_frequent"

    STRATEGY = StringParam(
        "strategy",
        "The imputation strategy.",
        "mean",
        ParamValidators.in_array(["mean", "median", "most_frequent"]),
    )
    MISSING_VALUE = FloatParam(
        "missingValue", "The placeholder for the missing values.", float("nan")
    )

    def get_strategy(self) -> str:
        return self.get(self.STRATEGY)

    def set_strategy(self, value: str):
        return self.set(self.STRATEGY, value)

    def get_missing_value(self) -> float:
        return self.get(self.MISSING_VALUE)

    def set_missing_value(self, value: float):
        return self.set(self.MISSING_VALUE, value)


def _is_missing(x: np.ndarray, missing: float) -> np.ndarray:
    return np.isnan(x) if np.isnan(missing) else (x == missing)


def _missing_static(missing: float):
    """Canonicalize the placeholder for the kernel cache: NaN placeholders
    must key as (True, 0.0) — NaN != NaN would defeat ``functools.cache``."""
    return (True, 0.0) if np.isnan(missing) else (False, float(missing))


class ImputerModel(ModelArraysMixin, Model, _ImputerParams):
    """Ref ImputerModel.java — surrogate per input column, filled by the
    shared ``impute`` kernel (``ops/kernels.py``)."""

    _MODEL_ARRAY_NAMES = ("surrogates",)

    def __init__(self):
        super().__init__()
        self.surrogates: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        is_nan, value = _missing_static(self.get_missing_value())
        kernel = impute_kernel(is_nan, value)
        out = df.clone()
        for i, (in_name, out_name) in enumerate(
            zip(self.get_input_cols(), self.get_output_cols())
        ):
            x = df.scalars(in_name)
            filled = kernel(x, self.surrogates[i])
            out.add_column(out_name, DataTypes.DOUBLE, np.asarray(filled, np.float64))
        return out

    def kernel_spec(self):
        """Per-column surrogate fill as a fusable spec — ``impute_fn``, the
        body ``transform``'s jitted kernel wraps, with the surrogates as a
        committed device buffer."""
        if self.surrogates is None:
            raise RuntimeError("set_model_data must be called before kernel_spec")
        in_cols, out_cols = self.get_input_cols(), self.get_output_cols()
        is_nan, value = _missing_static(self.get_missing_value())
        bindings = tuple((i, n, o) for i, (n, o) in enumerate(zip(in_cols, out_cols)))

        def kernel_fn(model, cols):
            return {
                o: impute_fn(cols[n], model["surrogates"][i], is_nan, value)
                for i, n, o in bindings
            }

        return KernelSpec(
            input_cols=in_cols,
            outputs=tuple((o, DataTypes.DOUBLE) for o in out_cols),
            model_arrays={"surrogates": np.asarray(self.surrogates, np.float32)},
            kernel_fn=kernel_fn,
            input_kinds={n: "scalar" for n in in_cols},
            elementwise=True,  # isnan/where fill: no FP accumulation
            fusion_op="impute",  # megakernel-safe
        )


class Imputer(Estimator, _ImputerParams):
    """Ref Imputer.java."""

    def fit(self, *inputs) -> ImputerModel:
        (df,) = inputs
        strategy = self.get_strategy()
        missing = self.get_missing_value()
        surrogates = []
        for name in self.get_input_cols():
            x = df.scalars(name)
            valid = x[~_is_missing(x, missing) & ~np.isnan(x)]
            if valid.size == 0:
                raise RuntimeError(f"Imputer: column {name} has no valid values to fit.")
            if strategy == self.MEAN:
                surrogates.append(valid.mean())
            elif strategy == self.MEDIAN:
                surrogates.append(np.median(valid))
            else:  # most_frequent: smallest among the modes, like the reference's map
                vals, counts = np.unique(valid, return_counts=True)
                surrogates.append(vals[np.argmax(counts)])
        model = ImputerModel()
        update_existing_params(model, self)
        model.surrogates = np.asarray(surrogates)
        return model
