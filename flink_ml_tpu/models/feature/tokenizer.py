"""Tokenizer and RegexTokenizer.

Reference: ``flink-ml-lib/.../feature/tokenizer/Tokenizer.java`` (lowercase, split
on ``\\s``) and ``feature/regextokenizer/RegexTokenizer.java`` (pattern default
``\\s+``, ``gaps`` default true — pattern matches separators; false — pattern
matches tokens; ``minTokenLength`` default 1; ``toLowercase`` default true).
"""
from __future__ import annotations

import re

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.params.param import BoolParam, IntParam, ParamValidators, StringParam
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol

__all__ = ["Tokenizer", "RegexTokenizer"]


class Tokenizer(Transformer, HasInputCol, HasOutputCol):
    """Ref Tokenizer.java — lowercase then ``split("\\s")``: consecutive whitespace
    produces interior empty-string tokens (Java's split drops only trailing
    empties), which downstream HashingTF/CountVectorizer see as terms."""

    def transform(self, *inputs):
        (df,) = inputs
        col = df.column(self.get_input_col())
        tokens = []
        for s in col:
            toks = re.split(r"\s", s.lower())
            while toks and toks[-1] == "":
                toks.pop()
            tokens.append(toks)
        out = df.clone()
        out.add_column(self.get_output_col(), DataTypes.STRING, tokens)
        return out


class RegexTokenizer(Transformer, HasInputCol, HasOutputCol):
    """Ref RegexTokenizer.java."""

    PATTERN = StringParam("pattern", "Regex pattern used for tokenizing.", r"\s+")
    GAPS = BoolParam(
        "gaps", "Whether the pattern matches gaps (true) or tokens (false).", True
    )
    MIN_TOKEN_LENGTH = IntParam(
        "minTokenLength", "Minimum token length.", 1, ParamValidators.gt_eq(0)
    )
    TO_LOWERCASE = BoolParam(
        "toLowercase", "Whether to convert all characters to lowercase before tokenizing.", True
    )

    def get_pattern(self) -> str:
        return self.get(self.PATTERN)

    def set_pattern(self, value: str):
        return self.set(self.PATTERN, value)

    def get_gaps(self) -> bool:
        return self.get(self.GAPS)

    def set_gaps(self, value: bool):
        return self.set(self.GAPS, value)

    def get_min_token_length(self) -> int:
        return self.get(self.MIN_TOKEN_LENGTH)

    def set_min_token_length(self, value: int):
        return self.set(self.MIN_TOKEN_LENGTH, value)

    def get_to_lowercase(self) -> bool:
        return self.get(self.TO_LOWERCASE)

    def set_to_lowercase(self, value: bool):
        return self.set(self.TO_LOWERCASE, value)

    def transform(self, *inputs):
        (df,) = inputs
        pattern = re.compile(self.get_pattern())
        gaps = self.get_gaps()
        min_len = self.get_min_token_length()
        lower = self.get_to_lowercase()
        col = df.column(self.get_input_col())
        tokens = []
        for s in col:
            if lower:
                s = s.lower()
            toks = pattern.split(s) if gaps else pattern.findall(s)
            tokens.append([t for t in toks if len(t) >= min_len])
        out = df.clone()
        out.add_column(self.get_output_col(), DataTypes.STRING, tokens)
        return out
