"""VectorIndexer.

Reference: ``flink-ml-lib/.../feature/vectorindexer/VectorIndexer.java`` — decide
per input-vector dimension whether it is categorical (≤ ``maxCategories``
distinct values); categorical dims get their values mapped to indices over the
sorted distinct values with 0.0 (if present) forced to index 0
(VectorIndexer.ModelGenerator); continuous dims pass through. ``handleInvalid``
applies to unseen values of categorical dims at transform ('keep' maps them to
mapSize).
"""
from __future__ import annotations

import json
from typing import Dict, Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.params.param import IntParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import HasHandleInvalid, HasInputCol, HasOutputCol
from flink_ml_tpu.utils import read_write as rw

__all__ = ["VectorIndexer", "VectorIndexerModel"]


class _ViParams(HasInputCol, HasOutputCol, HasHandleInvalid):
    MAX_CATEGORIES = IntParam(
        "maxCategories",
        "Threshold for the number of values a categorical feature can take.",
        20,
        ParamValidators.gt(1),
    )

    def get_max_categories(self) -> int:
        return self.get(self.MAX_CATEGORIES)

    def set_max_categories(self, value: int):
        return self.set(self.MAX_CATEGORIES, value)


class VectorIndexerModel(Model, _ViParams):
    """Ref VectorIndexerModel.java — categoryMaps: dim → {value → index}."""

    def __init__(self):
        super().__init__()
        self.category_maps: Optional[Dict[int, Dict[float, int]]] = None

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        handle = self.get_handle_invalid()
        out_vals = X.copy()
        keep_mask = np.ones(len(X), bool)
        for d, mapping in self.category_maps.items():
            col = X[:, d]
            mapped = np.full(len(col), -1.0)
            for value, idx in mapping.items():
                mapped[col == value] = idx
            unseen = mapped < 0
            if unseen.any():
                if handle == "error":
                    raise ValueError(
                        f"The input contains unseen value {col[unseen][0]} in dim {d}."
                    )
                if handle == "keep":
                    mapped[unseen] = len(mapping)
                else:
                    keep_mask &= ~unseen
            out_vals[:, d] = mapped
        out = df.clone()
        out.add_column(
            self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), out_vals
        )
        if not keep_mask.all():
            out = out.take(np.nonzero(keep_mask)[0])
        return out

    def get_model_data(self):
        from flink_ml_tpu.api.dataframe import DataFrame

        return [DataFrame(["categoryMaps"], None, [[self.category_maps]])]

    def set_model_data(self, *model_data):
        self.category_maps = model_data[0].column("categoryMaps")[0]
        return self

    def save(self, path: str) -> None:
        rw.save_metadata(
            self,
            path,
            {
                "categoryMaps": {
                    str(d): {repr(v): i for v, i in m.items()}
                    for d, m in self.category_maps.items()
                }
            },
        )

    @classmethod
    def load(cls, path: str):
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        model.category_maps = {
            int(d): {float(v): int(i) for v, i in m.items()}
            for d, m in metadata["categoryMaps"].items()
        }
        return model


class VectorIndexer(Estimator, _ViParams):
    """Ref VectorIndexer.java."""

    def fit(self, *inputs) -> VectorIndexerModel:
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        max_cat = self.get_max_categories()
        category_maps: Dict[int, Dict[float, int]] = {}
        for d in range(X.shape[1]):
            distinct = np.unique(X[:, d])
            if len(distinct) <= max_cat:
                values = sorted(distinct.tolist())
                if 0.0 in values:  # 0 is forced to index 0 (sparse-friendly)
                    values.remove(0.0)
                    values = [0.0] + values
                category_maps[d] = {v: i for i, v in enumerate(values)}
        model = VectorIndexerModel()
        update_existing_params(model, self)
        model.category_maps = category_maps
        return model
