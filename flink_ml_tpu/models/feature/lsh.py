"""MinHashLSH — locality-sensitive hashing for Jaccard similarity.

Reference: ``flink-ml-lib/.../feature/lsh/`` — ``MinHashLSHModelData`` (random
affine hash family over the prime 2038074743, coefficients drawn from
``java.util.Random(seed)`` — reproduced bit-exactly here; hash value per function
= min over non-zero indices of ((1+idx)·a + b) mod PRIME,
MinHashLSHModelData.java:125-143), ``LSHModel`` (transform appends the per-table
hash vectors; ``approxNearestNeighbors`` prunes candidates sharing a hash-table
bucket with the key then ranks by exact ``keyDistance`` = 1 − Jaccard;
``approxSimilarityJoin`` joins pairs sharing a bucket below a distance threshold,
LSHModel.java:334-482).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector, Vector
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.params.param import IntParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol, HasSeed

# The affine-family modulus lives with the serving tier (L1) so the fused
# retrieval head and this training-side model can never drift apart; re-export
# keeps this module's historical name working.
from flink_ml_tpu.servable.retrieval import HASH_PRIME

__all__ = ["HASH_PRIME", "MinHashLSH", "MinHashLSHModel"]


class JavaRandom:
    """java.util.Random's 48-bit LCG — needed for coefficient parity."""

    def __init__(self, seed: int):
        self._seed = (seed ^ 0x5DEECE66D) & ((1 << 48) - 1)

    def _next(self, bits: int) -> int:
        self._seed = (self._seed * 0x5DEECE66D + 0xB) & ((1 << 48) - 1)
        return self._seed >> (48 - bits)

    def next_int(self, bound: int) -> int:
        if bound <= 0:
            raise ValueError("bound must be positive")
        if (bound & -bound) == bound:  # power of two
            return (bound * self._next(31)) >> 31
        while True:
            bits = self._next(31)
            val = bits % bound
            if bits - val + (bound - 1) < (1 << 31):  # no int overflow
                return val


def _to_indices(v) -> np.ndarray:
    if isinstance(v, SparseVector):
        return np.asarray(v.indices, np.int64)
    arr = v.to_array() if isinstance(v, Vector) else np.asarray(v)
    return np.nonzero(arr)[0]


class _LshParams(HasInputCol, HasOutputCol, HasSeed):
    NUM_HASH_TABLES = IntParam(
        "numHashTables", "Number of hash tables.", 1, ParamValidators.gt_eq(1)
    )
    NUM_HASH_FUNCTIONS_PER_TABLE = IntParam(
        "numHashFunctionsPerTable",
        "Number of hash functions per hash table.",
        1,
        ParamValidators.gt_eq(1),
    )

    def get_num_hash_tables(self) -> int:
        return self.get(self.NUM_HASH_TABLES)

    def set_num_hash_tables(self, value: int):
        return self.set(self.NUM_HASH_TABLES, value)

    def get_num_hash_functions_per_table(self) -> int:
        return self.get(self.NUM_HASH_FUNCTIONS_PER_TABLE)

    def set_num_hash_functions_per_table(self, value: int):
        return self.set(self.NUM_HASH_FUNCTIONS_PER_TABLE, value)


class MinHashLSHModel(ModelArraysMixin, Model, _LshParams):
    """Ref MinHashLSHModel.java / LSHModel.java."""

    _MODEL_ARRAY_NAMES = ("coeff_a", "coeff_b")

    def __init__(self):
        super().__init__()
        self.coeff_a: Optional[np.ndarray] = None
        self.coeff_b: Optional[np.ndarray] = None

    # --- hash family ---------------------------------------------------------
    def hash_function(self, v) -> np.ndarray:
        """[numHashTables, numHashFunctionsPerTable] minhash values.
        Ref MinHashLSHModelData.hashFunction:125."""
        indices = _to_indices(v)
        if indices.size == 0:
            raise ValueError("Must have at least 1 non zero entry.")
        vals = ((1 + indices[:, None]) * self.coeff_a[None, :] + self.coeff_b[None, :]) % HASH_PRIME
        mins = vals.min(axis=0).astype(np.float64)
        return mins.reshape(self.get_num_hash_tables(), self.get_num_hash_functions_per_table())

    @staticmethod
    def key_distance(x, y) -> float:
        """1 − Jaccard over non-zero index sets. Ref keyDistance:146."""
        xi, yi = set(_to_indices(x).tolist()), set(_to_indices(y).tolist())
        if not xi and not yi:
            raise ValueError("The union of two input sets must have at least 1 elements")
        return 1.0 - len(xi & yi) / len(xi | yi)

    # --- Model API -----------------------------------------------------------
    @classmethod
    def load_servable(cls, path: str):
        """Load a published retrieval index built under this model's hash
        family (``CandidateIndex.from_lsh_model`` → ``publish_servable``) as
        its runtime-free two-phase serving head (docs/retrieval.md)."""
        from flink_ml_tpu.servable.retrieval import LSHTopKServable

        return LSHTopKServable.load_servable(path)

    def transform(self, *inputs):
        (df,) = inputs
        col = df.column(self.get_input_col())
        hashes = [self.hash_function(v) for v in col]
        out = df.clone()
        out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), hashes)
        return out

    def approx_nearest_neighbors(
        self, dataset: DataFrame, key, k: int, dist_col: str = "distCol"
    ) -> DataFrame:
        """Top-k rows of ``dataset`` closest to ``key``, pruned by shared hash-table
        buckets (OR-amplification). Ref LSHModel.approxNearestNeighbors:334-380."""
        key_hash = self.hash_function(key)
        col = dataset.column(self.get_input_col())
        candidates = []
        for i, v in enumerate(col):
            if _to_indices(v).size == 0:
                continue  # all-zero row: hashes to no bucket, never a candidate
            h = self.hash_function(v)
            if (h == key_hash).all(axis=1).any():  # shares at least one full bucket
                candidates.append(i)
        dists = [(i, self.key_distance(key, col[i])) for i in candidates]
        dists.sort(key=lambda t: t[1])  # stable: distance ties keep row order
        top = dists[:k]
        # No bucket-sharing candidates is a typed empty result, not an error.
        subset = dataset.take(np.asarray([i for i, _ in top], np.int64))
        subset.add_column(
            dist_col, DataTypes.DOUBLE, np.asarray([d for _, d in top], np.float64)
        )
        return subset

    def approx_similarity_join(
        self,
        dataset_a: DataFrame,
        dataset_b: DataFrame,
        threshold: float,
        id_col: str,
        dist_col: str = "distCol",
    ) -> DataFrame:
        """Pairs (idA, idB, distance) with distance < threshold among bucket-sharing
        pairs. Ref LSHModel.approxSimilarityJoin:430-482."""
        in_col = self.get_input_col()

        def explode(df):
            buckets = {}
            for i, v in enumerate(df.column(in_col)):
                for t, row in enumerate(self.hash_function(v)):
                    buckets.setdefault((t, tuple(row.tolist())), []).append(i)
            return buckets

        buckets_a, buckets_b = explode(dataset_a), explode(dataset_b)
        ids_a, ids_b = dataset_a.column(id_col), dataset_b.column(id_col)
        col_a, col_b = dataset_a.column(in_col), dataset_b.column(in_col)
        seen = set()
        rows = []
        for bucket, a_rows in buckets_a.items():
            for ia in a_rows:
                for ib in buckets_b.get(bucket, ()):
                    pair = (ia, ib)
                    if pair in seen:
                        continue
                    seen.add(pair)
                    dist = self.key_distance(col_a[ia], col_b[ib])
                    if dist < threshold:
                        rows.append((ids_a[ia], ids_b[ib], dist))
        return DataFrame(
            [f"{id_col}A", f"{id_col}B", dist_col],
            None,
            [
                [r[0] for r in rows],
                [r[1] for r in rows],
                np.asarray([r[2] for r in rows], np.float64),
            ],
        )


class MinHashLSH(Estimator, _LshParams):
    """Ref MinHashLSH.java — fit draws the hash family from java.util.Random(seed)."""

    def fit(self, *inputs) -> MinHashLSHModel:
        (df,) = inputs
        col = df.column(self.get_input_col())
        first = col[0]
        dim = first.size() if isinstance(first, Vector) else np.asarray(first).shape[0]
        if dim > HASH_PRIME:
            raise ValueError(
                f"The input vector dimension {dim} exceeds the threshold {HASH_PRIME}."
            )
        rng = JavaRandom(self.get_seed())
        n = self.get_num_hash_tables() * self.get_num_hash_functions_per_table()
        # a[i], b[i] are drawn interleaved from one Random stream
        # (MinHashLSHModelData.generateModelData:81-84) — order matters for parity.
        coeff_a = np.empty(n, np.int64)
        coeff_b = np.empty(n, np.int64)
        for i in range(n):
            coeff_a[i] = 1 + rng.next_int(HASH_PRIME - 1)
            coeff_b[i] = rng.next_int(HASH_PRIME - 1)
        model = MinHashLSHModel()
        update_existing_params(model, self)
        model.coeff_a = coeff_a
        model.coeff_b = coeff_b
        return model
