"""VectorSlicer.

Reference: ``flink-ml-lib/.../feature/vectorslicer/VectorSlicer.java`` — select the
given indices (in order, duplicates disallowed) from each input vector. Dense
columns run the shared ``vector_slice`` gather kernel (``ops/kernels.py``);
sparse/ragged vectors keep the host path (sparsity preserved).
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector, Vector
from flink_ml_tpu.ops.kernels import vector_slice_fn, vector_slice_kernel
from flink_ml_tpu.params.param import IntArrayParam
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec

__all__ = ["VectorSlicer"]


def _indices_valid(v) -> bool:
    return (
        v is not None
        and len(v) > 0
        and all(int(i) >= 0 for i in v)
        and len(set(v)) == len(v)
    )


class VectorSlicer(Transformer, HasInputCol, HasOutputCol):
    """Ref VectorSlicer.java."""

    INDICES = IntArrayParam(
        "indices",
        "An array of indices to select features from a vector column.",
        None,
        _indices_valid,
    )

    def get_indices(self):
        return self.get(self.INDICES)

    def set_indices(self, *values: int):
        return self.set(self.INDICES, list(values))

    def transform(self, *inputs):
        (df,) = inputs
        idx = tuple(int(i) for i in self.get_indices())
        col = df.column(self.get_input_col())
        out = df.clone()
        if isinstance(col, np.ndarray):
            if max(idx) >= col.shape[1]:
                raise ValueError(
                    f"Index {max(idx)} out of bounds for vector of size {col.shape[1]}"
                )
            vals = vector_slice_kernel(idx)(col.astype(np.float64))
            out.add_column(
                self.get_output_col(),
                DataTypes.vector(BasicType.DOUBLE),
                np.asarray(vals, np.float64),
            )
        else:
            idx_arr = np.asarray(idx)
            new_col = []
            pos = {int(i): j for j, i in enumerate(idx_arr)}
            for v in col:
                if isinstance(v, SparseVector):
                    keep = [j for j, i in enumerate(v.indices) if int(i) in pos]
                    new_idx = np.asarray([pos[int(v.indices[j])] for j in keep])
                    order = np.argsort(new_idx) if len(new_idx) else new_idx
                    new_col.append(
                        SparseVector(
                            len(idx_arr),
                            new_idx[order] if len(new_idx) else new_idx,
                            v.values[keep][order] if len(keep) else np.zeros(0),
                        )
                    )
                else:
                    arr = v.to_array() if isinstance(v, Vector) else np.asarray(v)
                    new_col.append(arr[idx_arr])
            out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), new_col)
        return out

    def kernel_spec(self):
        """Feature gather as a fusable spec — ``vector_slice_fn``, the body
        ``transform``'s jitted kernel wraps. List (sparse) columns stay
        per-stage, so the input ingests as ``dense``; an out-of-bounds index
        for the traced width fails at compile, like ``transform`` raises."""
        if self.get_indices() is None:
            return None
        in_col, out_col = self.get_input_col(), self.get_output_col()
        idx = tuple(int(i) for i in self.get_indices())

        def kernel_fn(model, cols):
            X = cols[in_col]
            if max(idx) >= X.shape[1]:  # static trace-time width
                raise ValueError(
                    f"Index {max(idx)} out of bounds for vector of size {X.shape[1]}"
                )
            return {out_col: vector_slice_fn(X, idx)}

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
            input_kinds={in_col: "dense"},
            elementwise=True,  # gather: no FP arithmetic at all
        )
