"""VectorSlicer.

Reference: ``flink-ml-lib/.../feature/vectorslicer/VectorSlicer.java`` — select the
given indices (in order, duplicates disallowed) from each input vector.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector, Vector
from flink_ml_tpu.params.param import IntArrayParam
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol

__all__ = ["VectorSlicer"]


def _indices_valid(v) -> bool:
    return (
        v is not None
        and len(v) > 0
        and all(int(i) >= 0 for i in v)
        and len(set(v)) == len(v)
    )


class VectorSlicer(Transformer, HasInputCol, HasOutputCol):
    """Ref VectorSlicer.java."""

    INDICES = IntArrayParam(
        "indices",
        "An array of indices to select features from a vector column.",
        None,
        _indices_valid,
    )

    def get_indices(self):
        return self.get(self.INDICES)

    def set_indices(self, *values: int):
        return self.set(self.INDICES, list(values))

    def transform(self, *inputs):
        (df,) = inputs
        idx = np.asarray([int(i) for i in self.get_indices()])
        col = df.column(self.get_input_col())
        out = df.clone()
        if isinstance(col, np.ndarray):
            if idx.max() >= col.shape[1]:
                raise ValueError(
                    f"Index {idx.max()} out of bounds for vector of size {col.shape[1]}"
                )
            out.add_column(
                self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), col[:, idx]
            )
        else:
            new_col = []
            pos = {int(i): j for j, i in enumerate(idx)}
            for v in col:
                if isinstance(v, SparseVector):
                    keep = [j for j, i in enumerate(v.indices) if int(i) in pos]
                    new_idx = np.asarray([pos[int(v.indices[j])] for j in keep])
                    order = np.argsort(new_idx) if len(new_idx) else new_idx
                    new_col.append(
                        SparseVector(
                            len(idx),
                            new_idx[order] if len(new_idx) else new_idx,
                            v.values[keep][order] if len(keep) else np.zeros(0),
                        )
                    )
                else:
                    arr = v.to_array() if isinstance(v, Vector) else np.asarray(v)
                    new_col.append(arr[idx])
            out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), new_col)
        return out
