"""HashingTF.

Reference: ``flink-ml-lib/.../feature/hashingtf/HashingTF.java`` — map a list of
terms to a sparse term-frequency vector of ``numFeatures`` dims using the hashing
trick: index = nonNegativeMod(murmur3_32(0)(term)) (HashingTF.java:137-138,
161-193); counts, or 1s when ``binary``.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.ops import hashing
from flink_ml_tpu.params.param import BoolParam, IntParam, ParamValidators
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol

__all__ = ["HashingTF"]


def _hash(obj) -> int:
    """Ref HashingTF.hash:161 — type-dispatched guava murmur3_32(0)."""
    if obj is None:
        return 0
    if isinstance(obj, (bool, np.bool_)):
        return hashing.hash_int(1 if obj else 0)
    if isinstance(obj, (int, np.integer)):
        v = int(obj)
        if -(1 << 31) <= v < (1 << 31):
            return hashing.hash_int(v)
        return hashing.hash_long(v)
    if isinstance(obj, (float, np.floating)):
        return hashing.hash_long(
            int.from_bytes(np.float64(obj).tobytes(), "little", signed=False)
        )
    if isinstance(obj, str):
        return hashing.hash_unencoded_chars(obj)
    raise TypeError(f"HashingTF does not support type {type(obj).__name__} of input data.")


class HashingTF(Transformer, HasInputCol, HasOutputCol):
    """Ref HashingTF.java."""

    BINARY = BoolParam(
        "binary", "Whether each dimension of the output vector is binary or not.", False
    )
    NUM_FEATURES = IntParam(
        "numFeatures", "The number of features.", 1 << 18, ParamValidators.gt(0)
    )

    def get_binary(self) -> bool:
        return self.get(self.BINARY)

    def set_binary(self, value: bool):
        return self.set(self.BINARY, value)

    def get_num_features(self) -> int:
        return self.get(self.NUM_FEATURES)

    def set_num_features(self, value: int):
        return self.set(self.NUM_FEATURES, value)

    def transform(self, *inputs):
        (df,) = inputs
        num_features = self.get_num_features()
        binary = self.get_binary()
        col = df.column(self.get_input_col())
        vectors = []
        for terms in col:
            counts = {}
            for term in terms:
                idx = hashing.non_negative_mod(_hash(term), num_features)
                counts[idx] = 1 if (binary or idx not in counts) else counts[idx] + 1
            indices = np.asarray(sorted(counts), np.int64)
            values = np.asarray([counts[i] for i in indices], np.float64)
            vectors.append(SparseVector(num_features, indices, values))
        out = df.clone()
        out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), vectors)
        return out
