"""HashingTF.

Reference: ``flink-ml-lib/.../feature/hashingtf/HashingTF.java`` — map a list of
terms to a sparse term-frequency vector of ``numFeatures`` dims using the hashing
trick: index = nonNegativeMod(murmur3_32(0)(term)) (HashingTF.java:137-138,
161-193); counts, or 1s when ``binary``.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.ops import hashing
from flink_ml_tpu.ops.kernels import sparse_combine_fn, sparse_combine_kernel
from flink_ml_tpu.params.param import BoolParam, IntParam, ParamValidators
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec
from flink_ml_tpu.servable.sparse import (
    entries_names,
    pack_entry_rows,
    rebuild_sparse_column,
    sparse_names,
)

__all__ = ["HashingTF"]


def _hash(obj) -> int:
    """Ref HashingTF.hash:161 — type-dispatched guava murmur3_32(0)."""
    if obj is None:
        return 0
    if isinstance(obj, (bool, np.bool_)):
        return hashing.hash_int(1 if obj else 0)
    if isinstance(obj, (int, np.integer)):
        v = int(obj)
        if -(1 << 31) <= v < (1 << 31):
            return hashing.hash_int(v)
        return hashing.hash_long(v)
    if isinstance(obj, (float, np.floating)):
        return hashing.hash_long(
            int.from_bytes(np.float64(obj).tobytes(), "little", signed=False)
        )
    if isinstance(obj, str):
        return hashing.hash_unencoded_chars(obj)
    raise TypeError(f"HashingTF does not support type {type(obj).__name__} of input data.")


class HashingTF(Transformer, HasInputCol, HasOutputCol):
    """Ref HashingTF.java."""

    BINARY = BoolParam(
        "binary", "Whether each dimension of the output vector is binary or not.", False
    )
    NUM_FEATURES = IntParam(
        "numFeatures", "The number of features.", 1 << 18, ParamValidators.gt(0)
    )

    def get_binary(self) -> bool:
        return self.get(self.BINARY)

    def set_binary(self, value: bool):
        return self.set(self.BINARY, value)

    def get_num_features(self) -> int:
        return self.get(self.NUM_FEATURES)

    def set_num_features(self, value: int):
        return self.set(self.NUM_FEATURES, value)

    def _featurize(self, col):
        """Host half of the hashing trick: each row's terms hashed to raw
        (index, 1.0) entries, duplicates preserved — the device
        ``sparse_combine`` segment reduce turns them into sorted term counts.
        Shared by ``transform`` and the fused spec's host ingest, so both
        paths hash identically (ref HashingTF.java:137-138)."""
        num_features = self.get_num_features()
        rows = []
        lengths = []
        for terms in col:
            rows.append(
                [
                    (hashing.non_negative_mod(_hash(term), num_features), 1.0)
                    for term in terms
                ]
            )
            lengths.append(len(terms))
        return rows, lengths

    def transform(self, *inputs):
        (df,) = inputs
        num_features = self.get_num_features()
        in_col, out_col = self.get_input_col(), self.get_output_col()
        rows, lengths = self._featurize(df.column(in_col))
        arrays, _cap, _total = pack_entry_rows(out_col, rows, lengths)
        vn, idn, zn, _ln = entries_names(out_col)
        # Device segment reduce — the SAME ``sparse_combine`` body the fused
        # sparse spec composes: sort by term index, sum duplicate counts,
        # compact. Counts are small integers, exact in f32, so this equals
        # the reference's host dict counting bit for bit.
        values, ids, nnz = sparse_combine_kernel()(
            arrays[vn], arrays[idn], arrays[zn]
        )
        values = np.asarray(values)
        if self.get_binary():
            values = np.minimum(values, 1.0)
        vectors = rebuild_sparse_column(num_features, values, np.asarray(ids), np.asarray(nnz))
        out = df.clone()
        out.add_column(out_col, DataTypes.vector(BasicType.DOUBLE), vectors)
        return out

    def sparse_kernel_spec(self, known):
        """Sparse-convention spec (docs/sparse.md): the tokens column
        featurizes on the host (``_featurize`` — string hashing cannot run on
        device) into raw entries at a ladder cap; the device kernel is the
        ``sparse_combine`` segment reduce ``transform`` jits. The output is
        statically sparse — downstream specs (IDF, the logistic head) chain
        on-device without ever materializing SparseVectors."""
        num_features = self.get_num_features()
        binary = self.get_binary()
        in_col, out_col = self.get_input_col(), self.get_output_col()
        vn, idn, zn, _ln = entries_names(in_col)
        out_v, out_i, out_z = sparse_names(out_col)

        def host_ingest(df, cap, cap_max, truncate):
            rows, lengths = self._featurize(df.column(in_col))
            arrays, used_cap, total = pack_entry_rows(
                in_col, rows, lengths, cap=cap, cap_max=cap_max, truncate=truncate
            )
            return arrays, used_cap, total

        def kernel_fn(model, cols):
            values, ids, nnz = sparse_combine_fn(cols[vn], cols[idn], cols[zn])
            if binary:
                import jax.numpy as jnp

                values = jnp.minimum(values, 1.0)
            return {out_v: values, out_i: ids, out_z: nnz}

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
            input_kinds={in_col: "entries"},
            host_ingests={in_col: host_ingest},
            sparse_outputs={out_col: int(num_features)},
        )
