"""ElementwiseProduct.

Reference: ``flink-ml-lib/.../feature/elementwiseproduct/ElementwiseProduct.java`` —
Hadamard product of each input vector with the ``scalingVec`` param. Dense
columns run the shared ``elementwise_product`` kernel (``ops/kernels.py``);
sparse vectors stay sparse on the host path.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector, Vector
from flink_ml_tpu.ops.kernels import elementwise_product_fn, elementwise_product_kernel
from flink_ml_tpu.params.param import ParamValidators, VectorParam
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec

__all__ = ["ElementwiseProduct"]


class ElementwiseProduct(Transformer, HasInputCol, HasOutputCol):
    """Ref ElementwiseProduct.java."""

    SCALING_VEC = VectorParam(
        "scalingVec",
        "The scaling vector to multiply with input vectors using hadamard product.",
        None,
        ParamValidators.not_null(),
    )

    def get_scaling_vec(self):
        return self.get(self.SCALING_VEC)

    def set_scaling_vec(self, value):
        return self.set(self.SCALING_VEC, value)

    def _scaling_array(self) -> np.ndarray:
        scaling = self.get_scaling_vec()
        return scaling.to_array() if isinstance(scaling, Vector) else np.asarray(scaling)

    def transform(self, *inputs):
        (df,) = inputs
        s = self._scaling_array()
        col = df.column(self.get_input_col())
        out = df.clone()
        if isinstance(col, np.ndarray):
            vals = elementwise_product_kernel()(col.astype(np.float64), s)
            out.add_column(
                self.get_output_col(),
                DataTypes.vector(BasicType.DOUBLE),
                np.asarray(vals, np.float64),
            )
        else:  # sparse vectors stay sparse (product with stored values only)
            new_col = [
                SparseVector(v.size(), v.indices, v.values * s[v.indices])
                if isinstance(v, SparseVector)
                else v.to_array() * s
                for v in col
            ]
            out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), new_col)
        return out

    def kernel_spec(self):
        """Hadamard product as a fusable spec — ``elementwise_product_fn``
        with the scaling vector as a committed device buffer. List (sparse)
        columns stay per-stage, so the input ingests as ``dense``."""
        if self.get_scaling_vec() is None:
            return None
        in_col, out_col = self.get_input_col(), self.get_output_col()

        def kernel_fn(model, cols):
            return {out_col: elementwise_product_fn(cols[in_col], model["scaling"])}

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={"scaling": np.asarray(self._scaling_array(), np.float32)},
            kernel_fn=kernel_fn,
            input_kinds={in_col: "dense"},
            elementwise=True,  # Hadamard product: no FP accumulation
            fusion_op="elementwise_product",  # megakernel-safe
        )
