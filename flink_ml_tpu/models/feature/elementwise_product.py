"""ElementwiseProduct.

Reference: ``flink-ml-lib/.../feature/elementwiseproduct/ElementwiseProduct.java`` —
Hadamard product of each input vector with the ``scalingVec`` param.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector, Vector
from flink_ml_tpu.params.param import ParamValidators, VectorParam
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol

__all__ = ["ElementwiseProduct"]


class ElementwiseProduct(Transformer, HasInputCol, HasOutputCol):
    """Ref ElementwiseProduct.java."""

    SCALING_VEC = VectorParam(
        "scalingVec",
        "The scaling vector to multiply with input vectors using hadamard product.",
        None,
        ParamValidators.not_null(),
    )

    def get_scaling_vec(self):
        return self.get(self.SCALING_VEC)

    def set_scaling_vec(self, value):
        return self.set(self.SCALING_VEC, value)

    def transform(self, *inputs):
        (df,) = inputs
        scaling = self.get_scaling_vec()
        s = scaling.to_array() if isinstance(scaling, Vector) else np.asarray(scaling)
        col = df.column(self.get_input_col())
        out = df.clone()
        if isinstance(col, np.ndarray):
            out.add_column(
                self.get_output_col(),
                DataTypes.vector(BasicType.DOUBLE),
                col.astype(np.float64) * s[None, :],
            )
        else:  # sparse vectors stay sparse (product with stored values only)
            new_col = [
                SparseVector(v.size(), v.indices, v.values * s[v.indices])
                if isinstance(v, SparseVector)
                else v.to_array() * s
                for v in col
            ]
            out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), new_col)
        return out
