"""Discrete cosine transform.

Reference: ``flink-ml-lib/.../feature/dct/DCT.java`` — orthonormal DCT-II of the
input vector (inverse = DCT-III when ``inverse``).

TPU-native: the transform is a [d, d] cosine-basis matmul over the whole batch —
an MXU op — instead of the reference's per-row FFT library call. The basis is
built once per dimension and cached.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.params.param import BoolParam
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol

__all__ = ["DCT"]


@functools.cache
def _dct_matrix(d: int) -> np.ndarray:
    """Orthonormal DCT-II basis: B[k, j] = s_k cos(pi (j + 1/2) k / d)."""
    j = np.arange(d)
    k = np.arange(d)[:, None]
    basis = np.cos(np.pi * (j + 0.5) * k / d)
    scale = np.full(d, np.sqrt(2.0 / d))
    scale[0] = np.sqrt(1.0 / d)
    return (basis * scale[:, None]).astype(np.float64)


@functools.cache
def _kernel(d: int, inverse: bool):
    mat = jnp.asarray(_dct_matrix(d))

    @jax.jit
    def forward(X):
        # orthonormal: inverse is the transpose
        return X @ (mat if inverse else mat.T)

    return forward


class DCT(Transformer, HasInputCol, HasOutputCol):
    """Ref DCT.java."""

    INVERSE = BoolParam(
        "inverse", "Whether to perform the inverse DCT (true) or forward DCT (false).", False
    )

    def get_inverse(self) -> bool:
        return self.get(self.INVERSE)

    def set_inverse(self, value: bool):
        return self.set(self.INVERSE, value)

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_input_col())
        vals = _kernel(X.shape[1], self.get_inverse())(X.astype(np.float64))
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(vals, np.float64),
        )
        return out
