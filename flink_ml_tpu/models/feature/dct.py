"""Discrete cosine transform.

Reference: ``flink-ml-lib/.../feature/dct/DCT.java`` — orthonormal DCT-II of the
input vector (inverse = DCT-III when ``inverse``).

TPU-native: the transform is a [d, d] cosine-basis matmul over the whole batch —
an MXU op — instead of the reference's per-row FFT library call. The basis and
the matmul are the shared ``dct_basis`` / ``dct`` kernel (``ops/kernels.py``);
the basis is built once per dimension and burned into the compiled program as a
constant by both the per-stage kernel and the fused spec.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.ops.kernels import dct_basis, dct_fn, dct_kernel
from flink_ml_tpu.params.param import BoolParam
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec

__all__ = ["DCT"]


class DCT(Transformer, HasInputCol, HasOutputCol):
    """Ref DCT.java."""

    INVERSE = BoolParam(
        "inverse", "Whether to perform the inverse DCT (true) or forward DCT (false).", False
    )

    def get_inverse(self) -> bool:
        return self.get(self.INVERSE)

    def set_inverse(self, value: bool):
        return self.set(self.INVERSE, value)

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_input_col())
        vals = dct_kernel(X.shape[1], bool(self.get_inverse()))(X.astype(np.float64))
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(vals, np.float64),
        )
        return out

    def kernel_spec(self):
        """Basis matmul as a fusable spec — ``dct_fn`` with the per-dimension
        basis resolved at trace time (static width) and embedded as the same
        compile-time constant ``transform``'s kernel uses."""
        in_col, out_col = self.get_input_col(), self.get_output_col()
        inverse = bool(self.get_inverse())

        def kernel_fn(model, cols):
            X = cols[in_col]
            return {out_col: dct_fn(X, dct_basis(X.shape[1], inverse))}

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
        )
