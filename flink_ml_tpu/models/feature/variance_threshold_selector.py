"""VarianceThresholdSelector.

Reference: ``flink-ml-lib/.../feature/variancethresholdselector/`` — remove
features whose sample variance is not greater than ``varianceThreshold``
(default 0: keep only non-constant features).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.params.param import FloatParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol

__all__ = ["VarianceThresholdSelector", "VarianceThresholdSelectorModel"]


class _VtsParams(HasInputCol, HasOutputCol):
    VARIANCE_THRESHOLD = FloatParam(
        "varianceThreshold",
        "Features with a variance not greater than this threshold will be removed.",
        0.0,
        ParamValidators.gt_eq(0),
    )

    def get_variance_threshold(self) -> float:
        return self.get(self.VARIANCE_THRESHOLD)

    def set_variance_threshold(self, value: float):
        return self.set(self.VARIANCE_THRESHOLD, value)


class VarianceThresholdSelectorModel(ModelArraysMixin, Model, _VtsParams):
    """Ref VarianceThresholdSelectorModel.java — keeps ``indices``."""

    _MODEL_ARRAY_NAMES = ("indices",)

    def __init__(self):
        super().__init__()
        self.indices: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            X[:, self.indices.astype(np.int64)],
        )
        return out


class VarianceThresholdSelector(Estimator, _VtsParams):
    """Ref VarianceThresholdSelector.java."""

    def fit(self, *inputs) -> VarianceThresholdSelectorModel:
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        if len(X) == 0:
            raise RuntimeError("The training set is empty.")
        variance = X.var(axis=0, ddof=1) if len(X) > 1 else np.zeros(X.shape[1])
        model = VarianceThresholdSelectorModel()
        update_existing_params(model, self)
        model.indices = np.nonzero(variance > self.get_variance_threshold())[0].astype(
            np.int64
        )
        return model
