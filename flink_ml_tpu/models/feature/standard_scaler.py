"""StandardScaler (batch) and OnlineStandardScaler (windowed, versioned).

Reference: ``flink-ml-lib/.../feature/standardscaler/`` —
``StandardScaler.java`` (fit: per-partition [sum, squaredSum, count] then a
parallelism-1 merge; mean = sum/n, std = sqrt((sqSum − n·mean²)/(n−1)), std = 0
when n == 1; empty input → "The training set is empty");
``StandardScalerModel.java:60-97`` (transform: subtract mean if withMean, multiply
by 1/std — 0 for zero std — if withStd);
``OnlineStandardScaler.java`` (cumulative sums across windows; one model version
per window, version starting at 0; event-time window max timestamp recorded);
``OnlineStandardScalerModel.java:206-211`` (model-version gauges; version column).

TPU-native: the fit statistics are one jit'd masked reduction over the
mesh-sharded dataset (psum inserted by XLA); transform is a fused elementwise
kernel. Deviation: the online model serves with the latest arrived version (the
reference joins rows to versions by event time when event-time windows are used;
max-allowed-model-delay gating is recorded but not enforced row-wise).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Estimator
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.iteration import DeviceDataCache
from flink_ml_tpu.iteration.stream import window_stream
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.models.online import OnlineModelBase, SnapshotDriver, as_batch_stream
from flink_ml_tpu.api.core import Model
from flink_ml_tpu.params.param import BoolParam, WithParams, update_existing_params
from flink_ml_tpu.params.shared import (
    HasInputCol,
    HasMaxAllowedModelDelayMs,
    HasModelVersionCol,
    HasOutputCol,
    HasWindows,
)
from flink_ml_tpu.parallel.mesh import get_mesh_context

__all__ = [
    "StandardScaler",
    "StandardScalerModel",
    "OnlineStandardScaler",
    "OnlineStandardScalerModel",
]


class _ScalerParams(HasInputCol, HasOutputCol):
    """Ref StandardScalerParams — withMean (false), withStd (true)."""

    WITH_MEAN = BoolParam("withMean", "Whether centers the data with mean before scaling.", False)
    WITH_STD = BoolParam("withStd", "Whether scales the data with standard deviation.", True)

    def get_with_mean(self) -> bool:
        return self.get(self.WITH_MEAN)

    def set_with_mean(self, value: bool):
        return self.set(self.WITH_MEAN, value)

    def get_with_std(self) -> bool:
        return self.get(self.WITH_STD)

    def set_with_std(self, value: bool):
        return self.set(self.WITH_STD, value)


def _mean_std(sum_: np.ndarray, sq_sum: np.ndarray, n: float):
    """Shared mean/std finalization (BuildModelOperator.endInput math)."""
    mean = sum_ / n
    if n > 1:
        var = (sq_sum - n * mean * mean) / (n - 1)
        std = np.sqrt(np.maximum(var, 0.0))
    else:
        std = np.zeros_like(mean)
    return mean, std


@functools.cache
def _stats_kernel():
    """Masked mean + *centered* second moment in one program.

    The reference finalizes var = (sqSum − n·mean²)/(n−1) in Java doubles
    (StandardScaler.java BuildModelOperator); in f32 (TPU has no f64) that
    formula cancels catastrophically when |mean| ≫ std, so the kernel centers
    before squaring: var = Σ mask·(x−mean)² / (n−1). Same answer, stable.
    """

    @jax.jit
    def stats(X, mask):
        n = jnp.sum(mask)
        mean = jnp.sum(X * mask[:, None], axis=0) / jnp.maximum(n, 1.0)
        c = (X - mean[None, :]) * mask[:, None]
        return mean, jnp.sum(c * c, axis=0), n

    return stats


@functools.cache
def _transform_kernel(with_mean: bool, with_std: bool):
    @jax.jit
    def kernel(X, mean, inv_std):
        out = X
        if with_mean:
            out = out - mean[None, :]
        if with_std:
            out = out * inv_std[None, :]
        return out

    return kernel


class _ScalerTransformMixin(_ScalerParams):
    """Shared transform over (mean, std) state — used by both the batch and the
    online model (the reference's PredictOutputFunction math,
    StandardScalerModel.java:60-97)."""

    mean: Optional[np.ndarray]
    std: Optional[np.ndarray]

    def _transform_df(self, df: DataFrame) -> DataFrame:
        X = df.vectors(self.get_input_col()).astype(np.float32)
        std = np.asarray(self.std, np.float32)
        inv_std = np.where(std == 0.0, 0.0, 1.0 / np.where(std == 0.0, 1.0, std))
        out_vals = _transform_kernel(self.get_with_mean(), self.get_with_std())(
            X, np.asarray(self.mean, np.float32), inv_std
        )
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(out_vals, np.float64),
        )
        return out


class StandardScalerModel(ModelArraysMixin, Model, _ScalerTransformMixin):
    """Ref StandardScalerModel.java."""

    _MODEL_ARRAY_NAMES = ("mean", "std")

    def __init__(self):
        super().__init__()
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        return self._transform_df(df)


class StandardScaler(Estimator, _ScalerParams):
    """Ref StandardScaler.java."""

    def fit(self, *inputs) -> StandardScalerModel:
        (df,) = inputs
        if len(df) == 0:
            raise RuntimeError("The training set is empty.")
        X = df.vectors(self.get_input_col()).astype(np.float32)
        ctx = get_mesh_context()
        cache = DeviceDataCache({"x": X}, ctx=ctx)
        mean, sq_c, n = _stats_kernel()(cache["x"], cache.mask)
        mean = np.asarray(mean, np.float64)
        n = float(n)
        if n > 1:
            std = np.sqrt(np.maximum(np.asarray(sq_c, np.float64) / (n - 1), 0.0))
        else:
            std = np.zeros_like(mean)
        model = StandardScalerModel()
        update_existing_params(model, self)
        model.mean, model.std = mean, std
        return model


class OnlineStandardScalerModel(
    OnlineModelBase, _ScalerTransformMixin, HasModelVersionCol, HasMaxAllowedModelDelayMs
):
    """Ref OnlineStandardScalerModel.java — versioned serving with gauges."""

    _MODEL_ARRAY_NAMES = ("mean", "std")

    def __init__(self):
        super().__init__()
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def _apply_snapshot(self, payload) -> None:
        self.mean, self.std = (np.asarray(a) for a in payload)

    def transform(self, *inputs):
        (df,) = inputs
        if self.mean is None:
            raise RuntimeError("no model version has arrived yet; advance() the model")
        out = self._transform_df(df)
        out.add_column(
            self.get_model_version_col(),
            DataTypes.LONG,
            np.full(len(df), self.model_version, np.int64),
        )
        return out


class OnlineStandardScaler(
    Estimator, _ScalerParams, HasWindows, HasModelVersionCol, HasMaxAllowedModelDelayMs
):
    """Ref OnlineStandardScaler.java — one model version per window over cumulative
    statistics. Versions start at 0 on the first window (the reference emits the
    model computed *including* the window, versioned before increment)."""

    TIMESTAMP_COL = "__timestamp__"  # column consulted by event-time windows

    def fit(self, *inputs) -> OnlineStandardScalerModel:
        (data,) = inputs
        input_col = self.get_input_col()
        windows = self.get_windows()

        stream, bounded = as_batch_stream(data, None)
        if bounded:
            windowed = window_stream(stream, windows, timestamp_column=self.TIMESTAMP_COL)
        else:
            # Feedable unbounded stream: each arriving batch is one training window
            # (window_stream is a generator and would be killed by a propagating
            # StreamDry; stepwise feeding already defines the window boundaries).
            windowed = stream

        def train_step(state, batch):
            s, sq, n = state
            X = np.asarray(batch[input_col], np.float64)
            if X.ndim == 1:
                X = X[:, None]
            if s is None:
                s = np.zeros(X.shape[1])
                sq = np.zeros(X.shape[1])
            s = s + X.sum(axis=0)
            sq = sq + (X * X).sum(axis=0)
            n = n + X.shape[0]
            mean, std = _mean_std(s, sq, n)
            return (s, sq, n), (mean, std)

        driver = SnapshotDriver(windowed, train_step, (None, None, 0))
        model = OnlineStandardScalerModel()
        update_existing_params(model, self)
        model.model_version = -1  # first applied snapshot becomes version 0
        model._attach_stream(_VersionFromZero(driver))
        if bounded:
            model.advance()
        return model


class _VersionFromZero:
    """Adapter: SnapshotDriver counts 1-based; OnlineStandardScaler versions are
    0-based (OnlineStandardScaler.java modelVersion starts at 0)."""

    def __init__(self, driver: SnapshotDriver):
        self._driver = driver

    def __iter__(self):
        return self

    def __next__(self):
        version, payload = next(self._driver)
        return version - 1, payload
