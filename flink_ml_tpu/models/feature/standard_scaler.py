"""StandardScaler (batch) and OnlineStandardScaler (windowed, versioned).

Reference: ``flink-ml-lib/.../feature/standardscaler/`` —
``StandardScaler.java`` (fit: per-partition [sum, squaredSum, count] then a
parallelism-1 merge; mean = sum/n, std = sqrt((sqSum − n·mean²)/(n−1)), std = 0
when n == 1; empty input → "The training set is empty");
``StandardScalerModel.java:60-97`` (transform: subtract mean if withMean, multiply
by 1/std — 0 for zero std — if withStd);
``OnlineStandardScaler.java`` (cumulative sums across windows; one model version
per window, version starting at 0; event-time window max timestamp recorded);
``OnlineStandardScalerModel.java:206-211`` (model-version gauges; version column).

TPU-native: the fit statistics are one jit'd masked reduction over the
mesh-sharded dataset (psum inserted by XLA); transform is a fused elementwise
kernel. Model-delay semantics (OnlineStandardScalerModel.processElement1): a
row with event time ``t`` may only be served by a model whose training-window
timestamp satisfies ``t - maxAllowedModelDelayMs <= modelTimestamp``; too-new
rows are buffered until a fresh-enough version arrives. The single-controller
collapse of that two-input operator: ``transform`` serves each row with the
*earliest* fresh-enough version, pulling further versions from the training
stream on demand, and parks still-unservable rows in ``pending`` (the
``bufferedPointsState`` role) for a later ``serve_pending()``.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Estimator
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.iteration import DeviceDataCache
from flink_ml_tpu.iteration.stream import window_stream
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.models.online import (
    HasCheckpointing,
    OnlineModelBase,
    SnapshotDriver,
    as_batch_stream,
)
from flink_ml_tpu.api.core import Model
from flink_ml_tpu.params.param import BoolParam, WithParams, update_existing_params
from flink_ml_tpu.params.shared import (
    HasInputCol,
    HasMaxAllowedModelDelayMs,
    HasModelVersionCol,
    HasOutputCol,
    HasWindows,
)
from flink_ml_tpu.parallel.mesh import get_mesh_context

__all__ = [
    "StandardScaler",
    "StandardScalerModel",
    "OnlineStandardScaler",
    "OnlineStandardScalerModel",
]


class _ScalerParams(HasInputCol, HasOutputCol):
    """Ref StandardScalerParams — withMean (false), withStd (true)."""

    WITH_MEAN = BoolParam("withMean", "Whether centers the data with mean before scaling.", False)
    WITH_STD = BoolParam("withStd", "Whether scales the data with standard deviation.", True)

    def get_with_mean(self) -> bool:
        return self.get(self.WITH_MEAN)

    def set_with_mean(self, value: bool):
        return self.set(self.WITH_MEAN, value)

    def get_with_std(self) -> bool:
        return self.get(self.WITH_STD)

    def set_with_std(self, value: bool):
        return self.set(self.WITH_STD, value)


def _mean_std(sum_: np.ndarray, sq_sum: np.ndarray, n: float):
    """Shared mean/std finalization (BuildModelOperator.endInput math)."""
    mean = sum_ / n
    if n > 1:
        var = (sq_sum - n * mean * mean) / (n - 1)
        std = np.sqrt(np.maximum(var, 0.0))
    else:
        std = np.zeros_like(mean)
    return mean, std


@functools.cache
def _stats_kernel():
    """Masked mean + *centered* second moment in one program.

    The reference finalizes var = (sqSum − n·mean²)/(n−1) in Java doubles
    (StandardScaler.java BuildModelOperator); in f32 (TPU has no f64) that
    formula cancels catastrophically when |mean| ≫ std, so the kernel centers
    before squaring: var = Σ mask·(x−mean)² / (n−1). Same answer, stable.
    """

    @jax.jit
    def stats(X, mask):
        n = jnp.sum(mask)
        mean = jnp.sum(X * mask[:, None], axis=0) / jnp.maximum(n, 1.0)
        c = (X - mean[None, :]) * mask[:, None]
        return mean, jnp.sum(c * c, axis=0), n

    return stats


# Shared with the runtime-free StandardScalerModelServable — one jit cache
# entry per (with_mean, with_std) across the batch, online and serving paths.
from flink_ml_tpu.ops.kernels import scale_fn, scale_kernel as _transform_kernel


class _ScalerTransformMixin(_ScalerParams):
    """Shared transform over (mean, std) state — used by both the batch and the
    online model (the reference's PredictOutputFunction math,
    StandardScalerModel.java:60-97)."""

    mean: Optional[np.ndarray]
    std: Optional[np.ndarray]

    def _transform_df(self, df: DataFrame) -> DataFrame:
        X = df.vectors(self.get_input_col()).astype(np.float32)
        std = np.asarray(self.std, np.float32)
        inv_std = np.where(std == 0.0, 0.0, 1.0 / np.where(std == 0.0, 1.0, std))
        out_vals = _transform_kernel(self.get_with_mean(), self.get_with_std())(
            X, np.asarray(self.mean, np.float32), inv_std
        )
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(out_vals, np.float64),
        )
        return out


class StandardScalerModel(ModelArraysMixin, Model, _ScalerTransformMixin):
    """Ref StandardScalerModel.java."""

    _MODEL_ARRAY_NAMES = ("mean", "std")

    def __init__(self):
        super().__init__()
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    @classmethod
    def load_servable(cls, path: str):
        """Runtime-free replica from this model's save dir (ref the
        LogisticRegressionModel → LogisticRegressionModelServable pairing)."""
        from flink_ml_tpu.servable.lib import StandardScalerModelServable

        return StandardScalerModelServable.load_servable(path)

    def transform(self, *inputs):
        (df,) = inputs
        return self._transform_df(df)

    def kernel_spec(self):
        """Standardization as a fusable spec for the batch fast path — the
        same ``scale_fn`` body ``_transform_df``'s jitted kernel wraps, with
        mean and precomputed inverse std as committed device buffers
        (mirrors StandardScalerModelServable.kernel_spec)."""
        if self.mean is None:
            raise RuntimeError("model must be fit/loaded before kernel_spec")
        from flink_ml_tpu.servable.kernel_spec import KernelSpec

        in_col, out_col = self.get_input_col(), self.get_output_col()
        with_mean, with_std = self.get_with_mean(), self.get_with_std()
        std = np.asarray(self.std, np.float32)
        inv_std = np.where(std == 0.0, 0.0, 1.0 / np.where(std == 0.0, 1.0, std))

        def kernel_fn(model, cols):
            return {
                out_col: scale_fn(
                    cols[in_col],
                    model["mean"],
                    model["inv_std"],
                    with_mean=with_mean,
                    with_std=with_std,
                )
            }

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={"mean": np.asarray(self.mean, np.float32), "inv_std": inv_std},
            kernel_fn=kernel_fn,
            elementwise=True,  # shift + scale: no FP accumulation
            fusion_op="scale",  # megakernel-safe (docs/fusion.md vocabulary)
        )


class StandardScaler(Estimator, _ScalerParams):
    """Ref StandardScaler.java."""

    def fit(self, *inputs) -> StandardScalerModel:
        (df,) = inputs
        if len(df) == 0:
            raise RuntimeError("The training set is empty.")
        X = df.vectors(self.get_input_col()).astype(np.float32)
        ctx = get_mesh_context()
        cache = DeviceDataCache({"x": X}, ctx=ctx)
        mean, sq_c, n = _stats_kernel()(cache["x"], cache.mask)
        mean = np.asarray(mean, np.float64)
        n = float(n)
        if n > 1:
            std = np.sqrt(np.maximum(np.asarray(sq_c, np.float64) / (n - 1), 0.0))
        else:
            std = np.zeros_like(mean)
        model = StandardScalerModel()
        update_existing_params(model, self)
        model.mean, model.std = mean, std
        return model


def _concat_frames(frames):
    """Row-concatenate DataFrames with identical schemas (DataFrame.concat)."""
    return frames[0] if len(frames) == 1 else DataFrame.concat(frames)


class OnlineStandardScalerModel(
    OnlineModelBase, _ScalerTransformMixin, HasModelVersionCol, HasMaxAllowedModelDelayMs
):
    """Ref OnlineStandardScalerModel.java — versioned serving with gauges and
    row-wise max-allowed-model-delay gating against event timestamps."""

    _MODEL_ARRAY_NAMES = ("mean", "std")

    def __init__(self):
        super().__init__()
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None
        self.model_timestamp: float = float("-inf")
        self._pending: list = []  # the bufferedPointsState role

    def _apply_snapshot(self, payload) -> None:
        mean, std, ts = payload
        self.mean = np.asarray(mean)
        self.std = np.asarray(std)
        self.model_timestamp = float(ts)

    def _serve(self, df: DataFrame) -> DataFrame:
        out = self._transform_df(df)
        out.add_column(
            self.get_model_version_col(),
            DataTypes.LONG,
            np.full(len(df), self.model_version, np.int64),
        )
        return out

    @property
    def pending_rows(self) -> int:
        """Rows buffered because no fresh-enough model version has arrived."""
        return sum(len(f) for f in self._pending)

    # -- persistence: buffered rows are state (bufferedPointsState is part of
    # the reference operator's checkpoint) and must survive save/load --------
    def save(self, path: str) -> None:
        import os

        super().save(path)
        for i, frame in enumerate(self._pending):
            cols = {}
            for name in frame.get_column_names():
                col = frame.column(name)
                if isinstance(col, np.ndarray):
                    cols[name] = col
                else:  # ragged/list column (e.g. SparseVector cells): keep the
                    # objects — np.asarray would densify via the sequence protocol
                    arr = np.empty(len(col), dtype=object)
                    arr[:] = col
                    cols[name] = arr
            np.savez(os.path.join(path, f"pending{i}.npz"), **cols)

    @classmethod
    def load(cls, path: str):
        import os

        model = super().load(path)
        i = 0
        while os.path.exists(os.path.join(path, f"pending{i}.npz")):
            # allow_pickle: object columns (e.g. SparseVector cells) round-trip
            # through our own checkpoint files; lists rehydrate as list columns.
            with np.load(os.path.join(path, f"pending{i}.npz"), allow_pickle=True) as z:
                cols = [
                    list(z[k]) if z[k].dtype == object else z[k] for k in z.files
                ]
                model._pending.append(DataFrame(list(z.files), None, cols))
            i += 1
        return model

    def serve_pending(self) -> Optional[DataFrame]:
        """Try to serve buffered rows (after new versions arrived); returns the
        served rows, or None if nothing became servable."""
        if not self._pending:
            return None
        buffered, self._pending = self._pending, []
        outs = [self.transform(f) for f in buffered]
        outs = [o for o in outs if len(o)]
        return _concat_frames(outs) if outs else None

    def transform(self, *inputs):
        (df,) = inputs
        if self.mean is None:
            raise RuntimeError("no model version has arrived yet; advance() the model")
        if TIMESTAMP_COL not in df.get_column_names():
            return self._serve(df)  # no event time -> no gating (ref: timestamps
            # only exist on event-time streams)
        delay = float(self.get_max_allowed_model_delay_ms())
        ts = df.scalars(TIMESTAMP_COL)
        remaining = np.arange(len(df))
        parts = []
        while remaining.size:
            servable = ts[remaining] - delay <= self.model_timestamp
            if servable.any():
                idx = remaining[servable]
                parts.append((idx, self._serve(df.take(idx))))
                remaining = remaining[~servable]
            if not remaining.size:
                break
            if self.advance(1) == 0:
                # training stream dry/ended: buffer the too-new rows
                self._pending.append(df.take(remaining))
                break
        if not parts:  # nothing servable yet: empty output, right schema
            return self._serve(df.take(np.asarray([], np.int64)))
        order = np.argsort(np.concatenate([idx for idx, _ in parts]), kind="stable")
        return _concat_frames([out for _, out in parts]).take(order)


TIMESTAMP_COL = "__timestamp__"  # event-time column (windows + delay gating)


class OnlineStandardScaler(
    Estimator,
    _ScalerParams,
    HasWindows,
    HasModelVersionCol,
    HasMaxAllowedModelDelayMs,
    HasCheckpointing,
):
    """Ref OnlineStandardScaler.java — one model version per window over cumulative
    statistics. Versions start at 0 on the first window (the reference emits the
    model computed *including* the window, versioned before increment)."""

    TIMESTAMP_COL = TIMESTAMP_COL

    def fit(self, *inputs) -> OnlineStandardScalerModel:
        (data,) = inputs
        input_col = self.get_input_col()
        windows = self.get_windows()

        stream, bounded = as_batch_stream(data, None)
        if bounded:
            windowed = window_stream(stream, windows, timestamp_column=self.TIMESTAMP_COL)
        else:
            # Feedable unbounded stream: window_stream is a generator and would
            # be killed by a propagating StreamDry, so event-time batches are
            # split window-by-window with a StreamDry-safe iterator; other
            # window kinds (count, processing-time, global) treat each arriving
            # batch as one training window — stepwise feeding defines the
            # processing-time boundaries, so splitting by the event-time column
            # would be the wrong time domain.
            from flink_ml_tpu.ops.windows import EventTimeTumblingWindows

            if isinstance(windows, EventTimeTumblingWindows):
                windowed = _BatchWindowSplitter(stream, windows.size_ms, self.TIMESTAMP_COL)
            else:
                windowed = stream

        def train_step(state, batch):
            s, sq, n = state
            X = np.asarray(batch[input_col], np.float64)
            if X.ndim == 1:
                X = X[:, None]
            if s is None:
                s = np.zeros(X.shape[1])
                sq = np.zeros(X.shape[1])
            s = s + X.sum(axis=0)
            sq = sq + (X * X).sum(axis=0)
            n = n + X.shape[0]
            mean, std = _mean_std(s, sq, n)
            # Model timestamp = the training window's max event time
            # (StandardScalerModelData.timestamp); without event time the
            # model is always "fresh" (no gating possible or needed).
            ts_col = batch.get(TIMESTAMP_COL)
            w_ts = (
                float(np.max(ts_col)) if ts_col is not None and len(ts_col) else float("inf")
            )
            return (s, sq, n), (mean, std, w_ts)

        # The scaler's payload carries the window timestamp, which is not in
        # the training state — snapshots keep the payload explicitly.
        driver = self._snapshot_driver(windowed, train_step, (None, None, 0))
        model = OnlineStandardScalerModel()
        update_existing_params(model, self)
        model.model_version = -1  # first applied snapshot becomes version 0
        driver.resume_into(model, version_offset=-1)  # 0-based versions
        model._attach_stream(_VersionFromZero(driver))
        if bounded:
            model.advance()
        return model


class _BatchWindowSplitter:
    """Split each arriving batch into per-tumbling-window sub-batches.

    A plain object (not a generator) so a ``StreamDry`` from the feedable
    stream propagates without killing iteration state. Windows inside one
    added batch emit in timestamp order; windows never merge across added
    batches (each add is assumed watermark-complete, the stepwise analogue of
    the reference's event-time window firing).
    """

    def __init__(self, stream, size_ms: float, ts_col: str):
        self._stream = stream
        self._size = size_ms
        self._ts_col = ts_col
        self._queue: list = []

    def __iter__(self):
        return self

    def __next__(self):
        from flink_ml_tpu.iteration.stream import split_by_tumbling_window

        while not self._queue:
            batch = next(self._stream)  # may raise StopIteration / StreamDry
            ts = batch.get(self._ts_col)
            if ts is None:
                return batch
            self._queue.extend(
                part for _, part in split_by_tumbling_window(batch, self._size, ts)
            )
        return self._queue.pop(0)


class _VersionFromZero:
    """Adapter: SnapshotDriver counts 1-based; OnlineStandardScaler versions are
    0-based (OnlineStandardScaler.java modelVersion starts at 0)."""

    def __init__(self, driver: SnapshotDriver):
        self._driver = driver

    def __iter__(self):
        return self

    def __next__(self):
        version, payload = next(self._driver)
        return version - 1, payload
