"""Feature engineering stages. Ref flink-ml-lib/.../ml/feature/ (33 stages)."""
