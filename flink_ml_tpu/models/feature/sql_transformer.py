"""SQLTransformer.

Reference: ``flink-ml-lib/.../feature/sqltransformer/SQLTransformer.java`` —
executes a SQL statement against the input table; ``__THIS__`` is the placeholder
for the input (e.g. ``SELECT *, (v1 + v2) AS v3 FROM __THIS__``).

The reference delegates to Flink's full SQL planner. Here a documented subset is
evaluated columnar over numpy:
  SELECT <expr> [AS alias][, ...] FROM __THIS__ [WHERE <cond>] [GROUP BY col[, ...]]
with ``*`` expansion, arithmetic/comparison/boolean operators (SQL ``=``, AND, OR,
NOT), and the scalar functions ABS, SQRT, EXP, LOG, POW, MIN, MAX (two-argument
MIN/MAX are elementwise, like SQL LEAST/GREATEST).

Aggregates — COUNT(*), COUNT(expr), SUM, AVG, and single-argument MIN/MAX
(round 5) — are supported two ways:

- **Global** (no GROUP BY): every select item must be an expression of
  aggregates (the output is one row; per-row columns may appear only
  inside an aggregate). Over an empty (filtered) table: COUNT = 0,
  SUM = 0.0, and MIN/MAX/AVG = NaN (this subset has no NULL).
- **GROUP BY col[, col...]** (round 5, second pass): keys are bare column
  names; each select item is either a group-key column (optionally
  aliased) or an aggregate expression, evaluated per group — group keys
  may also appear OUTSIDE aggregates within an aggregate item
  (``SUM(v) + cat``), as in real SQL. Output rows follow the keys'
  first-appearance order (deterministic; the reference's planner makes no
  order promise either).

In both forms WHERE filters before aggregation (aggregates are not
allowed inside WHERE — no HAVING), and aggregates compose with arithmetic
(``SUM(v1) / COUNT(*)``). Joins, ORDER BY, HAVING, and window clauses are
not supported and raise ValueError.
"""
from __future__ import annotations

import re
from typing import Dict, List

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.params.param import ParamValidators, StringParam

__all__ = ["SQLTransformer"]

_FUNCS = {
    "ABS": np.abs,
    "SQRT": np.sqrt,
    "EXP": np.exp,
    "LOG": np.log,
    "POW": np.power,
    "MIN": np.minimum,
    "MAX": np.maximum,
}


def _split_top_level_commas(s: str) -> List[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur).strip())
    return parts


def _split_top_level_keyword(s: str, keyword: str) -> List[str]:
    """Split on a keyword at paren depth 0 (case-insensitive, word-bounded)."""
    pattern = re.compile(rf"\b{keyword}\b", re.I)
    parts, depth, last, i = [], 0, 0, 0
    while i < len(s):
        ch = s[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif depth == 0:
            m = pattern.match(s, i)
            if m:
                parts.append(s[last : i])
                i = m.end()
                last = i
                continue
        i += 1
    parts.append(s[last:])
    return parts


_AGG_REDUCERS = {
    "COUNT": len,
    "SUM": np.sum,
    "AVG": np.mean,
    "MIN": np.min,
    "MAX": np.max,
}


def _find_aggregate_calls(expr: str):
    """Locate aggregate calls ``FN(...)`` with balanced parens. Returns
    ``[(start, end, fn_name, inner)]``. Two-argument MIN/MAX are the
    documented elementwise scalars (SQL LEAST/GREATEST), not aggregates."""
    calls = []
    for m in re.finditer(r"\b(COUNT|SUM|AVG|MIN|MAX)\s*\(", expr, re.I):
        depth, i = 1, m.end()
        while i < len(expr) and depth:
            if expr[i] == "(":
                depth += 1
            elif expr[i] == ")":
                depth -= 1
            i += 1
        if depth:
            raise ValueError(f"SQLTransformer: unbalanced parens in {expr!r}")
        inner = expr[m.end() : i - 1].strip()
        fn = m.group(1).upper()
        if fn in ("MIN", "MAX") and len(_split_top_level_commas(inner)) > 1:
            continue  # elementwise two-argument form
        calls.append((m.start(), i, fn, inner))
    return calls


class _GlobalReducer:
    """Whole-table aggregation: scalars out (the one-row result)."""

    def __init__(self, n_rows: int):
        self.n_rows = n_rows

    def count(self):
        return self.n_rows

    def reduce(self, fn: str, col: np.ndarray):
        col = np.atleast_1d(col)
        if col.size == 0:
            # empty filtered table: SUM = 0.0, MIN/MAX/AVG = NaN (no NULL
            # in this subset) — defined results, not numpy errors
            return 0.0 if fn == "SUM" else float("nan")
        return _AGG_REDUCERS[fn](col)


class _GroupReducer:
    """Per-group aggregation via sorted-order ``reduceat``: vectors of one
    value per group, groups in key first-appearance order."""

    def __init__(self, gid: np.ndarray, n_groups: int):
        self.n_rows = gid.shape[0]
        self.order = np.argsort(gid, kind="stable")
        self.counts = np.bincount(gid, minlength=n_groups)
        self.starts = (
            np.concatenate(([0], np.cumsum(self.counts)[:-1]))
            if n_groups
            else np.zeros(0, np.int64)  # zero rows -> zero groups
        )

    def count(self):
        return self.counts

    def reduce(self, fn: str, col: np.ndarray):
        col = np.asarray(col)
        if col.ndim == 0:  # constant expression: broadcast over the rows
            col = np.full(self.n_rows, col[()])
        s = col[self.order]
        if fn == "SUM":
            return np.add.reduceat(s, self.starts)
        if fn == "MIN":
            return np.minimum.reduceat(s, self.starts)
        if fn == "MAX":
            return np.maximum.reduceat(s, self.starts)
        return np.add.reduceat(np.asarray(s, np.float64), self.starts) / self.counts


def _split_alias(item: str):
    """``'expr AS alias'`` -> ``(expr, alias)``; bare item -> the stripped
    expression doubling as the output name. One implementation for every
    select branch so the alias grammar cannot drift between them."""
    m = re.match(r"(?P<expr>.+?)\s+AS\s+(?P<alias>\w+)$", item, re.I)
    if m:
        return m.group("expr").strip(), m.group("alias")
    return item.strip(), item.strip()


def _eval_aggregate_item(expr: str, allowed, namespace, reducer, outer_ns=None):
    """Evaluate a select item that contains aggregate calls: each call is
    reduced (to a scalar globally, or a per-group vector under GROUP BY),
    substituted for a temp name, and the remaining expression (arithmetic
    of aggregates plus, under GROUP BY, the group keys' per-group values
    via ``outer_ns`` — any other bare per-row column outside an aggregate
    has no meaning in an aggregated result and is rejected, as in real
    SQL) is evaluated."""
    calls = _find_aggregate_calls(expr)
    rewritten, last = [], 0
    local_ns = dict(namespace)
    outer_allowed = set()  # temps + group keys only in the outer expr
    if outer_ns:
        local_ns.update(outer_ns)
        outer_allowed.update(outer_ns)
    for j, (start, end, fn, inner) in enumerate(calls):
        if _find_aggregate_calls(inner):
            raise ValueError(
                f"SQLTransformer: nested aggregates are not supported: {expr!r}"
            )
        temp = f"aggtmp{j}"
        if fn == "COUNT":
            if inner != "*":
                # validate the expression, but COUNT counts rows — this
                # subset has no NULL, so COUNT(expr) == COUNT(*), including
                # the COUNT(1) idiom.
                _check_safe(inner, allowed)
                eval(_sql_to_python(inner), {"__builtins__": {}}, namespace)
            value = reducer.count()
        else:
            _check_safe(inner, allowed)
            col = np.asarray(
                eval(_sql_to_python(inner), {"__builtins__": {}}, namespace)
            )
            value = reducer.reduce(fn, col)
        local_ns[temp] = value
        outer_allowed.add(temp)
        rewritten.append(expr[last:start])
        rewritten.append(temp)
        last = end
    rewritten.append(expr[last:])
    outer = "".join(rewritten)
    _check_safe(outer, outer_allowed)
    return eval(_sql_to_python(outer), {"__builtins__": {}}, local_ns)


def _sql_to_python(expr: str) -> str:
    """SQL boolean expression → numpy-evaluable Python, preserving SQL precedence
    (OR < AND < NOT < comparison) by parenthesizing each operand — numpy's &/| bind
    tighter than comparisons, so bare substitution would mis-parse."""
    or_parts = _split_top_level_keyword(expr, "OR")
    if len(or_parts) > 1:
        return " | ".join(f"({_sql_to_python(p.strip())})" for p in or_parts)
    and_parts = _split_top_level_keyword(expr, "AND")
    if len(and_parts) > 1:
        return " & ".join(f"({_sql_to_python(p.strip())})" for p in and_parts)
    stripped = expr.strip()
    m = re.match(r"NOT\b(.*)$", stripped, re.I | re.S)
    if m:
        return f"~({_sql_to_python(m.group(1).strip())})"
    return re.sub(r"(?<![<>!=])=(?!=)", "==", stripped)


def _check_safe(expr: str, allowed_names) -> None:
    """Reject anything outside the documented subset BEFORE eval: attribute access,
    indexing, double underscores, lambda/comprehension keywords, and identifiers
    that are neither columns nor whitelisted functions."""
    if re.search(r"\.\s*[A-Za-z_]", expr):
        raise ValueError(f"SQLTransformer: attribute access is not supported: {expr!r}")
    if "__" in expr or "[" in expr or "]" in expr or "{" in expr or ":" in expr:
        raise ValueError(f"SQLTransformer: unsupported construct in {expr!r}")
    # (?<![\w.]) keeps exponents of numeric literals (1e5, 1e-3) from being
    # mistaken for identifiers.
    for ident in re.findall(r"(?<![\w.])[A-Za-z_]\w*", expr):
        if ident.upper() in ("AND", "OR", "NOT", "AS"):
            continue
        if ident not in allowed_names and ident.upper() not in _FUNCS:
            raise ValueError(f"SQLTransformer: unknown identifier {ident!r} in {expr!r}")


class SQLTransformer(Transformer):
    """Ref SQLTransformer.java."""

    STATEMENT = StringParam(
        "statement", "SQL statement with __THIS__ as the input table.", None, ParamValidators.not_null()
    )

    def get_statement(self) -> str:
        return self.get(self.STATEMENT)

    def set_statement(self, value: str):
        return self.set(self.STATEMENT, value)

    def transform(self, *inputs):
        (df,) = inputs
        stmt = self.get_statement().strip().rstrip(";")
        # Loud, specific rejections for SQL the subset will never parse —
        # checked on the whole statement so a trailing clause after WHERE
        # cannot be swallowed by the WHERE capture and surface as a
        # misleading unknown-identifier error. These are SQL reserved words
        # (plus OVER followed by a paren), so no legal column reference in
        # the subset collides with them.
        for pattern, name in (
            (r"ORDER\s+BY", "ORDER BY"),
            (r"JOIN", "JOIN"),
            (r"HAVING", "HAVING"),
            (r"OVER\s*\(", "OVER (window)"),
        ):
            if re.search(rf"\b{pattern}", stmt, re.I):
                raise ValueError(
                    f"SQLTransformer: {name} is not supported (the subset is "
                    "'SELECT ... FROM __THIS__ [WHERE ...] [GROUP BY ...]' "
                    "with aggregates; see the module docstring)"
                )
        m = re.match(
            r"SELECT\s+(?P<select>.+?)\s+FROM\s+__THIS__"
            r"(?:\s+WHERE\s+(?P<where>.+?))?"
            r"(?:\s+GROUP\s+BY\s+(?P<groupby>.+))?$",
            stmt,
            re.I | re.S,
        )
        if not m:
            raise ValueError(
                "SQLTransformer supports 'SELECT ... FROM __THIS__ [WHERE ...] "
                "[GROUP BY ...]'; got: " + stmt
            )
        namespace: Dict[str, object] = dict(_FUNCS)
        namespace.update({k.lower(): v for k, v in _FUNCS.items()})
        for name in df.get_column_names():
            namespace[name] = df.column(name)
        allowed = set(df.get_column_names())

        base = df
        if m.group("where"):
            if _find_aggregate_calls(m.group("where")):
                raise ValueError(
                    "SQLTransformer: aggregates are not allowed in WHERE "
                    "(there is no HAVING in the subset)"
                )
            _check_safe(m.group("where"), allowed)
            cond = eval(_sql_to_python(m.group("where")), {"__builtins__": {}}, namespace)
            base = df.take(np.nonzero(np.asarray(cond))[0])
            for name in base.get_column_names():
                namespace[name] = base.column(name)

        items = _split_top_level_commas(m.group("select"))
        has_agg = [bool(_find_aggregate_calls(i)) for i in items]

        if m.group("groupby") is not None:
            return self._transform_grouped(
                m.group("groupby"), items, has_agg, base, allowed, namespace
            )

        if any(has_agg):
            if not all(has_agg):
                raise ValueError(
                    "SQLTransformer: without GROUP BY every select item must "
                    "be an aggregate expression (the output is one row); got "
                    f"mixed items in {m.group('select')!r}"
                )
            reducer = _GlobalReducer(base.num_rows)
            out_names, out_cols = [], []
            for item in items:
                expr, name = _split_alias(item)
                value = _eval_aggregate_item(expr, allowed, namespace, reducer)
                out_names.append(name)
                out_cols.append(np.asarray([value]))
            return DataFrame(out_names, None, out_cols)

        out_names: List[str] = []
        out_cols = []
        for item in items:
            if item == "*":
                for name in base.get_column_names():
                    out_names.append(name)
                    out_cols.append(base.column(name))
                continue
            expr, name = _split_alias(item)
            _check_safe(expr, allowed)
            value = eval(_sql_to_python(expr), {"__builtins__": {}}, namespace)
            if np.isscalar(value):
                value = np.full(base.num_rows, value)
            out_names.append(name)
            out_cols.append(value)
        return DataFrame(out_names, None, out_cols)

    def _transform_grouped(self, groupby, items, has_agg, base, allowed, namespace):
        """The GROUP BY path: keys are bare input columns; every select item
        is either a key (bare / aliased) or an aggregate expression. One
        output row per distinct key tuple, in first-appearance order."""
        keys = [k.strip() for k in _split_top_level_commas(groupby)]
        for k in keys:
            if not re.fullmatch(r"[A-Za-z_]\w*", k) or k not in allowed:
                raise ValueError(
                    "SQLTransformer: GROUP BY keys must be bare input column "
                    f"names; got {k!r}"
                )
        key_cols = {k: np.asarray(base.column(k)) for k in keys}

        # Classify select items before touching the data so errors do not
        # depend on the table being non-empty.
        plan = []  # ("key", name, key) | ("agg", name, expr)
        for item, agg in zip(items, has_agg):
            expr, name = _split_alias(item)
            if agg:
                plan.append(("agg", name, expr))
            elif expr in key_cols:
                plan.append(("key", name, expr))
            else:
                raise ValueError(
                    "SQLTransformer: with GROUP BY every select item must be "
                    f"a group key or an aggregate expression; got {item!r}"
                )

        # Group ids in key first-appearance order: factorize each key, then
        # unique over the code tuples. Zero input rows flow through as zero
        # groups — every output column comes out empty WITH its natural
        # dtype (int counts, key dtypes preserved).
        codes = np.stack(
            [np.unique(c, return_inverse=True)[1].reshape(-1) for c in key_cols.values()],
            axis=1,
        )
        _, first_idx, ginv = np.unique(
            codes, axis=0, return_index=True, return_inverse=True
        )
        appear = np.argsort(first_idx, kind="stable")
        rank = np.empty(appear.shape[0], np.int64)
        rank[appear] = np.arange(appear.shape[0])
        gid = rank[ginv.reshape(-1)]
        reducer = _GroupReducer(gid, appear.shape[0])
        first_row_of_group = np.asarray(first_idx)[appear]
        # Group keys are legal OUTSIDE aggregates within an aggregate item
        # (SUM(v) + cat), carrying their per-group value.
        keys_per_group = {k: c[first_row_of_group] for k, c in key_cols.items()}

        out_names, out_cols = [], []
        for kind, name, ref in plan:
            if kind == "key":
                value = keys_per_group[ref]
            else:
                value = np.asarray(
                    _eval_aggregate_item(
                        ref, allowed, namespace, reducer, outer_ns=keys_per_group
                    )
                )
            out_names.append(name)
            out_cols.append(value)
        return DataFrame(out_names, None, out_cols)
