"""SQLTransformer.

Reference: ``flink-ml-lib/.../feature/sqltransformer/SQLTransformer.java`` —
executes a SQL statement against the input table; ``__THIS__`` is the placeholder
for the input (e.g. ``SELECT *, (v1 + v2) AS v3 FROM __THIS__``).

The reference delegates to Flink's full SQL planner. Here a documented subset is
evaluated columnar over numpy:
  SELECT <expr> [AS alias][, ...] FROM __THIS__ [WHERE <cond>]
with ``*`` expansion, arithmetic/comparison/boolean operators (SQL ``=``, AND, OR,
NOT), and the scalar functions ABS, SQRT, EXP, LOG, POW, MIN, MAX (two-argument
MIN/MAX are elementwise, like SQL LEAST/GREATEST).

Global aggregates — COUNT(*), COUNT(expr), SUM, AVG, and single-argument
MIN/MAX over the whole table (round 5) — are supported without GROUP BY:
every select item must then be an expression of aggregates (the output is
one row; per-row columns may appear only inside an aggregate), WHERE
filters before aggregation (aggregates are not allowed inside WHERE — no
HAVING), and aggregates compose with arithmetic (``SUM(v1) / COUNT(*)``).
Over an empty (filtered) table: COUNT = 0, SUM = 0.0, and MIN/MAX/AVG =
NaN (this subset has no NULL). GROUP BY, joins, and window clauses are not
supported and raise ValueError.
"""
from __future__ import annotations

import re
from typing import Dict, List

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.params.param import ParamValidators, StringParam

__all__ = ["SQLTransformer"]

_FUNCS = {
    "ABS": np.abs,
    "SQRT": np.sqrt,
    "EXP": np.exp,
    "LOG": np.log,
    "POW": np.power,
    "MIN": np.minimum,
    "MAX": np.maximum,
}


def _split_top_level_commas(s: str) -> List[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur).strip())
    return parts


def _split_top_level_keyword(s: str, keyword: str) -> List[str]:
    """Split on a keyword at paren depth 0 (case-insensitive, word-bounded)."""
    pattern = re.compile(rf"\b{keyword}\b", re.I)
    parts, depth, last, i = [], 0, 0, 0
    while i < len(s):
        ch = s[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif depth == 0:
            m = pattern.match(s, i)
            if m:
                parts.append(s[last : i])
                i = m.end()
                last = i
                continue
        i += 1
    parts.append(s[last:])
    return parts


_AGG_REDUCERS = {
    "COUNT": len,
    "SUM": np.sum,
    "AVG": np.mean,
    "MIN": np.min,
    "MAX": np.max,
}


def _find_aggregate_calls(expr: str):
    """Locate aggregate calls ``FN(...)`` with balanced parens. Returns
    ``[(start, end, fn_name, inner)]``. Two-argument MIN/MAX are the
    documented elementwise scalars (SQL LEAST/GREATEST), not aggregates."""
    calls = []
    for m in re.finditer(r"\b(COUNT|SUM|AVG|MIN|MAX)\s*\(", expr, re.I):
        depth, i = 1, m.end()
        while i < len(expr) and depth:
            if expr[i] == "(":
                depth += 1
            elif expr[i] == ")":
                depth -= 1
            i += 1
        if depth:
            raise ValueError(f"SQLTransformer: unbalanced parens in {expr!r}")
        inner = expr[m.end() : i - 1].strip()
        fn = m.group(1).upper()
        if fn in ("MIN", "MAX") and len(_split_top_level_commas(inner)) > 1:
            continue  # elementwise two-argument form
        calls.append((m.start(), i, fn, inner))
    return calls


def _eval_aggregate_item(expr: str, allowed, namespace, n_rows: int):
    """Evaluate a select item that contains aggregate calls: each call is
    reduced to a scalar, substituted for a temp name, and the remaining
    expression (arithmetic of aggregates ONLY — a bare per-row column
    outside an aggregate has no meaning in a one-row result and is
    rejected, as in real SQL) is evaluated."""
    calls = _find_aggregate_calls(expr)
    rewritten, last = [], 0
    local_ns = dict(namespace)
    outer_allowed = set()  # temps only: no per-row columns in the outer expr
    for j, (start, end, fn, inner) in enumerate(calls):
        if _find_aggregate_calls(inner):
            raise ValueError(
                f"SQLTransformer: nested aggregates are not supported: {expr!r}"
            )
        temp = f"aggtmp{j}"
        if fn == "COUNT":
            if inner != "*":
                # validate the expression, but COUNT counts rows — this
                # subset has no NULL, so COUNT(expr) == COUNT(*), including
                # the COUNT(1) idiom.
                _check_safe(inner, allowed)
                eval(_sql_to_python(inner), {"__builtins__": {}}, namespace)
            value = n_rows
        else:
            _check_safe(inner, allowed)
            col = np.atleast_1d(
                np.asarray(
                    eval(_sql_to_python(inner), {"__builtins__": {}}, namespace)
                )
            )
            if col.size == 0:
                # empty filtered table: SUM = 0.0, MIN/MAX/AVG = NaN (no
                # NULL in this subset) — defined results, not numpy errors
                value = 0.0 if fn == "SUM" else float("nan")
            else:
                value = _AGG_REDUCERS[fn](col)
        local_ns[temp] = value
        outer_allowed.add(temp)
        rewritten.append(expr[last:start])
        rewritten.append(temp)
        last = end
    rewritten.append(expr[last:])
    outer = "".join(rewritten)
    _check_safe(outer, outer_allowed)
    return eval(_sql_to_python(outer), {"__builtins__": {}}, local_ns)


def _sql_to_python(expr: str) -> str:
    """SQL boolean expression → numpy-evaluable Python, preserving SQL precedence
    (OR < AND < NOT < comparison) by parenthesizing each operand — numpy's &/| bind
    tighter than comparisons, so bare substitution would mis-parse."""
    or_parts = _split_top_level_keyword(expr, "OR")
    if len(or_parts) > 1:
        return " | ".join(f"({_sql_to_python(p.strip())})" for p in or_parts)
    and_parts = _split_top_level_keyword(expr, "AND")
    if len(and_parts) > 1:
        return " & ".join(f"({_sql_to_python(p.strip())})" for p in and_parts)
    stripped = expr.strip()
    m = re.match(r"NOT\b(.*)$", stripped, re.I | re.S)
    if m:
        return f"~({_sql_to_python(m.group(1).strip())})"
    return re.sub(r"(?<![<>!=])=(?!=)", "==", stripped)


def _check_safe(expr: str, allowed_names) -> None:
    """Reject anything outside the documented subset BEFORE eval: attribute access,
    indexing, double underscores, lambda/comprehension keywords, and identifiers
    that are neither columns nor whitelisted functions."""
    if re.search(r"\.\s*[A-Za-z_]", expr):
        raise ValueError(f"SQLTransformer: attribute access is not supported: {expr!r}")
    if "__" in expr or "[" in expr or "]" in expr or "{" in expr or ":" in expr:
        raise ValueError(f"SQLTransformer: unsupported construct in {expr!r}")
    # (?<![\w.]) keeps exponents of numeric literals (1e5, 1e-3) from being
    # mistaken for identifiers.
    for ident in re.findall(r"(?<![\w.])[A-Za-z_]\w*", expr):
        if ident.upper() in ("AND", "OR", "NOT", "AS"):
            continue
        if ident not in allowed_names and ident.upper() not in _FUNCS:
            raise ValueError(f"SQLTransformer: unknown identifier {ident!r} in {expr!r}")


class SQLTransformer(Transformer):
    """Ref SQLTransformer.java."""

    STATEMENT = StringParam(
        "statement", "SQL statement with __THIS__ as the input table.", None, ParamValidators.not_null()
    )

    def get_statement(self) -> str:
        return self.get(self.STATEMENT)

    def set_statement(self, value: str):
        return self.set(self.STATEMENT, value)

    def transform(self, *inputs):
        (df,) = inputs
        stmt = self.get_statement().strip().rstrip(";")
        # Loud, specific rejections for SQL the subset will never parse —
        # checked on the whole statement so a trailing clause after WHERE
        # cannot be swallowed by the WHERE capture and surface as a
        # misleading unknown-identifier error. These are SQL reserved words
        # (plus OVER followed by a paren), so no legal column reference in
        # the subset collides with them.
        for pattern, name in (
            (r"GROUP\s+BY", "GROUP BY"),
            (r"ORDER\s+BY", "ORDER BY"),
            (r"JOIN", "JOIN"),
            (r"HAVING", "HAVING"),
            (r"OVER\s*\(", "OVER (window)"),
        ):
            if re.search(rf"\b{pattern}", stmt, re.I):
                raise ValueError(
                    f"SQLTransformer: {name} is not supported (the subset is "
                    "'SELECT ... FROM __THIS__ [WHERE ...]' with global "
                    "aggregates; see the module docstring)"
                )
        m = re.match(
            r"SELECT\s+(?P<select>.+?)\s+FROM\s+__THIS__(?:\s+WHERE\s+(?P<where>.+))?$",
            stmt,
            re.I | re.S,
        )
        if not m:
            raise ValueError(
                "SQLTransformer supports 'SELECT ... FROM __THIS__ [WHERE ...]'; got: "
                + stmt
            )
        namespace: Dict[str, object] = dict(_FUNCS)
        namespace.update({k.lower(): v for k, v in _FUNCS.items()})
        for name in df.get_column_names():
            namespace[name] = df.column(name)
        allowed = set(df.get_column_names())

        base = df
        if m.group("where"):
            if _find_aggregate_calls(m.group("where")):
                raise ValueError(
                    "SQLTransformer: aggregates are not allowed in WHERE "
                    "(there is no HAVING in the subset)"
                )
            _check_safe(m.group("where"), allowed)
            cond = eval(_sql_to_python(m.group("where")), {"__builtins__": {}}, namespace)
            base = df.take(np.nonzero(np.asarray(cond))[0])
            for name in base.get_column_names():
                namespace[name] = base.column(name)

        items = _split_top_level_commas(m.group("select"))
        has_agg = [bool(_find_aggregate_calls(i)) for i in items]
        if any(has_agg):
            if not all(has_agg):
                raise ValueError(
                    "SQLTransformer: without GROUP BY every select item must "
                    "be an aggregate expression (the output is one row); got "
                    f"mixed items in {m.group('select')!r}"
                )
            out_names, out_cols = [], []
            for item in items:
                alias_match = re.match(
                    r"(?P<expr>.+?)\s+AS\s+(?P<alias>\w+)$", item, re.I
                )
                expr = alias_match.group("expr") if alias_match else item
                name = alias_match.group("alias") if alias_match else expr.strip()
                value = _eval_aggregate_item(expr, allowed, namespace, base.num_rows)
                out_names.append(name)
                out_cols.append(np.asarray([value]))
            return DataFrame(out_names, None, out_cols)

        out_names: List[str] = []
        out_cols = []
        for item in items:
            if item == "*":
                for name in base.get_column_names():
                    out_names.append(name)
                    out_cols.append(base.column(name))
                continue
            alias_match = re.match(r"(?P<expr>.+?)\s+AS\s+(?P<alias>\w+)$", item, re.I)
            expr = alias_match.group("expr") if alias_match else item
            name = alias_match.group("alias") if alias_match else expr.strip()
            _check_safe(expr, allowed)
            value = eval(_sql_to_python(expr), {"__builtins__": {}}, namespace)
            if np.isscalar(value):
                value = np.full(base.num_rows, value)
            out_names.append(name)
            out_cols.append(value)
        return DataFrame(out_names, None, out_cols)
