"""RandomSplitter.

Reference: ``flink-ml-lib/.../feature/randomsplitter/RandomSplitter.java`` — an
AlgoOperator splitting the input into N output tables with the given weight
proportions, row membership drawn independently per row from the seeded RNG.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import AlgoOperator
from flink_ml_tpu.params.param import FloatArrayParam
from flink_ml_tpu.params.shared import HasSeed

__all__ = ["RandomSplitter"]


class RandomSplitter(AlgoOperator, HasSeed):
    """Ref RandomSplitter.java."""

    WEIGHTS = FloatArrayParam(
        "weights",
        "The weights of the output tables; rows are assigned proportionally.",
        [1.0, 1.0],
        lambda v: v is not None and len(v) >= 2 and all(w > 0 for w in v),
    )

    def get_weights(self):
        return self.get(self.WEIGHTS)

    def set_weights(self, *values: float):
        return self.set(self.WEIGHTS, list(values))

    def transform(self, *inputs):
        (df,) = inputs
        weights = np.asarray(self.get_weights(), np.float64)
        bounds = np.cumsum(weights / weights.sum())
        rng = np.random.default_rng(self.get_seed())
        draws = rng.random(len(df))
        assignment = np.searchsorted(bounds, draws, side="right")
        assignment = np.minimum(assignment, len(weights) - 1)
        return [df.take(np.nonzero(assignment == i)[0]) for i in range(len(weights))]
