"""PolynomialExpansion.

Reference: ``flink-ml-lib/.../feature/polynomialexpansion/PolynomialExpansion.java``
— expand an n-dim vector into all monomials of degree 1..degree.

Output ordering here is ``itertools.combinations_with_replacement`` grouped by
degree (deterministic and documented); the reference follows Spark's recursive
ordering, which enumerates the same monomial set in a different order.
"""
from __future__ import annotations

import functools
import itertools

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.params.param import IntParam, ParamValidators
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol

__all__ = ["PolynomialExpansion"]


@functools.cache
def _combos(d: int, degree: int):
    out = []
    for deg in range(1, degree + 1):
        out.extend(itertools.combinations_with_replacement(range(d), deg))
    return tuple(out)


@functools.cache
def _kernel(d: int, degree: int):
    combos = _combos(d, degree)

    @jax.jit
    def expand(X):
        cols = [jnp.prod(X[:, jnp.asarray(c)], axis=1) for c in combos]
        return jnp.stack(cols, axis=1)

    return expand


class PolynomialExpansion(Transformer, HasInputCol, HasOutputCol):
    """Ref PolynomialExpansion.java."""

    DEGREE = IntParam("degree", "Degree of the polynomial expansion.", 2, ParamValidators.gt_eq(1))

    def get_degree(self) -> int:
        return self.get(self.DEGREE)

    def set_degree(self, value: int):
        return self.set(self.DEGREE, value)

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        vals = _kernel(X.shape[1], self.get_degree())(X)
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(vals, np.float64),
        )
        return out
