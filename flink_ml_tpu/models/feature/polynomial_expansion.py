"""PolynomialExpansion.

Reference: ``flink-ml-lib/.../feature/polynomialexpansion/PolynomialExpansion.java``
— expand an n-dim vector into all monomials of degree 1..degree.

Output ordering here is ``itertools.combinations_with_replacement`` grouped by
degree (deterministic and documented); the reference follows Spark's recursive
ordering, which enumerates the same monomial set in a different order. The
expansion is the shared ``poly_expand`` kernel (``ops/kernels.py``), which
derives the combo set from the trace-time width.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.ops.kernels import poly_expand_fn, poly_expand_kernel
from flink_ml_tpu.params.param import IntParam, ParamValidators
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec

__all__ = ["PolynomialExpansion"]


class PolynomialExpansion(Transformer, HasInputCol, HasOutputCol):
    """Ref PolynomialExpansion.java."""

    DEGREE = IntParam("degree", "Degree of the polynomial expansion.", 2, ParamValidators.gt_eq(1))

    def get_degree(self) -> int:
        return self.get(self.DEGREE)

    def set_degree(self, value: int):
        return self.set(self.DEGREE, value)

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        vals = poly_expand_kernel(int(self.get_degree()))(X)
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(vals, np.float64),
        )
        return out

    def kernel_spec(self):
        """Monomial expansion as a fusable spec — ``poly_expand_fn``, the body
        ``transform``'s jitted kernel wraps (combos resolve from the static
        trace-time width, so one spec serves any input dimension)."""
        in_col, out_col = self.get_input_col(), self.get_output_col()
        degree = int(self.get_degree())

        def kernel_fn(model, cols):
            return {out_col: poly_expand_fn(cols[in_col], degree)}

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
        )
