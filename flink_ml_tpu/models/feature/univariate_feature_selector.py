"""UnivariateFeatureSelector.

Reference: ``flink-ml-lib/.../feature/univariatefeatureselector/`` — select
features by univariate statistical tests against the label: chi-square
(categorical/categorical), ANOVA F (continuous features / categorical label),
F-regression (continuous/continuous). Selection modes
(UnivariateFeatureSelectorParams): numTopFeatures (default threshold 50),
percentile (0.1), fpr / fdr / fwe (0.05; fdr = Benjamini-Hochberg, fwe =
Bonferroni p < t/numFeatures).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.ops.stats import anova_f_classification, chi_square_test, f_regression
from flink_ml_tpu.params.param import FloatParam, ParamValidators, StringParam, update_existing_params
from flink_ml_tpu.params.shared import HasFeaturesCol, HasLabelCol, HasOutputCol

__all__ = ["UnivariateFeatureSelector", "UnivariateFeatureSelectorModel"]

CATEGORICAL, CONTINUOUS = "categorical", "continuous"
NUM_TOP_FEATURES, PERCENTILE, FPR, FDR, FWE = (
    "numTopFeatures",
    "percentile",
    "fpr",
    "fdr",
    "fwe",
)
_DEFAULT_THRESHOLDS = {NUM_TOP_FEATURES: 50.0, PERCENTILE: 0.1, FPR: 0.05, FDR: 0.05, FWE: 0.05}


class _UfsParams(HasFeaturesCol, HasLabelCol, HasOutputCol):
    FEATURE_TYPE = StringParam(
        "featureType", "The feature type.", None, ParamValidators.in_array([CATEGORICAL, CONTINUOUS])
    )
    LABEL_TYPE = StringParam(
        "labelType", "The label type.", None, ParamValidators.in_array([CATEGORICAL, CONTINUOUS])
    )
    SELECTION_MODE = StringParam(
        "selectionMode",
        "The feature selection mode.",
        NUM_TOP_FEATURES,
        ParamValidators.in_array([NUM_TOP_FEATURES, PERCENTILE, FPR, FDR, FWE]),
    )
    SELECTION_THRESHOLD = FloatParam(
        "selectionThreshold", "The upper bound of the features the selector will select.", None
    )

    def get_feature_type(self) -> str:
        return self.get(self.FEATURE_TYPE)

    def set_feature_type(self, value: str):
        return self.set(self.FEATURE_TYPE, value)

    def get_label_type(self) -> str:
        return self.get(self.LABEL_TYPE)

    def set_label_type(self, value: str):
        return self.set(self.LABEL_TYPE, value)

    def get_selection_mode(self) -> str:
        return self.get(self.SELECTION_MODE)

    def set_selection_mode(self, value: str):
        return self.set(self.SELECTION_MODE, value)

    def get_selection_threshold(self):
        return self.get(self.SELECTION_THRESHOLD)

    def set_selection_threshold(self, value: float):
        return self.set(self.SELECTION_THRESHOLD, value)


class UnivariateFeatureSelectorModel(ModelArraysMixin, Model, _UfsParams):
    """Ref UnivariateFeatureSelectorModel.java — keeps ``indices``."""

    _MODEL_ARRAY_NAMES = ("indices",)

    def __init__(self):
        super().__init__()
        self.indices: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float64)
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            X[:, np.sort(self.indices.astype(np.int64))],
        )
        return out


class UnivariateFeatureSelector(Estimator, _UfsParams):
    """Ref UnivariateFeatureSelector.java."""

    def fit(self, *inputs) -> UnivariateFeatureSelectorModel:
        (df,) = inputs
        feature_type, label_type = self.get_feature_type(), self.get_label_type()
        if feature_type is None or label_type is None:
            raise ValueError("featureType and labelType must be set.")
        X = df.vectors(self.get_features_col()).astype(np.float64)
        y = df.scalars(self.get_label_col())

        if feature_type == CATEGORICAL and label_type == CATEGORICAL:
            p_values = np.asarray(
                [chi_square_test(X[:, d], y)[2] for d in range(X.shape[1])]
            )
        elif feature_type == CONTINUOUS and label_type == CATEGORICAL:
            _, p_values = anova_f_classification(X, y)
        elif feature_type == CONTINUOUS and label_type == CONTINUOUS:
            _, p_values = f_regression(X, y)
        else:
            raise ValueError(
                f"Unsupported combination: featureType={feature_type}, labelType={label_type}."
            )

        mode = self.get_selection_mode()
        threshold = self.get_selection_threshold()
        if threshold is None:
            threshold = _DEFAULT_THRESHOLDS[mode]
        d = X.shape[1]
        order = np.argsort(p_values, kind="stable")
        if mode == NUM_TOP_FEATURES:
            indices = order[: int(threshold)]
        elif mode == PERCENTILE:
            indices = order[: int(d * threshold)]
        elif mode == FPR:
            indices = np.nonzero(p_values < threshold)[0]
        elif mode == FDR:  # Benjamini-Hochberg
            sorted_p = p_values[order]
            below = np.nonzero(sorted_p <= threshold * (np.arange(1, d + 1) / d))[0]
            indices = order[: below[-1] + 1] if below.size else np.asarray([], np.int64)
        else:  # FWE (Bonferroni)
            indices = np.nonzero(p_values < threshold / d)[0]

        model = UnivariateFeatureSelectorModel()
        update_existing_params(model, self)
        model.indices = np.sort(np.asarray(indices, np.int64))
        return model
