"""StringIndexer / StringIndexerModel / IndexToStringModel.

Reference: ``flink-ml-lib/.../feature/stringindexer/`` — multi-column mapping of
string (or numeric) values to double indices. ``stringOrderType``: arbitrary
(default), frequencyDesc/Asc, alphabetDesc/Asc (first label after ordering gets
index 0, StringIndexerParams.java); ``handleInvalid``: error raises on unseen
values, skip drops the row, keep maps them to numDistinct. ``IndexToStringModel``
reverses the mapping using the same model data.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.params.param import StringParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import HasHandleInvalid, HasInputCols, HasOutputCols
from flink_ml_tpu.utils import read_write as rw

__all__ = ["StringIndexer", "StringIndexerModel", "IndexToStringModel"]

ARBITRARY_ORDER = "arbitrary"
FREQUENCY_DESC_ORDER = "frequencyDesc"
FREQUENCY_ASC_ORDER = "frequencyAsc"
ALPHABET_DESC_ORDER = "alphabetDesc"
ALPHABET_ASC_ORDER = "alphabetAsc"


class _IndexerModelBase(Model, HasInputCols, HasOutputCols, HasHandleInvalid):
    """Shared save/load for models whose data is per-column string lists."""

    def __init__(self):
        super().__init__()
        self.string_arrays: Optional[List[List[str]]] = None

    # model data = one column of per-input-column label lists
    def get_model_data(self):
        return [DataFrame(["stringArrays"], None, [[list(a) for a in self.string_arrays]])]

    def set_model_data(self, *model_data: DataFrame):
        df = model_data[0]
        self.string_arrays = [list(a) for a in df.column("stringArrays")[0]]
        return self

    def save(self, path: str) -> None:
        rw.save_metadata(self, path)
        arrays = {
            f"col{i}": np.asarray(a, dtype=str) for i, a in enumerate(self.string_arrays)
        }
        arrays["__num_cols__"] = np.asarray([len(self.string_arrays)])
        rw.save_model_arrays(path, arrays)

    @classmethod
    def load(cls, path: str):
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        arrays = rw.load_model_arrays(path)
        n = int(arrays["__num_cols__"][0])
        model.string_arrays = [[str(s) for s in arrays[f"col{i}"]] for i in range(n)]
        return model


class StringIndexerModel(_IndexerModelBase):
    """Ref StringIndexerModel.java — value → index."""

    def transform(self, *inputs):
        (df,) = inputs
        handle = self.get_handle_invalid()
        n = len(df)
        keep_mask = np.ones(n, bool)
        out_cols = []
        for i, name in enumerate(self.get_input_cols()):
            mapping = {v: j for j, v in enumerate(self.string_arrays[i])}
            col = df.column(name)
            values = np.empty(n, np.float64)
            for r in range(n):
                v = col[r]
                key = str(v) if not isinstance(v, str) else v
                if key in mapping:
                    values[r] = mapping[key]
                elif handle == "error":
                    raise ValueError(
                        f"The input contains unseen string: {v!r}. See handleInvalid."
                    )
                elif handle == "keep":
                    values[r] = len(mapping)
                else:
                    keep_mask[r] = False
            out_cols.append(values)
        out = df.clone()
        for out_name, values in zip(self.get_output_cols(), out_cols):
            out.add_column(out_name, DataTypes.DOUBLE, values)
        if not keep_mask.all():
            out = out.take(np.nonzero(keep_mask)[0])
        return out


class IndexToStringModel(_IndexerModelBase):
    """Ref IndexToStringModel.java — index → original string."""

    def transform(self, *inputs):
        (df,) = inputs
        out = df.clone()
        for i, (in_name, out_name) in enumerate(
            zip(self.get_input_cols(), self.get_output_cols())
        ):
            labels = self.string_arrays[i]
            idx = df.scalars(in_name, np.int64)
            if (idx < 0).any() or (idx >= len(labels)).any():
                bad = idx[(idx < 0) | (idx >= len(labels))][0]
                raise ValueError(
                    f"The input contains index {bad} out of the model's range."
                )
            out.add_column(out_name, DataTypes.STRING, [labels[j] for j in idx])
        return out


class StringIndexer(Estimator, HasInputCols, HasOutputCols, HasHandleInvalid):
    """Ref StringIndexer.java."""

    STRING_ORDER_TYPE = StringParam(
        "stringOrderType",
        "How to order strings of each column.",
        ARBITRARY_ORDER,
        ParamValidators.in_array(
            [
                ARBITRARY_ORDER,
                FREQUENCY_DESC_ORDER,
                FREQUENCY_ASC_ORDER,
                ALPHABET_DESC_ORDER,
                ALPHABET_ASC_ORDER,
            ]
        ),
    )

    def get_string_order_type(self) -> str:
        return self.get(self.STRING_ORDER_TYPE)

    def set_string_order_type(self, value: str):
        return self.set(self.STRING_ORDER_TYPE, value)

    def fit(self, *inputs) -> StringIndexerModel:
        (df,) = inputs
        order = self.get_string_order_type()
        string_arrays = []
        for name in self.get_input_cols():
            col = df.column(name)
            counts = {}
            for v in col:
                key = str(v) if not isinstance(v, str) else v
                counts[key] = counts.get(key, 0) + 1
            if order == FREQUENCY_DESC_ORDER:
                labels = sorted(counts, key=lambda k: (-counts[k], k))
            elif order == FREQUENCY_ASC_ORDER:
                labels = sorted(counts, key=lambda k: (counts[k], k))
            elif order == ALPHABET_DESC_ORDER:
                labels = sorted(counts, reverse=True)
            elif order == ALPHABET_ASC_ORDER:
                labels = sorted(counts)
            else:  # arbitrary: first-seen order
                labels = list(counts)
            string_arrays.append(labels)
        model = StringIndexerModel()
        update_existing_params(model, self)
        model.string_arrays = string_arrays
        return model
