"""IDF.

Reference: ``flink-ml-lib/.../feature/idf/IDF.java`` — fit: document frequency per
term dimension; idf[i] = log((numDocs + 1)/(df[i] + 1)), dims with df < minDocFreq
get idf 0; transform multiplies term-frequency vectors elementwise by idf.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector, Vector
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.ops.kernels import (
    idf_scale_fn,
    idf_scale_kernel,
    sparse_idf_scale_fn,
    sparse_idf_scale_kernel,
)
from flink_ml_tpu.params.param import IntParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec
from flink_ml_tpu.servable.sparse import sparse_names

__all__ = ["IDF", "IDFModel"]


class _IDFParams(HasInputCol, HasOutputCol):
    MIN_DOC_FREQ = IntParam(
        "minDocFreq",
        "Minimum number of documents that a term should appear for filtering.",
        0,
        ParamValidators.gt_eq(0),
    )

    def get_min_doc_freq(self) -> int:
        return self.get(self.MIN_DOC_FREQ)

    def set_min_doc_freq(self, value: int):
        return self.set(self.MIN_DOC_FREQ, value)


class IDFModel(ModelArraysMixin, Model, _IDFParams):
    """Ref IDFModel.java."""

    _MODEL_ARRAY_NAMES = ("idf", "doc_freq", "num_docs")

    def __init__(self):
        super().__init__()
        self.idf: Optional[np.ndarray] = None
        self.doc_freq: Optional[np.ndarray] = None
        self.num_docs: Optional[np.ndarray] = None

    @classmethod
    def load_servable(cls, path: str) -> "IDFModel":
        """The fitted model is its own runtime-free replica (state = the idf
        vector; ``transform`` is one jitted kernel) — published text
        pipelines load it directly on the serving tier (docs/sparse.md)."""
        return cls.load(path)

    def transform(self, *inputs):
        (df,) = inputs
        in_col = self.get_input_col()
        col = df.column(in_col)
        out = df.clone()
        if len(df) == 0:
            # An empty column normalizes to a shapeless (0,) array — nothing
            # to scale, and the kernels cannot infer a width from it.
            out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), [])
            return out
        if isinstance(col, np.ndarray):
            vals = idf_scale_kernel()(col.astype(np.float64), self.idf)
            out.add_column(
                self.get_output_col(),
                DataTypes.vector(BasicType.DOUBLE),
                np.asarray(vals, np.float64),
            )
        elif df.is_sparse(in_col):
            # Sparse path: one batched gather-scale kernel over the padded-CSR
            # layout — the SAME ``sparse_idf_scale`` body the fused sparse
            # spec composes, so the two paths agree bit for bit (per-entry
            # f32 multiply, widened to the f64 storage dtype).
            batch = df.sparse_batch(in_col)
            vals = np.asarray(
                sparse_idf_scale_kernel()(
                    batch.values, batch.indices, np.asarray(self.idf, np.float32)
                ),
                np.float64,
            )
            new_col = []
            for i, v in enumerate(col):
                k = len(v.indices) if isinstance(v, SparseVector) else int(batch.nnz[i])
                new_col.append(
                    SparseVector(batch.dim, batch.indices[i, :k].astype(np.int64), vals[i, :k])
                )
            out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), new_col)
        else:
            new_col = [
                SparseVector(v.size(), v.indices, v.values * self.idf[v.indices])
                if isinstance(v, SparseVector)
                else v.to_array() * self.idf
                for v in col
            ]
            out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), new_col)
        return out

    def sparse_kernel_spec(self, known):
        """Sparse-convention spec (docs/sparse.md): when the input column is
        statically known sparse, idf scaling fuses as a per-entry
        gather-scale (``sparse_idf_scale_fn`` — the body the per-stage sparse
        path jits), structure (ids/nnz) passing through unchanged. No
        cross-entry accumulation, so the spec is elementwise and merges
        bit-exactly; ``sparse_idf`` is in the megakernel vocabulary."""
        if self.idf is None:
            raise RuntimeError("set_model_data must be called before kernel_spec")
        in_col, out_col = self.get_input_col(), self.get_output_col()
        dim = int(len(self.idf))
        if known.get(in_col) != dim:
            return None  # not sparse here (or a dim-mismatched model): dense spec
        in_v, in_i, in_z = sparse_names(in_col)
        out_v, out_i, out_z = sparse_names(out_col)

        def kernel_fn(model, cols):
            return {
                out_v: sparse_idf_scale_fn(cols[in_v], cols[in_i], model["idf"]),
                out_i: cols[in_i],
                out_z: cols[in_z],
            }

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={"idf": np.asarray(self.idf, np.float32)},
            kernel_fn=kernel_fn,
            input_kinds={in_col: "sparse"},
            sparse_outputs={out_col: dim},
            sparse_input_dims={in_col: dim},
            elementwise=True,  # per-entry gather + multiply: no accumulation
            fusion_op="sparse_idf",  # megakernel-safe
        )

    def kernel_spec(self):
        """idf scaling as a fusable spec — ``idf_scale_fn``, the body
        ``transform``'s jitted kernel wraps, with the idf vector as a
        committed device buffer. Sparse columns stay per-stage (sparsity
        preserved there), so the input ingests as ``dense``."""
        if self.idf is None:
            raise RuntimeError("set_model_data must be called before kernel_spec")
        in_col, out_col = self.get_input_col(), self.get_output_col()

        def kernel_fn(model, cols):
            return {out_col: idf_scale_fn(cols[in_col], model["idf"])}

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={"idf": np.asarray(self.idf, np.float32)},
            kernel_fn=kernel_fn,
            input_kinds={in_col: "dense"},
            elementwise=True,  # per-term scaling: no FP accumulation
            fusion_op="idf",  # megakernel-safe
        )


class IDF(Estimator, _IDFParams):
    """Ref IDF.java."""

    def fit(self, *inputs) -> IDFModel:
        (df,) = inputs
        col = df.column(self.get_input_col())
        if isinstance(col, np.ndarray):
            docs = col.astype(np.float64)
            doc_freq = (docs != 0).sum(axis=0).astype(np.float64)
            num_docs = docs.shape[0]
        else:
            dim = col[0].size() if isinstance(col[0], Vector) else len(col[0])
            doc_freq = np.zeros(dim)
            for v in col:
                if isinstance(v, SparseVector):
                    doc_freq[v.indices[v.values != 0]] += 1
                else:
                    doc_freq[np.asarray(v.to_array()) != 0] += 1
            num_docs = len(col)
        min_df = self.get_min_doc_freq()
        idf = np.where(
            doc_freq >= min_df, np.log((num_docs + 1.0) / (doc_freq + 1.0)), 0.0
        )
        model = IDFModel()
        update_existing_params(model, self)
        model.idf = idf
        model.doc_freq = doc_freq
        model.num_docs = np.asarray([num_docs])
        return model
