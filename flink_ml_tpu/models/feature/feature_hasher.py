"""FeatureHasher.

Reference: ``flink-ml-lib/.../feature/featurehasher/FeatureHasher.java`` — project
numeric and categorical columns into a ``numFeatures``-dim sparse vector:
numeric col → index hash(colName), value x; categorical col → index
hash("col=value"), value 1.0; index = Math.abs(murmur3_32(0).hashUnencodedChars(s))
% numFeatures (FeatureHasher.java:185-190); collisions accumulate.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.ops import hashing
from flink_ml_tpu.params.param import IntParam, ParamValidators
from flink_ml_tpu.params.shared import HasCategoricalCols, HasInputCols, HasOutputCol

__all__ = ["FeatureHasher"]


def _index(s: str, num_features: int) -> int:
    return hashing.java_abs(hashing.hash_unencoded_chars(s)) % num_features


class FeatureHasher(Transformer, HasInputCols, HasOutputCol, HasCategoricalCols):
    """Ref FeatureHasher.java."""

    NUM_FEATURES = IntParam(
        "numFeatures", "The number of features.", 1 << 18, ParamValidators.gt(0)
    )

    def get_num_features(self) -> int:
        return self.get(self.NUM_FEATURES)

    def set_num_features(self, value: int):
        return self.set(self.NUM_FEATURES, value)

    def transform(self, *inputs):
        (df,) = inputs
        num_features = self.get_num_features()
        in_cols = list(self.get_input_cols())
        cat_cols = list(self.get_categorical_cols())
        if cat_cols and not set(cat_cols) <= set(in_cols):
            raise ValueError("CategoricalCols must be included in inputCols!")
        # Non-declared string/bool columns are treated as categorical like the
        # reference's schema inspection (FeatureHasher.generateCategoricalCols).
        num_cols = []
        for name in in_cols:
            if name in cat_cols:
                continue
            col = df.column(name)
            if isinstance(col, np.ndarray) and np.issubdtype(col.dtype, np.number):
                num_cols.append(name)
            else:
                cat_cols.append(name)

        n = len(df)
        vectors = []
        columns = {name: df.column(name) for name in in_cols}
        for i in range(n):
            feature = {}
            for name in num_cols:
                v = columns[name][i]
                if v is None:
                    continue
                idx = _index(name, num_features)
                feature[idx] = feature.get(idx, 0.0) + float(v)
            for name in cat_cols:
                v = columns[name][i]
                if v is None:
                    continue
                if isinstance(v, (bool, np.bool_)):
                    v = "true" if v else "false"  # Java String.valueOf(boolean)
                idx = _index(f"{name}={v}", num_features)
                feature[idx] = feature.get(idx, 0.0) + 1.0
            indices = np.asarray(sorted(feature), np.int64)
            values = np.asarray([feature[j] for j in indices], np.float64)
            vectors.append(SparseVector(num_features, indices, values))
        out = df.clone()
        out.add_column(self.get_output_col(), DataTypes.vector(BasicType.DOUBLE), vectors)
        return out
