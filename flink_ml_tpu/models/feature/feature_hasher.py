"""FeatureHasher.

Reference: ``flink-ml-lib/.../feature/featurehasher/FeatureHasher.java`` — project
numeric and categorical columns into a ``numFeatures``-dim sparse vector:
numeric col → index hash(colName), value x; categorical col → index
hash("col=value"), value 1.0; index = Math.abs(murmur3_32(0).hashUnencodedChars(s))
% numFeatures (FeatureHasher.java:185-190); collisions accumulate.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.ops import hashing
from flink_ml_tpu.ops.kernels import sparse_combine_fn, sparse_combine_kernel
from flink_ml_tpu.params.param import IntParam, ParamValidators
from flink_ml_tpu.params.shared import HasCategoricalCols, HasInputCols, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec
from flink_ml_tpu.servable.sparse import (
    entries_names,
    pack_entry_rows,
    rebuild_sparse_column,
    sparse_names,
)

__all__ = ["FeatureHasher"]


def _index(s: str, num_features: int) -> int:
    return hashing.java_abs(hashing.hash_unencoded_chars(s)) % num_features


class FeatureHasher(Transformer, HasInputCols, HasOutputCol, HasCategoricalCols):
    """Ref FeatureHasher.java."""

    NUM_FEATURES = IntParam(
        "numFeatures", "The number of features.", 1 << 18, ParamValidators.gt(0)
    )

    def get_num_features(self) -> int:
        return self.get(self.NUM_FEATURES)

    def set_num_features(self, value: int):
        return self.set(self.NUM_FEATURES, value)

    def _featurize(self, df):
        """Host half of the row hashing: every column's contribution as raw
        (index, value) entries per row — numeric columns at the static
        hash(colName) index with value x, categorical at hash("col=value")
        with value 1.0, duplicates (collisions) preserved for the device
        ``sparse_combine`` segment reduce, in column order (the reference's
        accumulation order). Shared by ``transform`` and the fused spec's
        host ingest (ref FeatureHasher.java:185-190)."""
        num_features = self.get_num_features()
        in_cols = list(self.get_input_cols())
        cat_cols = list(self.get_categorical_cols())
        if cat_cols and not set(cat_cols) <= set(in_cols):
            raise ValueError("CategoricalCols must be included in inputCols!")
        # Non-declared string/bool columns are treated as categorical like the
        # reference's schema inspection (FeatureHasher.generateCategoricalCols).
        num_cols = []
        for name in in_cols:
            if name in cat_cols:
                continue
            col = df.column(name)
            if isinstance(col, np.ndarray) and np.issubdtype(col.dtype, np.number):
                num_cols.append(name)
            else:
                cat_cols.append(name)
        n = len(df)
        columns = {name: df.column(name) for name in in_cols}
        num_idx = {name: _index(name, num_features) for name in num_cols}
        rows = []
        for i in range(n):
            entries = []
            for name in num_cols:
                v = columns[name][i]
                if v is None:
                    continue
                entries.append((num_idx[name], float(v)))
            for name in cat_cols:
                v = columns[name][i]
                if v is None:
                    continue
                if isinstance(v, (bool, np.bool_)):
                    v = "true" if v else "false"  # Java String.valueOf(boolean)
                entries.append((_index(f"{name}={v}", num_features), 1.0))
            rows.append(entries)
        return rows, [len(r) for r in rows]

    def transform(self, *inputs):
        (df,) = inputs
        num_features = self.get_num_features()
        out_col = self.get_output_col()
        rows, lengths = self._featurize(df)
        arrays, _cap, _total = pack_entry_rows(out_col, rows, lengths)
        vn, idn, zn, _ln = entries_names(out_col)
        # Device segment reduce — the SAME ``sparse_combine`` body the fused
        # sparse spec composes: sort by index, fold colliding contributions
        # in column order, compact.
        values, ids, nnz = sparse_combine_kernel()(arrays[vn], arrays[idn], arrays[zn])
        vectors = rebuild_sparse_column(
            num_features, np.asarray(values), np.asarray(ids), np.asarray(nnz)
        )
        out = df.clone()
        out.add_column(out_col, DataTypes.vector(BasicType.DOUBLE), vectors)
        return out

    def sparse_kernel_spec(self, known):
        """Sparse-convention spec (docs/sparse.md): the whole row hashes on
        the host into raw entries (strings cannot run on device) under a
        synthetic source column; the device kernel is the ``sparse_combine``
        segment reduce ``transform`` jits. Output statically sparse."""
        num_features = self.get_num_features()
        out_col = self.get_output_col()
        src = f"{out_col}!src"  # synthetic: the ingest reads the df directly
        vn, idn, zn, _ln = entries_names(src)
        out_v, out_i, out_z = sparse_names(out_col)

        def host_ingest(df, cap, cap_max, truncate):
            rows, lengths = self._featurize(df)
            return pack_entry_rows(
                src, rows, lengths, cap=cap, cap_max=cap_max, truncate=truncate
            )

        def kernel_fn(model, cols):
            values, ids, nnz = sparse_combine_fn(cols[vn], cols[idn], cols[zn])
            return {out_v: values, out_i: ids, out_z: nnz}

        return KernelSpec(
            input_cols=(src,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
            input_kinds={src: "entries"},
            host_ingests={src: host_ingest},
            sparse_outputs={out_col: int(num_features)},
        )
