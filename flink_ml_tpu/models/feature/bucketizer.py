"""Bucketizer.

Reference: ``flink-ml-lib/.../feature/bucketizer/Bucketizer.java`` — multi-column:
value in [splits[j], splits[j+1]) → bucket j (last bucket right-inclusive);
values outside the splits or NaN are invalid, handled per ``handleInvalid``:
'error' raises, 'skip' drops the row, 'keep' maps to the extra bucket numSplits-1.

The bucket search runs on the shared ``bucketize`` kernel (``ops/kernels.py``);
'error' raising and 'skip' row-dropping consume the kernel's invalid mask on
the host (they are inherently host decisions — a fused device program cannot
raise or change the row count, which is why only 'keep' exports a kernel spec).
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.ops.kernels import bucketize_fn, bucketize_kernel
from flink_ml_tpu.params.param import Param, ParamValidators
from flink_ml_tpu.params.shared import HasHandleInvalid, HasInputCols, HasOutputCols
from flink_ml_tpu.servable.kernel_spec import KernelSpec

__all__ = ["Bucketizer"]


def _splits_valid(splits_array) -> bool:
    if not splits_array:
        return False
    for splits in splits_array:
        if len(splits) < 3:
            return False
        if any(splits[i] >= splits[i + 1] for i in range(len(splits) - 1)):
            return False
    return True


class Bucketizer(Transformer, HasInputCols, HasOutputCols, HasHandleInvalid):
    """Ref Bucketizer.java."""

    SPLITS_ARRAY = Param(
        "splitsArray",
        "Array of split points for mapping continuous features into buckets.",
        None,
        lambda v: v is not None and _splits_valid(v),
    )

    def get_splits_array(self):
        return self.get(self.SPLITS_ARRAY)

    def set_splits_array(self, value):
        return self.set(self.SPLITS_ARRAY, [list(s) for s in value])

    def transform(self, *inputs):
        (df,) = inputs
        in_cols, out_cols = self.get_input_cols(), self.get_output_cols()
        splits_array = self.get_splits_array()
        handle = self.get_handle_invalid()
        if len(in_cols) != len(splits_array):
            raise ValueError("Bucketizer: one splits array per input column required")

        kernel = bucketize_kernel(handle == "keep")
        n = len(df)
        keep_mask = np.ones(n, bool)
        buckets = []
        for name, splits in zip(in_cols, splits_array):
            x = df.scalars(name)
            idx, invalid = kernel(x, np.asarray(splits, np.float64))
            idx, invalid = np.asarray(idx, np.float64), np.asarray(invalid)
            if handle == "error" and invalid.any():
                raise ValueError(
                    f"The input contains invalid value {x[invalid][0]} for column {name}. "
                    "See Bucketizer handleInvalid."
                )
            if handle == "skip":
                keep_mask &= ~invalid
            buckets.append(idx)

        out = df.clone()
        for out_name, idx in zip(out_cols, buckets):
            out.add_column(out_name, DataTypes.DOUBLE, idx)
        if handle == "skip" and not keep_mask.all():
            out = out.take(np.nonzero(keep_mask)[0])
        return out

    def kernel_spec(self):
        """Bucket search as a fusable spec — ``bucketize_fn`` in 'keep' mode,
        the splits committed as device buffers. 'error'/'skip' need the host
        (raise / row-drop), so they stay per-stage."""
        splits_array = self.get_splits_array()
        in_cols, out_cols = self.get_input_cols(), self.get_output_cols()
        if (
            self.get_handle_invalid() != "keep"
            or splits_array is None
            or not in_cols
            or len(in_cols) != len(splits_array)
        ):
            return None
        bindings = tuple((i, n, o) for i, (n, o) in enumerate(zip(in_cols, out_cols)))

        def kernel_fn(model, cols):
            return {
                o: bucketize_fn(cols[n], model[f"splits{i}"], True)[0]
                for i, n, o in bindings
            }

        return KernelSpec(
            input_cols=in_cols,
            outputs=tuple((o, DataTypes.DOUBLE) for o in out_cols),
            model_arrays={
                f"splits{i}": np.asarray(s, np.float32)
                for i, s in enumerate(splits_array)
            },
            kernel_fn=kernel_fn,
            input_kinds={n: "scalar" for n in in_cols},
            elementwise=True,  # searchsorted + compares: no FP accumulation
        )
