"""Bucketizer.

Reference: ``flink-ml-lib/.../feature/bucketizer/Bucketizer.java`` — multi-column:
value in [splits[j], splits[j+1]) → bucket j (last bucket right-inclusive);
values outside the splits or NaN are invalid, handled per ``handleInvalid``:
'error' raises, 'skip' drops the row, 'keep' maps to the extra bucket numSplits-1.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.params.param import Param, ParamValidators
from flink_ml_tpu.params.shared import HasHandleInvalid, HasInputCols, HasOutputCols

__all__ = ["Bucketizer"]


def _splits_valid(splits_array) -> bool:
    if not splits_array:
        return False
    for splits in splits_array:
        if len(splits) < 3:
            return False
        if any(splits[i] >= splits[i + 1] for i in range(len(splits) - 1)):
            return False
    return True


class Bucketizer(Transformer, HasInputCols, HasOutputCols, HasHandleInvalid):
    """Ref Bucketizer.java."""

    SPLITS_ARRAY = Param(
        "splitsArray",
        "Array of split points for mapping continuous features into buckets.",
        None,
        lambda v: v is not None and _splits_valid(v),
    )

    def get_splits_array(self):
        return self.get(self.SPLITS_ARRAY)

    def set_splits_array(self, value):
        return self.set(self.SPLITS_ARRAY, [list(s) for s in value])

    def transform(self, *inputs):
        (df,) = inputs
        in_cols, out_cols = self.get_input_cols(), self.get_output_cols()
        splits_array = self.get_splits_array()
        handle = self.get_handle_invalid()
        if len(in_cols) != len(splits_array):
            raise ValueError("Bucketizer: one splits array per input column required")

        n = len(df)
        keep_mask = np.ones(n, bool)
        buckets = []
        for name, splits in zip(in_cols, splits_array):
            x = df.scalars(name)
            splits = np.asarray(splits, np.float64)
            # bucket j for [splits[j], splits[j+1]); last bucket right-inclusive
            idx = np.searchsorted(splits, x, side="right") - 1
            idx = np.where(x == splits[-1], len(splits) - 2, idx)
            invalid = (x < splits[0]) | (x > splits[-1]) | np.isnan(x)
            if handle == "error" and invalid.any():
                raise ValueError(
                    f"The input contains invalid value {x[invalid][0]} for column {name}. "
                    "See Bucketizer handleInvalid."
                )
            if handle == "keep":
                idx = np.where(invalid, len(splits) - 1, idx)
            else:  # skip
                keep_mask &= ~invalid
            buckets.append(idx.astype(np.float64))

        out = df.clone()
        for out_name, idx in zip(out_cols, buckets):
            out.add_column(out_name, DataTypes.DOUBLE, idx)
        if handle == "skip" and not keep_mask.all():
            out = out.take(np.nonzero(keep_mask)[0])
        return out
