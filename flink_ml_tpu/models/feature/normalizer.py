"""Normalizer.

Reference: ``flink-ml-lib/.../feature/normalizer/Normalizer.java`` — scale each
vector to unit p-norm (p ≥ 1, default 2). The math is the shared ``normalize``
kernel (``ops/kernels.py``), composable into fused batch plans.
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.ops.kernels import normalize_fn, normalize_kernel
from flink_ml_tpu.params.param import FloatParam, ParamValidators
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol
from flink_ml_tpu.servable.kernel_spec import KernelSpec

__all__ = ["Normalizer"]


class Normalizer(Transformer, HasInputCol, HasOutputCol):
    """Ref Normalizer.java."""

    P = FloatParam("p", "The p norm value.", 2.0, ParamValidators.gt_eq(1.0))

    def get_p(self) -> float:
        return self.get(self.P)

    def set_p(self, value: float):
        return self.set(self.P, value)

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        vals = normalize_kernel(float(self.get_p()))(X)
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(vals, np.float64),
        )
        return out

    def kernel_spec(self):
        """Row-wise unit p-norm scaling as a fusable spec — ``normalize_fn``,
        the body ``transform``'s jitted kernel wraps."""
        in_col, out_col, p = self.get_input_col(), self.get_output_col(), float(self.get_p())

        def kernel_fn(model, cols):
            return {out_col: normalize_fn(cols[in_col], p)}

        return KernelSpec(
            input_cols=(in_col,),
            outputs=((out_col, DataTypes.vector(BasicType.DOUBLE)),),
            model_arrays={},
            kernel_fn=kernel_fn,
            fusion_op="normalize",  # row-local reduction: megakernel-safe
        )
