"""Normalizer.

Reference: ``flink-ml-lib/.../feature/normalizer/Normalizer.java`` — scale each
vector to unit p-norm (p ≥ 1, default 2).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.params.param import FloatParam, ParamValidators
from flink_ml_tpu.params.shared import HasInputCol, HasOutputCol

__all__ = ["Normalizer"]


@functools.cache
def _kernel(p: float):
    @jax.jit
    def normalize(X):
        norm = jnp.sum(jnp.abs(X) ** p, axis=1, keepdims=True) ** (1.0 / p)
        return X / jnp.where(norm == 0.0, 1.0, norm)

    return normalize


class Normalizer(Transformer, HasInputCol, HasOutputCol):
    """Ref Normalizer.java."""

    P = FloatParam("p", "The p norm value.", 2.0, ParamValidators.gt_eq(1.0))

    def get_p(self) -> float:
        return self.get(self.P)

    def set_p(self, value: float):
        return self.set(self.P, value)

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_input_col()).astype(np.float64)
        vals = _kernel(self.get_p())(X)
        out = df.clone()
        out.add_column(
            self.get_output_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(vals, np.float64),
        )
        return out
