"""StopWordsRemover.

Reference: ``flink-ml-lib/.../feature/stopwordsremover/StopWordsRemover.java`` —
multi-column token-list filter; ``stopWords`` defaults to the bundled English list
(``loadDefaultStopWords``), ``caseSensitive`` false (locale-aware lowercase
matching), snowball stop-word lists bundled per language (same public-domain data
files as the reference's resources).
"""
from __future__ import annotations

import os
from typing import List

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.params.param import BoolParam, StringArrayParam, StringParam
from flink_ml_tpu.params.shared import HasInputCols, HasOutputCols

__all__ = ["StopWordsRemover"]

_STOPWORDS_DIR = os.path.join(os.path.dirname(__file__), "stopwords")


def _available_languages() -> List[str]:
    return sorted(f[:-4] for f in os.listdir(_STOPWORDS_DIR) if f.endswith(".txt"))


def load_default_stop_words(language: str) -> List[str]:
    """Ref StopWordsRemover.loadDefaultStopWords."""
    path = os.path.join(_STOPWORDS_DIR, f"{language}.txt")
    if not os.path.exists(path):
        raise ValueError(
            f"{language} is not in the supported language list: {_available_languages()}."
        )
    with open(path, encoding="utf-8") as f:
        return [line.strip() for line in f if line.strip()]


def _locale_lower(locale: str):
    """Locale-aware lowercasing for case-insensitive matching (the reference uses
    java.util.Locale). Turkish/Azerbaijani dotted/dotless-i rules are handled
    explicitly; other locales use str.lower() (full ICU tailoring needs an ICU
    dependency this image doesn't ship)."""
    lang = locale.split("_")[0].lower()
    if lang in ("tr", "az"):
        def lower(s: str) -> str:
            return s.replace("İ", "i").replace("I", "ı").lower()

        return lower
    return str.lower


class StopWordsRemover(Transformer, HasInputCols, HasOutputCols):
    """Ref StopWordsRemover.java."""

    STOP_WORDS = StringArrayParam(
        "stopWords", "The words to be filtered out.", load_default_stop_words("english")
    )
    CASE_SENSITIVE = BoolParam(
        "caseSensitive", "Whether to do a case-sensitive comparison over the stop words.", False
    )
    LOCALE = StringParam(
        "locale",
        "Locale of the input for case insensitive matching. Ignored when caseSensitive is true.",
        "en_US",
    )

    load_default_stop_words = staticmethod(load_default_stop_words)
    get_available_locales = staticmethod(_available_languages)

    def get_stop_words(self):
        return self.get(self.STOP_WORDS)

    def set_stop_words(self, *values: str):
        return self.set(self.STOP_WORDS, list(values))

    def get_case_sensitive(self) -> bool:
        return self.get(self.CASE_SENSITIVE)

    def set_case_sensitive(self, value: bool):
        return self.set(self.CASE_SENSITIVE, value)

    def get_locale(self) -> str:
        return self.get(self.LOCALE)

    def set_locale(self, value: str):
        return self.set(self.LOCALE, value)

    def transform(self, *inputs):
        (df,) = inputs
        case_sensitive = self.get_case_sensitive()
        lower = _locale_lower(self.get_locale())
        stop = set(self.get_stop_words())
        if not case_sensitive:
            stop = {lower(w) for w in stop}

        def keep(token: str) -> bool:
            t = token if case_sensitive else lower(token)
            return t not in stop

        out = df.clone()
        for in_name, out_name in zip(self.get_input_cols(), self.get_output_cols()):
            col = df.column(in_name)
            out.add_column(
                out_name, DataTypes.STRING, [[t for t in tokens if keep(t)] for tokens in col]
            )
        return out
