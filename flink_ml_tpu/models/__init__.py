"""L4 — the algorithm library.

Reference: ``flink-ml-lib`` (48 Stage implementations, SURVEY.md §2.5). Mirrors the
reference's package-per-group layout: ``classification``, ``clustering``, ``feature``,
``regression``, ``evaluation``, ``stats``, ``recommendation``.

``STAGE_REGISTRY`` maps public stage name → dotted class path. It is the single
source of truth for persistence dispatch and for the completeness test (the analogue
of the reference's ``test_ml_lib_completeness.py:31``): every stage the framework
claims must be importable from here.
"""
import importlib

STAGE_REGISTRY = {
    # classification
    "LogisticRegression": "flink_ml_tpu.models.classification.logistic_regression.LogisticRegression",
    "LogisticRegressionModel": "flink_ml_tpu.models.classification.logistic_regression.LogisticRegressionModel",
    "LinearSVC": "flink_ml_tpu.models.classification.linearsvc.LinearSVC",
    "LinearSVCModel": "flink_ml_tpu.models.classification.linearsvc.LinearSVCModel",
    "MLPClassifier": "flink_ml_tpu.models.classification.mlp_classifier.MLPClassifier",
    "MLPClassifierModel": "flink_ml_tpu.models.classification.mlp_classifier.MLPClassifierModel",
    "NaiveBayes": "flink_ml_tpu.models.classification.naive_bayes.NaiveBayes",
    "NaiveBayesModel": "flink_ml_tpu.models.classification.naive_bayes.NaiveBayesModel",
    "Knn": "flink_ml_tpu.models.classification.knn.Knn",
    "KnnModel": "flink_ml_tpu.models.classification.knn.KnnModel",
    "OnlineLogisticRegression": "flink_ml_tpu.models.classification.online_logistic_regression.OnlineLogisticRegression",
    "OnlineLogisticRegressionModel": "flink_ml_tpu.models.classification.online_logistic_regression.OnlineLogisticRegressionModel",
    "SelfAttentionClassifier": "flink_ml_tpu.models.classification.attention_classifier.SelfAttentionClassifier",
    "SelfAttentionClassifierModel": "flink_ml_tpu.models.classification.attention_classifier.SelfAttentionClassifierModel",
    # clustering
    "KMeans": "flink_ml_tpu.models.clustering.kmeans.KMeans",
    "KMeansModel": "flink_ml_tpu.models.clustering.kmeans.KMeansModel",
    "OnlineKMeans": "flink_ml_tpu.models.clustering.online_kmeans.OnlineKMeans",
    "OnlineKMeansModel": "flink_ml_tpu.models.clustering.online_kmeans.OnlineKMeansModel",
    "AgglomerativeClustering": "flink_ml_tpu.models.clustering.agglomerative_clustering.AgglomerativeClustering",
    # evaluation / stats / recommendation
    "BinaryClassificationEvaluator": "flink_ml_tpu.models.evaluation.binary_classification_evaluator.BinaryClassificationEvaluator",
    "ChiSqTest": "flink_ml_tpu.models.stats.tests.ChiSqTest",
    "ANOVATest": "flink_ml_tpu.models.stats.tests.ANOVATest",
    "FValueTest": "flink_ml_tpu.models.stats.tests.FValueTest",
    "Swing": "flink_ml_tpu.models.recommendation.swing.Swing",
    # feature (stateless)
    "Binarizer": "flink_ml_tpu.models.feature.binarizer.Binarizer",
    "Bucketizer": "flink_ml_tpu.models.feature.bucketizer.Bucketizer",
    "DCT": "flink_ml_tpu.models.feature.dct.DCT",
    "ElementwiseProduct": "flink_ml_tpu.models.feature.elementwise_product.ElementwiseProduct",
    "FeatureHasher": "flink_ml_tpu.models.feature.feature_hasher.FeatureHasher",
    "HashingTF": "flink_ml_tpu.models.feature.hashing_tf.HashingTF",
    "Interaction": "flink_ml_tpu.models.feature.interaction.Interaction",
    "NGram": "flink_ml_tpu.models.feature.ngram.NGram",
    "Normalizer": "flink_ml_tpu.models.feature.normalizer.Normalizer",
    "PolynomialExpansion": "flink_ml_tpu.models.feature.polynomial_expansion.PolynomialExpansion",
    "RandomSplitter": "flink_ml_tpu.models.feature.random_splitter.RandomSplitter",
    "RegexTokenizer": "flink_ml_tpu.models.feature.tokenizer.RegexTokenizer",
    "SQLTransformer": "flink_ml_tpu.models.feature.sql_transformer.SQLTransformer",
    "StopWordsRemover": "flink_ml_tpu.models.feature.stop_words_remover.StopWordsRemover",
    "Tokenizer": "flink_ml_tpu.models.feature.tokenizer.Tokenizer",
    "VectorAssembler": "flink_ml_tpu.models.feature.vector_assembler.VectorAssembler",
    "VectorSlicer": "flink_ml_tpu.models.feature.vector_slicer.VectorSlicer",
    # feature (fitted)
    "CountVectorizer": "flink_ml_tpu.models.feature.count_vectorizer.CountVectorizer",
    "CountVectorizerModel": "flink_ml_tpu.models.feature.count_vectorizer.CountVectorizerModel",
    "IDF": "flink_ml_tpu.models.feature.idf.IDF",
    "IDFModel": "flink_ml_tpu.models.feature.idf.IDFModel",
    "Imputer": "flink_ml_tpu.models.feature.imputer.Imputer",
    "ImputerModel": "flink_ml_tpu.models.feature.imputer.ImputerModel",
    "IndexToStringModel": "flink_ml_tpu.models.feature.string_indexer.IndexToStringModel",
    "KBinsDiscretizer": "flink_ml_tpu.models.feature.kbins_discretizer.KBinsDiscretizer",
    "KBinsDiscretizerModel": "flink_ml_tpu.models.feature.kbins_discretizer.KBinsDiscretizerModel",
    "MaxAbsScaler": "flink_ml_tpu.models.feature.scalers.MaxAbsScaler",
    "MaxAbsScalerModel": "flink_ml_tpu.models.feature.scalers.MaxAbsScalerModel",
    "MinHashLSH": "flink_ml_tpu.models.feature.lsh.MinHashLSH",
    "MinHashLSHModel": "flink_ml_tpu.models.feature.lsh.MinHashLSHModel",
    "MinMaxScaler": "flink_ml_tpu.models.feature.scalers.MinMaxScaler",
    "MinMaxScalerModel": "flink_ml_tpu.models.feature.scalers.MinMaxScalerModel",
    "OneHotEncoder": "flink_ml_tpu.models.feature.one_hot_encoder.OneHotEncoder",
    "OneHotEncoderModel": "flink_ml_tpu.models.feature.one_hot_encoder.OneHotEncoderModel",
    "RobustScaler": "flink_ml_tpu.models.feature.scalers.RobustScaler",
    "RobustScalerModel": "flink_ml_tpu.models.feature.scalers.RobustScalerModel",
    "StringIndexer": "flink_ml_tpu.models.feature.string_indexer.StringIndexer",
    "StringIndexerModel": "flink_ml_tpu.models.feature.string_indexer.StringIndexerModel",
    "UnivariateFeatureSelector": "flink_ml_tpu.models.feature.univariate_feature_selector.UnivariateFeatureSelector",
    "UnivariateFeatureSelectorModel": "flink_ml_tpu.models.feature.univariate_feature_selector.UnivariateFeatureSelectorModel",
    "VarianceThresholdSelector": "flink_ml_tpu.models.feature.variance_threshold_selector.VarianceThresholdSelector",
    "VarianceThresholdSelectorModel": "flink_ml_tpu.models.feature.variance_threshold_selector.VarianceThresholdSelectorModel",
    "VectorIndexer": "flink_ml_tpu.models.feature.vector_indexer.VectorIndexer",
    "VectorIndexerModel": "flink_ml_tpu.models.feature.vector_indexer.VectorIndexerModel",
    "StandardScaler": "flink_ml_tpu.models.feature.standard_scaler.StandardScaler",
    "StandardScalerModel": "flink_ml_tpu.models.feature.standard_scaler.StandardScalerModel",
    "OnlineStandardScaler": "flink_ml_tpu.models.feature.standard_scaler.OnlineStandardScaler",
    "OnlineStandardScalerModel": "flink_ml_tpu.models.feature.standard_scaler.OnlineStandardScalerModel",
    # regression
    "LinearRegression": "flink_ml_tpu.models.regression.linear_regression.LinearRegression",
    "LinearRegressionModel": "flink_ml_tpu.models.regression.linear_regression.LinearRegressionModel",
}


def get_stage_class(name: str):
    dotted = STAGE_REGISTRY[name]
    module_name, _, cls_name = dotted.rpartition(".")
    return getattr(importlib.import_module(module_name), cls_name)
