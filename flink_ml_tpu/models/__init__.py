"""L4 — the algorithm library.

Reference: ``flink-ml-lib`` (48 Stage implementations, SURVEY.md §2.5). Mirrors the
reference's package-per-group layout: ``classification``, ``clustering``, ``feature``,
``regression``, ``evaluation``, ``stats``, ``recommendation``.

``STAGE_REGISTRY`` maps public stage name → dotted class path. It is the single
source of truth for persistence dispatch and for the completeness test (the analogue
of the reference's ``test_ml_lib_completeness.py:31``): every stage the framework
claims must be importable from here.
"""
import importlib

STAGE_REGISTRY = {
    # classification
    "LogisticRegression": "flink_ml_tpu.models.classification.logistic_regression.LogisticRegression",
    "LogisticRegressionModel": "flink_ml_tpu.models.classification.logistic_regression.LogisticRegressionModel",
    "LinearSVC": "flink_ml_tpu.models.classification.linearsvc.LinearSVC",
    "LinearSVCModel": "flink_ml_tpu.models.classification.linearsvc.LinearSVCModel",
    "OnlineLogisticRegression": "flink_ml_tpu.models.classification.online_logistic_regression.OnlineLogisticRegression",
    "OnlineLogisticRegressionModel": "flink_ml_tpu.models.classification.online_logistic_regression.OnlineLogisticRegressionModel",
    # clustering
    "KMeans": "flink_ml_tpu.models.clustering.kmeans.KMeans",
    "KMeansModel": "flink_ml_tpu.models.clustering.kmeans.KMeansModel",
    "OnlineKMeans": "flink_ml_tpu.models.clustering.online_kmeans.OnlineKMeans",
    "OnlineKMeansModel": "flink_ml_tpu.models.clustering.online_kmeans.OnlineKMeansModel",
    # feature
    "StandardScaler": "flink_ml_tpu.models.feature.standard_scaler.StandardScaler",
    "StandardScalerModel": "flink_ml_tpu.models.feature.standard_scaler.StandardScalerModel",
    "OnlineStandardScaler": "flink_ml_tpu.models.feature.standard_scaler.OnlineStandardScaler",
    "OnlineStandardScalerModel": "flink_ml_tpu.models.feature.standard_scaler.OnlineStandardScalerModel",
    # regression
    "LinearRegression": "flink_ml_tpu.models.regression.linear_regression.LinearRegression",
    "LinearRegressionModel": "flink_ml_tpu.models.regression.linear_regression.LinearRegressionModel",
}


def get_stage_class(name: str):
    dotted = STAGE_REGISTRY[name]
    module_name, _, cls_name = dotted.rpartition(".")
    return getattr(importlib.import_module(module_name), cls_name)
