"""L4 — the algorithm library.

Reference: ``flink-ml-lib`` (48 Stage implementations, SURVEY.md §2.5). Mirrors the
reference's package-per-group layout: ``classification``, ``clustering``, ``feature``,
``regression``, ``evaluation``, ``stats``, ``recommendation``.

``STAGE_REGISTRY`` maps public stage name → dotted class path. It is the single
source of truth for persistence dispatch and for the completeness test (the analogue
of the reference's ``test_ml_lib_completeness.py:31``): every stage the framework
claims must be importable from here.
"""
import importlib

STAGE_REGISTRY = {
    # classification
    "LogisticRegression": "flink_ml_tpu.models.classification.logistic_regression.LogisticRegression",
    "LogisticRegressionModel": "flink_ml_tpu.models.classification.logistic_regression.LogisticRegressionModel",
    "LinearSVC": "flink_ml_tpu.models.classification.linearsvc.LinearSVC",
    "LinearSVCModel": "flink_ml_tpu.models.classification.linearsvc.LinearSVCModel",
    # clustering
    "KMeans": "flink_ml_tpu.models.clustering.kmeans.KMeans",
    "KMeansModel": "flink_ml_tpu.models.clustering.kmeans.KMeansModel",
    # regression
    "LinearRegression": "flink_ml_tpu.models.regression.linear_regression.LinearRegression",
    "LinearRegressionModel": "flink_ml_tpu.models.regression.linear_regression.LinearRegressionModel",
}


def get_stage_class(name: str):
    dotted = STAGE_REGISTRY[name]
    module_name, _, cls_name = dotted.rpartition(".")
    return getattr(importlib.import_module(module_name), cls_name)
