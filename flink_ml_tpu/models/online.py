"""Shared infrastructure for online (unbounded-stream) estimators and models.

Reference pattern (SURVEY.md §2.5 online algos, §5.7): an online Estimator's fit
wires an ``iterateUnboundedStreams`` dataflow that emits a *stream of versioned model
data*; the Model holds that model-data stream and serves predictions with whatever
version has arrived, exporting ``ml.model.version`` gauges.

Single-controller mapping: the fitted model owns a Python generator of model
snapshots. ``advance(n)`` pulls up to n snapshots (= n training windows) and applies
them — the explicit handle on "how far has training consumed the stream" that the
reference leaves to Flink's scheduler. Bounded inputs are trained eagerly in fit()
(the batch-user experience); unbounded inputs (any iterator of batches, e.g.
``QueueBatchStream``) stay lazy so tests and services can interleave feeding,
training, and serving — the InMemorySourceFunction workflow.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.iteration.stream import Batch, batch_stream_from_dataframe, rebatch
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.models.common import ModelArraysMixin

__all__ = ["QueueBatchStream", "OnlineModelBase", "SnapshotDriver", "as_batch_stream"]


class QueueBatchStream:
    """An in-memory feedable batch stream — the InMemorySourceFunction analogue.

    Tests/services ``add`` columnar batches (or DataFrames) and the training side
    pulls them; iteration ends when ``close()`` has been called and the queue is
    drained. Pulling from an empty-but-open stream raises ``StreamDry`` rather than
    blocking, so a single-threaded test can interleave add/advance deterministically.
    """

    class StreamDry(Exception):
        pass

    def __init__(self):
        self._queue: deque = deque()
        self._closed = False

    def add(self, batch) -> "QueueBatchStream":
        if self._closed:
            raise RuntimeError("stream is closed")
        self._queue.append(batch)
        return self

    def close(self) -> "QueueBatchStream":
        self._closed = True
        return self

    def __iter__(self):
        return self

    def __next__(self):
        while self._queue:
            item = self._queue.popleft()
            if isinstance(item, DataFrame):
                if item.num_rows == 0:
                    continue  # empty frames are not end-of-stream
                item = next(batch_stream_from_dataframe(item))
            elif item and next(iter(item.values())).shape[0] == 0:
                continue
            return item
        if self._closed:
            raise StopIteration
        raise QueueBatchStream.StreamDry(
            "no batch available; add() more data or close() the stream"
        )


def as_batch_stream(data, batch_size: Optional[int] = None) -> Tuple[Iterator[Batch], bool]:
    """Normalize fit() input → (batch iterator, is_bounded).

    Note for unbounded feedable streams: ``rebatch`` (a generator) would be killed
    permanently by a propagating StreamDry, so re-chunking is only applied to
    bounded inputs; a QueueBatchStream's batches are consumed as added.
    """
    if isinstance(data, DataFrame):
        return batch_stream_from_dataframe(data, batch_size), True
    if isinstance(data, QueueBatchStream):
        return data, False
    it = iter(data)
    if batch_size is not None:
        it = rebatch(it, batch_size, drop_last=False)
    return it, False


class SnapshotDriver:
    """Resumable iterator of (version, payload) model snapshots.

    One ``__next__`` = pull one batch from the input stream, run ``step_fn`` on it,
    emit the new snapshot. Implemented as a plain object (not a generator) so a
    ``StreamDry`` from a feedable stream propagates to the caller WITHOUT
    terminating training state — Python generators die on any raised exception.
    """

    def __init__(self, stream: Iterator[Batch], step_fn, state: Any):
        self._stream = stream
        self._step = step_fn
        self.state = state
        self.version = 0

    def __iter__(self):
        return self

    def __next__(self) -> Tuple[int, Any]:
        batch = next(self._stream)  # may raise StopIteration or StreamDry
        self.state, payload = self._step(self.state, batch)
        self.version += 1
        return self.version, payload


class OnlineModelBase(ModelArraysMixin, Model):
    """A Model fed by a stream of versioned snapshots.

    Subclasses implement ``_apply_snapshot(payload)`` to install one model version.
    The estimator attaches the training generator via ``_attach_stream``.
    """

    def __init__(self):
        super().__init__()
        self.model_version: int = 0
        self._snapshots: Iterator[Tuple[int, Any]] = iter(())
        self.version_history: List[int] = []

    # -- wiring ---------------------------------------------------------------
    def _attach_stream(self, snapshots: Iterator[Tuple[int, Any]]) -> None:
        self._snapshots = snapshots

    def _metric_scope(self) -> str:
        return f"{type(self).__name__}@{id(self):x}"

    def _apply_snapshot(self, payload: Any) -> None:
        raise NotImplementedError

    # -- persistence: model version travels with the model data ---------------
    # (the reference's model-data records carry modelVersion, e.g.
    # LogisticRegressionModelData(coefficient, modelVersion))
    def save(self, path: str) -> None:
        from flink_ml_tpu.utils import read_write as rw

        extra = {"modelVersion": self.model_version}
        # Models gated on event time must keep their freshness across
        # save/load — a loaded model with -inf timestamp would buffer every
        # timestamped row forever. ±inf survives json (Python emits Infinity).
        ts = getattr(self, "model_timestamp", None)
        if ts is not None:
            extra["modelTimestamp"] = float(ts)
        rw.save_metadata(self, path, extra)
        rw.save_model_arrays(path, self._model_arrays())

    @classmethod
    def load(cls, path: str):
        from flink_ml_tpu.utils import read_write as rw

        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        model._set_model_arrays(rw.load_model_arrays(path))
        model.model_version = metadata.get("modelVersion", 0)
        if hasattr(model, "model_timestamp"):
            # Legacy checkpoints lack the field: default to +inf (ungated) —
            # a -inf default would silently buffer every timestamped row.
            model.model_timestamp = float(metadata.get("modelTimestamp", float("inf")))
        return model

    # -- the public online surface -------------------------------------------
    def advance(self, n: Optional[int] = None) -> int:
        """Consume up to ``n`` model snapshots (None = until the stream ends);
        returns how many were applied. Each applied snapshot bumps
        ``ml.model.version`` / ``ml.model.timestamp`` gauges."""
        import time

        applied = 0
        while n is None or applied < n:
            try:
                version, payload = next(self._snapshots)
            except StopIteration:
                break
            except QueueBatchStream.StreamDry:
                break
            self._apply_snapshot(payload)
            self.model_version = version
            self.version_history.append(version)
            scope = self._metric_scope()
            metrics.gauge(scope, MLMetrics.VERSION, version)
            metrics.gauge(scope, MLMetrics.TIMESTAMP, int(time.time() * 1000))
            applied += 1
        return applied
