"""Shared infrastructure for online (unbounded-stream) estimators and models.

Reference pattern (SURVEY.md §2.5 online algos, §5.7): an online Estimator's fit
wires an ``iterateUnboundedStreams`` dataflow that emits a *stream of versioned model
data*; the Model holds that model-data stream and serves predictions with whatever
version has arrived, exporting ``ml.model.version`` gauges.

Single-controller mapping: the fitted model owns a Python generator of model
snapshots. ``advance(n)`` pulls up to n snapshots (= n training windows) and applies
them — the explicit handle on "how far has training consumed the stream" that the
reference leaves to Flink's scheduler. Bounded inputs are trained eagerly in fit()
(the batch-user experience); unbounded inputs (any iterator of batches, e.g.
``QueueBatchStream``) stay lazy so tests and services can interleave feeding,
training, and serving — the InMemorySourceFunction workflow.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.faults import faults
from flink_ml_tpu.iteration.stream import Batch, batch_stream_from_dataframe, rebatch
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.models.common import ModelArraysMixin

__all__ = [
    "QueueBatchStream",
    "OnlineModelBase",
    "SnapshotDriver",
    "as_batch_stream",
    "HasCheckpointing",
    "online_fingerprint",
]


class QueueBatchStream:
    """An in-memory feedable batch stream — the InMemorySourceFunction analogue.

    Tests/services ``add`` columnar batches (or DataFrames) and the training side
    pulls them; iteration ends when ``close()`` has been called and the queue is
    drained. Pulling from an empty-but-open stream raises ``StreamDry`` rather than
    blocking, so a single-threaded test can interleave add/advance deterministically.
    """

    class StreamDry(Exception):
        pass

    def __init__(self):
        self._queue: deque = deque()
        self._closed = False

    def add(self, batch) -> "QueueBatchStream":
        if self._closed:
            raise RuntimeError("stream is closed")
        self._queue.append(batch)
        return self

    def close(self) -> "QueueBatchStream":
        self._closed = True
        return self

    def __iter__(self):
        return self

    def __next__(self):
        while self._queue:
            item = self._queue.popleft()
            if isinstance(item, DataFrame):
                if item.num_rows == 0:
                    continue  # empty frames are not end-of-stream
                item = next(batch_stream_from_dataframe(item))
            elif item and next(iter(item.values())).shape[0] == 0:
                continue
            return item
        if self._closed:
            raise StopIteration
        raise QueueBatchStream.StreamDry(
            "no batch available; add() more data or close() the stream"
        )


def as_batch_stream(data, batch_size: Optional[int] = None) -> Tuple[Iterator[Batch], bool]:
    """Normalize fit() input → (batch iterator, is_bounded).

    Note for unbounded feedable streams: ``rebatch`` (a generator) would be killed
    permanently by a propagating StreamDry, so re-chunking is only applied to
    bounded inputs; a QueueBatchStream's batches are consumed as added.
    """
    if isinstance(data, DataFrame):
        return batch_stream_from_dataframe(data, batch_size), True
    if isinstance(data, QueueBatchStream):
        return data, False
    it = iter(data)
    if batch_size is not None:
        it = rebatch(it, batch_size, drop_last=False)
    return it, False


class HasCheckpointing:
    """Opt-in kill/resume for online estimators.

    The reference makes online training recoverable by checkpointing *source
    offsets alongside operator state* (Checkpoints.java:43-143; SGD's
    batch-offset state SGD.java:308-347). Here the estimator hands a
    ``CheckpointManager`` to its ``SnapshotDriver``, which snapshots
    ``(version, batches_consumed, training state, last payload)`` and, on
    resume, fast-forwards the re-fed source past the consumed prefix.

    Resume contract (the replayable-source contract): after a crash, re-create
    the estimator with the same params and the same checkpoint directory, and
    feed a source that replays the stream **from the beginning** (or one that
    implements ``skip(n)`` to seek). The driver discards the first
    ``batches_consumed`` batches and training continues at the next unseen
    batch with the next model version — no version reuse, no gap.
    """

    def set_checkpoint(self, manager, interval: int = 1):
        """Install a ``flink_ml_tpu.checkpoint.CheckpointManager`` (+ snapshot
        every ``interval`` model versions). Returns self for chaining."""
        self._checkpoint_manager = manager
        self._checkpoint_interval = interval
        return self

    def _checkpointing(self) -> Tuple[Any, int]:
        return (
            getattr(self, "_checkpoint_manager", None),
            getattr(self, "_checkpoint_interval", 1),
        )

    def _snapshot_driver(
        self, stream, step_fn, state, payload_from_state=None, **fingerprint_extra
    ) -> "SnapshotDriver":
        """The one checkpoint-wiring path shared by every online estimator:
        install the config fingerprint, then build the (possibly resuming)
        driver."""
        mgr, interval = self._checkpointing()
        if mgr is not None:
            mgr.set_fingerprint(online_fingerprint(self, **fingerprint_extra))
        return SnapshotDriver(
            stream,
            step_fn,
            state,
            checkpoint_manager=mgr,
            checkpoint_interval=interval,
            payload_from_state=payload_from_state,
        )


def online_fingerprint(estimator, **extra) -> str:
    """Run/config identity for online checkpoints (cf. SGD._run_fingerprint):
    a differently-configured job pointed at the same directory must refuse to
    resume rather than silently continue stale state."""
    import hashlib
    import json

    sig = {"class": type(estimator).__name__, "params": estimator.param_map_to_json()}
    sig.update(extra)
    return hashlib.sha256(json.dumps(sig, sort_keys=True, default=str).encode()).hexdigest()


def array_digest(*arrays) -> str:
    """Content hash of initial-model arrays for the resume fingerprint — a run
    warm-started from *different* initial data is a different run even when
    every param matches."""
    import hashlib

    h = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


class SnapshotDriver:
    """Resumable iterator of (version, payload) model snapshots.

    One ``__next__`` = pull one batch from the input stream, run ``step_fn`` on it,
    emit the new snapshot. Implemented as a plain object (not a generator) so a
    ``StreamDry`` from a feedable stream propagates to the caller WITHOUT
    terminating training state — Python generators die on any raised exception.

    With ``checkpoint_manager`` the driver snapshots
    ``{version, batches_consumed, state, payload}`` every
    ``checkpoint_interval`` versions and restores the newest snapshot at
    construction; the restored snapshot's stream offset is consumed *lazily*
    on the first ``__next__`` calls (`skip(n)` on the source when available,
    else drop-and-discard), so a feedable stream that has not been re-fed the
    full prefix yet raises StreamDry without losing the skip position — the
    single-controller analogue of the reference's checkpointed source offsets
    (Checkpoints.java, SGD.java:308-347).
    """

    def __init__(
        self,
        stream: Iterator[Batch],
        step_fn,
        state: Any,
        checkpoint_manager=None,
        checkpoint_interval: int = 1,
        payload_from_state=None,
    ):
        self._stream = stream
        self._step = step_fn
        self.state = state
        self.version = 0
        self._mgr = checkpoint_manager
        self._interval = max(1, int(checkpoint_interval))
        # With payload_from_state the snapshot stores only the training state
        # (the payload is a view of it — e.g. the FTRL coefficient) instead of
        # writing the arrays twice per checkpoint.
        self._payload_from_state = payload_from_state
        self._to_skip = 0
        # The in-flight mini-batch: pulled from the source but not yet
        # committed as a model version. A retryable fault inside the step
        # (collective abort, injected fault) must not lose it — a feedable
        # source like QueueBatchStream cannot replay — so a supervised retry
        # of __next__ redelivers it instead of pulling a fresh batch (the
        # analogue of the reference snapshotting in-flight feedback records).
        self._inflight: Optional[Batch] = None
        self.resumed = False
        self.restored_payload: Any = None
        if self._mgr is not None:
            restored = self._mgr.restore_latest()
            if restored is not None:
                # The manager's step IS the version IS the stream offset: one
                # __next__ consumes exactly one batch.
                step, snap = restored
                self.version = int(step)
                self.state = snap["state"]
                self.resumed = True
                self.restored_payload = (
                    payload_from_state(self.state)
                    if payload_from_state is not None
                    else snap["payload"]
                )
                self._to_skip = self.version
                if self._to_skip and hasattr(self._stream, "skip"):
                    self._stream.skip(self._to_skip)
                    self._to_skip = 0

    def resume_into(self, model: "OnlineModelBase", version_offset: int = 0) -> None:
        """Install the restored snapshot on a model (no-op on a fresh run)."""
        if self.resumed:
            model._apply_snapshot(self.restored_payload)
            model.model_version = self.version + version_offset

    def __iter__(self):
        return self

    def __next__(self) -> Tuple[int, Any]:
        while self._to_skip > 0:
            try:
                next(self._stream)  # replayed prefix; may raise StreamDry
            except StopIteration:
                # A closed source ending INSIDE the known-consumed prefix is a
                # replay-contract violation — ending here must not look like a
                # clean end of training.
                raise ValueError(
                    f"replayed source ended {self._to_skip} batch(es) before the "
                    f"checkpointed offset {self.version}; on resume the source "
                    "must replay the stream from the beginning"
                ) from None
            self._to_skip -= 1
        if self._inflight is None:
            # may raise StopIteration or StreamDry
            self._inflight = next(self._stream)
        batch = self._inflight
        faults.trip("online.step", version=self.version + 1)
        self.state, payload = self._step(self.state, batch)
        self._inflight = None  # committed: version counter owns it from here
        self.version += 1
        if self._mgr is not None and self.version % self._interval == 0:
            snap = {"state": self.state}
            if self._payload_from_state is None:
                snap["payload"] = payload
            self._mgr.save(self.version, snap)
        return self.version, payload


class OnlineModelBase(ModelArraysMixin, Model):
    """A Model fed by a stream of versioned snapshots.

    Subclasses implement ``_apply_snapshot(payload)`` to install one model version.
    The estimator attaches the training generator via ``_attach_stream``.
    """

    #: Injectable wall clock (seconds) behind the ml.model.timestamp gauge.
    #: Class-level default; tests pin ``model.clock`` to a fixed value and
    #: assert on the gauge without racing real time.
    clock: Callable[[], float] = staticmethod(time.time)

    def __init__(self):
        super().__init__()
        self.model_version: int = 0
        self._snapshots: Iterator[Tuple[int, Any]] = iter(())
        self.version_history: List[int] = []

    # -- wiring ---------------------------------------------------------------
    def _attach_stream(self, snapshots: Iterator[Tuple[int, Any]]) -> None:
        self._snapshots = snapshots

    def _metric_scope(self) -> str:
        return f"{type(self).__name__}@{id(self):x}"

    def _apply_snapshot(self, payload: Any) -> None:
        raise NotImplementedError

    # -- persistence: model version travels with the model data ---------------
    # (the reference's model-data records carry modelVersion, e.g.
    # LogisticRegressionModelData(coefficient, modelVersion))
    def save(self, path: str) -> None:
        from flink_ml_tpu.utils import read_write as rw

        extra = {"modelVersion": self.model_version}
        # Models gated on event time must keep their freshness across
        # save/load — a loaded model with -inf timestamp would buffer every
        # timestamped row forever. ±inf survives json (Python emits Infinity).
        ts = getattr(self, "model_timestamp", None)
        if ts is not None:
            extra["modelTimestamp"] = float(ts)
        rw.save_metadata(self, path, extra)
        rw.save_model_arrays(path, self._model_arrays())

    @classmethod
    def load(cls, path: str):
        from flink_ml_tpu.utils import read_write as rw

        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        model._set_model_arrays(rw.load_model_arrays(path))
        model.model_version = metadata.get("modelVersion", 0)
        if hasattr(model, "model_timestamp"):
            # Legacy checkpoints lack the field: default to +inf (ungated) —
            # a -inf default would silently buffer every timestamped row.
            model.model_timestamp = float(metadata.get("modelTimestamp", float("inf")))
        return model

    # -- the public online surface -------------------------------------------
    def advance(
        self,
        n: Optional[int] = None,
        on_snapshot: Optional[Callable[[int, Any], None]] = None,
    ) -> int:
        """Consume up to ``n`` model snapshots (None = until the stream ends);
        returns how many were applied. Each applied snapshot bumps
        ``ml.model.version`` / ``ml.model.timestamp`` gauges.

        ``on_snapshot(version, payload)`` fires after each snapshot is
        installed — the per-version seam continuous consumers hook (the
        publish cadence of ``loop/trainer.py`` rides here, so a publisher
        observes every version boundary without stepping the stream one
        snapshot at a time). An exception from the callback propagates with
        the snapshot already applied and counted: training state is intact
        and a supervised retry resumes at the NEXT version."""
        applied = 0
        while n is None or applied < n:
            try:
                version, payload = next(self._snapshots)
            except StopIteration:
                break
            except QueueBatchStream.StreamDry:
                break
            self._apply_snapshot(payload)
            self.model_version = version
            self.version_history.append(version)
            scope = self._metric_scope()
            metrics.gauge(scope, MLMetrics.VERSION, version)
            metrics.gauge(scope, MLMetrics.TIMESTAMP, int(self.clock() * 1000))
            applied += 1
            if on_snapshot is not None:
                on_snapshot(version, payload)
        return applied
