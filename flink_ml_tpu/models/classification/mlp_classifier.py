"""MLP classifier — the framework's deep-model flagship.

Not present in the reference (its models are single coefficient vectors;
SURVEY.md §2.9: no deep nets anywhere in the tree). This is the "new
flink-ml-lib algo; JAX-native" called for by BASELINE.json's config list: a
fully-connected relu network with softmax cross-entropy, trained data-parallel
over the mesh with the same Stage/Estimator contract as every other algorithm.

TPU mapping: one epoch = one jit'd SPMD step (shard_map) — minibatch gather on
the local shard, forward/backward as bf16-friendly matmuls on the MXU, a single
psum over the summed gradients, identical replicated adam update (optax) on
every device. The feedback edge carries the (params, opt_state, offset) pytree
in HBM.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.iteration import DeviceDataCache
from flink_ml_tpu.models.common import extract_labeled_data
from flink_ml_tpu.ops.optimizer import (
    _cache_put,
    chunked_schedule,
    fused_chunk_len,
    offset_schedule,
)
from flink_ml_tpu.params.param import (
    IntArrayParam,
    ParamValidators,
    StringParam,
    update_existing_params,
)
from flink_ml_tpu.params.shared import (
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasSeed,
    HasTol,
)
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.parallel.mesh import DATA_AXIS, MeshContext, get_mesh_context
from flink_ml_tpu.parallel.train_sharding import resolve_train_sharding
from flink_ml_tpu.utils import read_write as rw

__all__ = ["MLPClassifier", "MLPClassifierModel"]


class _MlpParams(
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasRawPredictionCol,
    HasMaxIter,
    HasLearningRate,
    HasGlobalBatchSize,
    HasTol,
    HasSeed,
):
    HIDDEN_LAYERS = IntArrayParam(
        "hiddenLayers",
        "Sizes of the hidden layers.",
        [64],
        ParamValidators.non_empty_array(),
    )
    COMPUTE_TYPE = StringParam(
        "computeType",
        "Matmul compute dtype: 'bfloat16' runs forward/backward matmuls on "
        "the MXU's native bf16 path (params, optimizer state and loss stay "
        "float32 — standard mixed precision); 'float32' is exact.",
        "float32",
        ParamValidators.in_array(["float32", "bfloat16"]),
    )

    def get_hidden_layers(self):
        return self.get(self.HIDDEN_LAYERS)

    def set_hidden_layers(self, *values: int):
        return self.set(self.HIDDEN_LAYERS, list(values))

    def get_compute_type(self) -> str:
        return self.get(self.COMPUTE_TYPE)

    def set_compute_type(self, value: str):
        return self.set(self.COMPUTE_TYPE, value)

    def _compute_dtype(self):
        return jnp.bfloat16 if self.get_compute_type() == "bfloat16" else None



def _mlp_flops_per_epoch(dims, local_batch, n_data):
    """Matmul FLOPs of one global minibatch epoch (fwd 2 + bwd 4 madds per
    weight per row) — the dispatch-length cost model shared by the resident
    and streamed fits."""
    return 6.0 * local_batch * n_data * sum(a * b for a, b in zip(dims[:-1], dims[1:]))


def _init_params(rng: np.random.Generator, dims: List[int]) -> List[Tuple[np.ndarray, np.ndarray]]:
    params = []
    for d_in, d_out in zip(dims[:-1], dims[1:]):
        scale = np.sqrt(2.0 / d_in)
        params.append(
            (
                (rng.normal(size=(d_in, d_out)) * scale).astype(np.float32),
                np.zeros(d_out, np.float32),
            )
        )
    return params


def _forward(params, X, compute_dtype=None):
    """Logits. With ``compute_dtype`` (mixed precision) inputs and weights are
    cast per-matmul so the MXU runs its native low-precision path; the casts
    are differentiable, so gradients come back in the params' float32."""
    cast = (lambda a: a.astype(compute_dtype)) if compute_dtype is not None else (lambda a: a)
    h = cast(X)
    for W, b in params[:-1]:
        h = jax.nn.relu(h @ cast(W) + cast(b))
    W, b = params[-1]
    return h @ cast(W) + cast(b)  # logits


@functools.cache
def _predict_kernel(compute_type: str = "float32"):
    compute_dtype = jnp.bfloat16 if compute_type == "bfloat16" else None

    @jax.jit
    def kernel(params, X):
        logits = _forward(params, X, compute_dtype).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.argmax(logits, axis=-1).astype(jnp.float32), probs

    return kernel


class MLPClassifierModel(Model, _MlpParams):
    """Serving side: one jit'd forward pass; prediction = argmax class index."""

    def __init__(self):
        super().__init__()
        self.params: Optional[list] = None
        self.labels: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float32)
        pred_idx, probs = _predict_kernel(self.get_compute_type())(
            [tuple(jnp.asarray(x) for x in layer) for layer in self.params], X
        )
        pred = self.labels[np.asarray(pred_idx, np.int64)]
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64))
        out.add_column(
            self.get_raw_prediction_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(probs, np.float64),
        )
        return out

    # --- persistence ---------------------------------------------------------
    def save(self, path: str) -> None:
        rw.save_metadata(self, path, {"numLayers": len(self.params)})
        arrays = {"labels": self.labels}
        for i, (W, b) in enumerate(self.params):
            arrays[f"W{i}"] = np.asarray(W)
            arrays[f"b{i}"] = np.asarray(b)
        rw.save_model_arrays(path, arrays)

    @classmethod
    def load(cls, path: str):
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        arrays = rw.load_model_arrays(path)
        model.labels = arrays["labels"]
        model.params = [
            (arrays[f"W{i}"], arrays[f"b{i}"]) for i in range(metadata["numLayers"])
        ]
        return model

    @classmethod
    def load_servable(cls, path: str):
        """A saved MLP serves runtime-free through
        ``MLPClassifierModelServable`` (same W{i}/b{i}/labels arrays, same
        param names) — the weight-resident throughput serving path and the
        ``publish_servable`` hook for continuous loops (docs/continuous.md)."""
        from flink_ml_tpu.servable.lib import MLPClassifierModelServable

        return MLPClassifierModelServable.load_servable(path)

    def get_model_data(self):
        from flink_ml_tpu.api.dataframe import DataFrame

        return [DataFrame(["params", "labels"], None, [[self.params], [self.labels]])]

    def set_model_data(self, *model_data):
        df = model_data[0]
        self.params = df.column("params")[0]
        self.labels = np.asarray(df.column("labels")[0])
        return self


_MLP_FUSED_CACHE: dict = {}


class MLPClassifier(Estimator, _MlpParams):
    """Data-parallel minibatch adam training of the MLP over the mesh."""

    def _build_fused(
        self, ctx: MeshContext, optimizer, local_batch: int, chunk_len: int, tol
    ):
        """A chunk of ``chunk_len`` training epochs as ONE program: ``lax.scan``
        over a per-epoch (start, offset, active) schedule passed as *arguments*
        (see ``ops.optimizer.offset_schedule`` — a slice start carried through
        the loop makes XLA's loop optimizer blow up at compile time), with a
        carried ``done`` flag replaying the tol criteria on device. The host
        observes ``done`` between chunks, so early convergence wastes at most
        chunk_len - 1 epochs.

        Programs are cached per (mesh, learning rate, batch, chunk, tol,
        compute type); jit re-specializes per parameter/data shapes on its
        own, so layer dims need not be part of the key."""
        key = (
            ctx.mesh, self.get_learning_rate(), local_batch, chunk_len, tol,
            self.get_compute_type(),
        )
        cached = _MLP_FUSED_CACHE.get(key)
        if cached is not None:
            return cached
        epoch = self._epoch_math(
            optimizer, local_batch, self._compute_dtype(), data_axes=ctx.data_axes
        )

        def per_shard(params, opt_state, done, starts, offsets, active, X, y, w):
            def body(carry, schedule):
                p, s, done = carry
                start, offset, act = schedule
                new_p, new_s, mean_loss = epoch(p, s, start, offset, X, y, w)
                executed = ~done & act
                keep = lambda old, new: jax.tree_util.tree_map(
                    lambda a, b: jnp.where(executed, b, a), old, new
                )
                if tol is not None:
                    # stop iff loss < tol (NaN continues, like the host criteria)
                    done = done | (executed & (mean_loss < tol))
                return (keep(p, new_p), keep(s, new_s), done), executed

            (params, opt_state, done), executed = jax.lax.scan(
                body, (params, opt_state, done), (starts, offsets, active)
            )
            return params, opt_state, done, jnp.sum(executed.astype(jnp.int32))

        program = jax.jit(
            jax.shard_map(
                per_shard,
                mesh=ctx.mesh,
                in_specs=(
                    P(), P(), P(), P(), P(), P(),
                    P(ctx.data_axes), P(ctx.data_axes), P(ctx.data_axes),
                ),
                out_specs=(P(), P(), P(), P()),
            ),
            donate_argnums=(0, 1, 2),
        )
        _cache_put(_MLP_FUSED_CACHE, key, program)
        return program

    @staticmethod
    def _epoch_math(optimizer, local_batch: int, compute_dtype=None, data_axes=DATA_AXIS):
        def per_shard(params, opt_state, start, offset, X, y, w):
            # Contiguous minibatch window via dynamic_slice (cheap on TPU) with the
            # clamped tail zero-weighted — same scheme as _sgd_epoch_math; start
            # and offset arrive from the precomputed schedule.
            Xb = jax.lax.dynamic_slice_in_dim(X, start, local_batch)
            yb = jax.lax.dynamic_slice_in_dim(y, start, local_batch)
            tail_valid = (start + jnp.arange(local_batch) >= offset).astype(jnp.float32)
            wb = jax.lax.dynamic_slice_in_dim(w, start, local_batch) * tail_valid

            def loss_sum(p):
                # Mixed precision: matmuls in compute_dtype, loss in float32.
                logits = _forward(p, Xb, compute_dtype).astype(jnp.float32)
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb.astype(jnp.int32)
                )
                return jnp.sum(losses * wb)

            loss, grads = jax.value_and_grad(loss_sum)(params)
            # On a multi-slice mesh this is the one DCN-crossing collective:
            # XLA reduces over ICI within each slice, then across slices.
            packed = jax.lax.psum(
                (grads, jnp.stack([jnp.sum(wb), loss])), data_axes
            )
            grads, stats = packed
            weight_sum, loss_sum_v = stats[0], stats[1]
            safe_w = jnp.maximum(weight_sum, 1e-30)
            grads = jax.tree_util.tree_map(lambda g: g / safe_w, grads)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            mean_loss = loss_sum_v / safe_w
            return params, opt_state, mean_loss

        return per_shard

    def fit(self, *inputs) -> MLPClassifierModel:
        (df,) = inputs
        data = extract_labeled_data(
            df, self.get_features_col(), self.get_label_col(), None
        )
        labels = np.unique(data["labels"])
        label_to_idx = {v: i for i, v in enumerate(labels)}
        y_idx = np.asarray([label_to_idx[v] for v in data["labels"]], np.float32)
        # train.mesh drives the MLP's data parallelism too: the resolved
        # TrainSharding supplies the mesh and the replicated layer placement
        # (this fit keeps its psum reduction — the bit-stability contract
        # covers SGD/KMeans; here the mesh width is a throughput knob).
        ts = resolve_train_sharding()
        ctx = ts.ctx if ts is not None else get_mesh_context()
        cache = DeviceDataCache(
            {"x": data["features"], "y": y_idx, "w": data["weights"]}, ctx=ctx
        )
        dims = [data["features"].shape[1], *[int(h) for h in self.get_hidden_layers()], len(labels)]
        rng = np.random.default_rng(self.get_seed())
        params = [tuple(jnp.asarray(a) for a in layer) for layer in _init_params(rng, dims)]
        if ts is not None:
            params = ts.place_state(params)
            metrics.counter(MLMetrics.TRAIN_GROUP, MLMetrics.TRAIN_SHARDED_FITS)
        optimizer = optax.adam(self.get_learning_rate())
        opt_state = optimizer.init(params)

        local_batch = max(1, -(-self.get_global_batch_size() // ctx.n_data))
        local_batch = min(local_batch, cache.local_rows)
        check_loss = np.isfinite(self.get_tol()) and self.get_tol() > 0
        mask = cache.mask

        # Whole-run fusion: no checkpoint/listener hooks on MLP fit, so all epochs
        # always run inside one XLA program (scan for maxIter-only, while_loop for
        # the tol criteria evaluated on device).
        max_iter = self.get_max_iter()
        chunk = fused_chunk_len(
            max_iter, check_loss,
            flops_per_epoch=_mlp_flops_per_epoch(dims, local_batch, ctx.n_data),
        )
        fused = self._build_fused(
            ctx,
            optimizer,
            local_batch,
            chunk,
            self.get_tol() if check_loss else None,
        )
        starts, offsets = offset_schedule(cache.local_rows, local_batch, max_iter)
        done = ctx.replicate(np.asarray(False))
        opt_params, opt_st = params, opt_state
        w_col = cache["w"] * mask
        for starts_c, offsets_c, active_c, n_active in chunked_schedule(
            starts, offsets, max_iter, chunk
        ):
            opt_params, opt_st, done, n_exec = fused(
                opt_params, opt_st, done, starts_c, offsets_c, active_c,
                cache["x"], cache["y"], w_col,
            )
            if check_loss and int(jax.device_get(n_exec)) < n_active:
                break  # done flipped mid-chunk
        final_params = opt_params
        model = MLPClassifierModel()
        update_existing_params(model, self)
        model.params = [
            tuple(np.asarray(jax.device_get(a)) for a in layer) for layer in final_params
        ]
        model.labels = labels.astype(np.float64)
        return model

    def fit_stream(self, cache, classes=None, window_rows=None) -> MLPClassifierModel:
        """Train out of a host-tier cache larger than HBM.

        ``cache`` is a HostDataCache/NativeDataCache with columns ``features``
        [n, d], ``labels`` [n] (class values) and optional ``weights`` [n].
        Per-shard HBM windows stream through the same fused chunk program as
        ``fit`` with one-ahead prefetch (``iteration/streaming.py`` — the
        ``ListStateWithCache.java:43`` role); with batch-aligned shards every
        epoch consumes exactly the rows the in-HBM fit would (equal results up
        to XLA fusion-order ULPs).
        """
        from flink_ml_tpu.iteration.streaming import plan_windows, run_windows

        if window_rows is None:  # runtime config tier decides
            from flink_ml_tpu.config import Options, config

            window_rows = config.get(Options.TRAIN_STREAM_WINDOW_ROWS)
        ts = resolve_train_sharding()
        ctx = ts.ctx if ts is not None else get_mesh_context()
        if classes is None:
            uniq: set = set()
            for chunk in cache.iter_rows():
                uniq.update(np.unique(np.asarray(chunk["labels"])).tolist())
            classes = sorted(uniq)
        classes = np.sort(np.asarray(classes, np.float64))

        def to_index(a):
            a64 = a.astype(np.float64)
            idx = np.searchsorted(classes, a64)
            bad = (idx >= len(classes)) | (classes[np.minimum(idx, len(classes) - 1)] != a64)
            if bad.any():  # a silent mis-map would train on wrong targets
                raise ValueError(
                    f"labels {np.unique(a64[bad])} not in classes {classes}"
                )
            return idx

        local_batch = max(1, -(-self.get_global_batch_size() // ctx.n_data))
        local_batch = min(local_batch, -(-int(cache.num_rows) // ctx.n_data))
        max_iter = self.get_max_iter()
        d = int(np.asarray(cache.rows(0, 1)["features"]).shape[-1])
        dims = [d, *[int(h) for h in self.get_hidden_layers()], len(classes)]
        check_loss = np.isfinite(self.get_tol()) and self.get_tol() > 0
        stream, sched = plan_windows(
            cache,
            {"x": "features", "y": "labels", "w": "weights"},
            ctx,
            window_rows,
            local_batch,
            max_iter,
            transforms={"y": to_index},
            check_loss=check_loss,
            flops_per_epoch=_mlp_flops_per_epoch(dims, local_batch, ctx.n_data),
        )
        rng = np.random.default_rng(self.get_seed())
        params = [tuple(jnp.asarray(a) for a in layer) for layer in _init_params(rng, dims)]
        if ts is not None:
            params = ts.place_state(params)
            metrics.counter(MLMetrics.TRAIN_GROUP, MLMetrics.TRAIN_SHARDED_FITS)
        optimizer = optax.adam(self.get_learning_rate())
        fused = self._build_fused(
            ctx, optimizer, local_batch, sched.chunk_len,
            self.get_tol() if check_loss else None,
        )
        state = {
            "params": params,
            "opt_state": optimizer.init(params),
            "done": ctx.replicate(np.asarray(False)),
        }

        def dispatch(i, win, starts_c, active_c, n_active):
            w_col = win["w"] * win["__mask__"]
            # starts double as offsets: window zero-mask padding realizes the
            # short tail batch instead of the resident path's clamped re-read.
            state["params"], state["opt_state"], state["done"], n_exec = fused(
                state["params"], state["opt_state"], state["done"],
                starts_c, starts_c, active_c, win["x"], win["y"], w_col,
            )
            if not check_loss:
                return None
            return lambda: int(jax.device_get(n_exec)) < n_active  # done mid-chunk

        run_windows(stream, sched, dispatch)
        model = MLPClassifierModel()
        update_existing_params(model, self)
        model.params = [
            tuple(np.asarray(jax.device_get(a)) for a in layer)
            for layer in state["params"]
        ]
        model.labels = classes
        return model
