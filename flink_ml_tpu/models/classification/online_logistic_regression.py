"""Online logistic regression via FTRL-proximal.

Reference: ``flink-ml-lib/.../classification/logisticregression/
OnlineLogisticRegression.java`` — per global batch: local per-dimension gradients of
the sigmoid loss (``CalculateLocalGradient:300-334``: grad[i] += (p − y)·x[i],
per-dim weight counts), reduced across workers, then the parallelism-1 FTRL update
(``UpdateModel:222-253``): per dimension
    σ = (√(n + g²) − √n)/α;  z += g − σ·w;  n += g²
    w = 0                         if |z| ≤ l1
      = (sign(z)·l1 − z) / ((β + √n)/α + l2)   otherwise
with l1 = elasticNet·reg, l2 = (1−elasticNet)·reg (same mapping as TF's FTRL).
Model versions start at 1 and increment per batch (``CreateLrModelData``).
``OnlineLogisticRegressionModel`` appends prediction/rawPrediction/modelVersion and
exports the model-version gauge.

TPU-native: the per-dimension loop is one fused elementwise jit program on [d]
arrays; the gradient is the same two-matmul kernel as batch training. Deviation:
sample weights scale the gradient in the dense path too (the reference's dense
branch ignores its weight column — sparse branch uses it — which reads as a bug).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Estimator
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.models.online import (
    HasCheckpointing,
    OnlineModelBase,
    array_digest,
    as_batch_stream,
)
from flink_ml_tpu.ops.kernels import logistic_predict_kernel
from flink_ml_tpu.params.param import FloatParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import (
    HasBatchStrategy,
    HasElasticNet,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasModelVersionCol,
    HasPredictionCol,
    HasRawPredictionCol,
    HasReg,
    HasWeightCol,
)

__all__ = ["OnlineLogisticRegression", "OnlineLogisticRegressionModel"]


class _FtrlParams(HasReg, HasElasticNet, HasGlobalBatchSize, HasBatchStrategy):
    """Ref OnlineLogisticRegressionParams — alpha/beta on top of the shared mixins."""

    ALPHA = FloatParam("alpha", "The alpha parameter of ftrl.", 0.1, ParamValidators.gt(0.0))
    BETA = FloatParam("beta", "The beta parameter of ftrl.", 0.1, ParamValidators.gt(0.0))

    def get_alpha(self) -> float:
        return self.get(self.ALPHA)

    def set_alpha(self, value: float):
        return self.set(self.ALPHA, value)

    def get_beta(self) -> float:
        return self.get(self.BETA)

    def set_beta(self, value: float):
        return self.set(self.BETA, value)


@functools.cache
def _ftrl_step(alpha: float, beta: float, l1: float, l2: float):
    @jax.jit
    def step(coef, n, z, X, y, w):
        p = jax.nn.sigmoid(X @ coef)
        grad = X.T @ (w * (p - y))  # [d]
        weight_sum = jnp.sum(w) * jnp.ones_like(grad)
        g = jnp.where(weight_sum != 0.0, grad / weight_sum, grad)
        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / alpha
        z = z + g - sigma * coef
        n = n + g * g
        new_coef = jnp.where(
            jnp.abs(z) <= l1,
            0.0,
            (jnp.sign(z) * l1 - z) / ((beta + jnp.sqrt(n)) / alpha + l2),
        )
        return new_coef, n, z

    return step


_predict_kernel = logistic_predict_kernel


class OnlineLogisticRegressionModel(
    OnlineModelBase,
    HasFeaturesCol,
    HasPredictionCol,
    HasRawPredictionCol,
    HasModelVersionCol,
):
    """Ref OnlineLogisticRegressionModel.java — latest-version serving + version col."""

    _MODEL_ARRAY_NAMES = ("coefficient",)

    def __init__(self):
        super().__init__()
        self.coefficient: Optional[np.ndarray] = None

    def _apply_snapshot(self, payload) -> None:
        self.coefficient = np.asarray(payload)

    @classmethod
    def load_servable(cls, path: str):
        """A published online-LR version serves through the runtime-free
        ``LogisticRegressionModelServable`` (same coefficient array, same
        param names) — this is what lets ``publish_servable(model, dir)``
        feed the serving tier's poller/fast path directly from a live
        continuous-training loop (docs/continuous.md)."""
        from flink_ml_tpu.servable.lib import LogisticRegressionModelServable

        return LogisticRegressionModelServable.load_servable(path)

    def transform(self, *inputs):
        (df,) = inputs
        if self.coefficient is None:
            raise RuntimeError(
                "no model version has arrived yet; advance() the model or set model data"
            )
        X = df.vectors(self.get_features_col()).astype(np.float32)
        pred, raw = _predict_kernel()(X, jnp.asarray(self.coefficient, jnp.float32))
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64))
        out.add_column(
            self.get_raw_prediction_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(raw, np.float64),
        )
        out.add_column(
            self.get_model_version_col(),
            DataTypes.LONG,
            np.full(len(df), self.model_version, np.int64),
        )
        return out


class OnlineLogisticRegression(
    Estimator,
    HasFeaturesCol,
    HasLabelCol,
    HasWeightCol,
    HasPredictionCol,
    HasRawPredictionCol,
    _FtrlParams,
    HasCheckpointing,
):
    """Ref OnlineLogisticRegression.java."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._initial_coefficient: Optional[np.ndarray] = None

    def set_initial_model_data(self, model_data: DataFrame) -> "OnlineLogisticRegression":
        """Ref setInitialModelData — a model-data DataFrame with a `coefficient` row."""
        col = model_data.column("coefficient")
        value = col[0]
        from flink_ml_tpu.linalg.vectors import Vector

        self._initial_coefficient = (
            value.to_array() if isinstance(value, Vector) else np.asarray(value)
        )
        return self

    def fit(self, *inputs) -> OnlineLogisticRegressionModel:
        (data,) = inputs
        if self._initial_coefficient is None:
            raise RuntimeError("OnlineLogisticRegression requires set_initial_model_data")
        coef = jnp.asarray(self._initial_coefficient, jnp.float32)
        dim = coef.shape[0]
        l1 = self.get_elastic_net() * self.get_reg()
        l2 = (1.0 - self.get_elastic_net()) * self.get_reg()
        step = _ftrl_step(self.get_alpha(), self.get_beta(), l1, l2)
        features_col, label_col = self.get_features_col(), self.get_label_col()
        weight_col = self.get_weight_col()

        stream, bounded = as_batch_stream(data, self.get_global_batch_size())

        def train_step(state, batch):
            coef, n, z = state
            X = jnp.asarray(np.asarray(batch[features_col], np.float32))
            y = jnp.asarray(np.asarray(batch[label_col], np.float32))
            w = (
                jnp.asarray(np.asarray(batch[weight_col], np.float32))
                if weight_col and weight_col in batch
                else jnp.ones_like(y)
            )
            coef, n, z = step(coef, n, z, X, y, w)
            return (coef, n, z), np.asarray(coef)

        driver = self._snapshot_driver(
            stream,
            train_step,
            (coef, jnp.zeros(dim), jnp.zeros(dim)),
            payload_from_state=lambda s: np.asarray(s[0]),
            dim=dim,
            init=array_digest(self._initial_coefficient),
        )
        model = OnlineLogisticRegressionModel()
        update_existing_params(model, self)
        model._apply_snapshot(self._initial_coefficient)  # version 0 = init model
        driver.resume_into(model)  # continue at the checkpointed version, if any
        model._attach_stream(driver)
        if bounded:
            model.advance()
        return model
