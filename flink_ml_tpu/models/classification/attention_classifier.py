"""Self-attention sequence classifier — the sequence-parallel flagship stage.

No analogue exists in the reference (its models are coefficient vectors;
SURVEY.md §2.9 records no deep nets anywhere in the tree), but the Stage
contract is the reference's: an ``Estimator`` whose ``fit`` returns a
``Model`` (Estimator.java:31,38), the standard param plumbing, save/load and
model-data access like every other algorithm here.

What makes it the *library consumer* of the sequence-parallel machinery: a
document is a token sequence far longer than one chip wants to hold
attention scores for, so both ``fit`` and ``transform`` run their attention
through ``parallel.ring.ring_attention`` with the sequence axis sharded over
the mesh's data axis — KV blocks rotate over ICI via ppermute while every
shard computes, no [T, T] score matrix ever materializes, and gradients flow
through the ring (pinned against dense attention in
tests/test_ring_attention.py).

Architecture (deliberately compact — the point is the parallelism contract,
not SOTA accuracy): embedding -> one multi-head self-attention block with a
residual -> masked mean-pool over real positions -> softmax head; adam
training with the full step (fwd + ring + bwd + update) in ONE jit'd
program per minibatch.
"""
from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.params.param import IntParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import (
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasSeed,
)
from flink_ml_tpu.parallel.mesh import DATA_AXIS, MeshContext, get_mesh_context
from flink_ml_tpu.parallel.ring import ring_attention
from flink_ml_tpu.utils import read_write as rw

__all__ = ["SelfAttentionClassifier", "SelfAttentionClassifierModel"]


class _AttnParams(
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasRawPredictionCol,
    HasMaxIter,
    HasLearningRate,
    HasGlobalBatchSize,
    HasSeed,
):
    EMBEDDING_DIM = IntParam(
        "embeddingDim", "Token embedding width.", 32, ParamValidators.gt(0)
    )
    NUM_HEADS = IntParam(
        "numHeads",
        "Attention heads; embeddingDim must divide evenly by it.",
        4,
        ParamValidators.gt(0),
    )
    VOCAB_SIZE = IntParam(
        "vocabSize",
        "Token vocabulary size; 0 infers max(token) + 1 from the training data.",
        0,
        ParamValidators.gt_eq(0),
    )

    def get_embedding_dim(self) -> int:
        return self.get(self.EMBEDDING_DIM)

    def set_embedding_dim(self, value: int):
        return self.set(self.EMBEDDING_DIM, value)

    def get_num_heads(self) -> int:
        return self.get(self.NUM_HEADS)

    def set_num_heads(self, value: int):
        return self.set(self.NUM_HEADS, value)

    def get_vocab_size(self) -> int:
        return self.get(self.VOCAB_SIZE)

    def set_vocab_size(self, value: int):
        return self.set(self.VOCAB_SIZE, value)


def _init_params(rng: np.random.Generator, vocab: int, emb: int, n_classes: int):
    def glorot(shape):
        scale = np.sqrt(2.0 / sum(shape))
        return (rng.normal(size=shape) * scale).astype(np.float32)

    return {
        "emb": glorot((vocab, emb)),
        "wq": glorot((emb, emb)),
        "wk": glorot((emb, emb)),
        "wv": glorot((emb, emb)),
        "wo": glorot((emb, emb)),
        "w_cls": glorot((emb, n_classes)),
        "b_cls": np.zeros(n_classes, np.float32),
    }


def _forward(params, tok, n_valid, n_heads: int, flash: bool = False):
    """Logits for token sequences ``tok [B, T_pad]`` with real length
    ``n_valid``. The attention is sequence-sharded: the surrounding shard_map
    splits T over the mesh's data axis, and ``ring_attention`` rotates KV
    around the ring. Padding positions beyond ``n_valid`` are masked out of
    both the attention keys and the mean-pool."""
    B, T = tok.shape
    E = params["emb"].shape[1]
    h = params["emb"][tok]  # [B, T, E]
    q = (h @ params["wq"]).reshape(B, T, n_heads, E // n_heads)
    k = (h @ params["wk"]).reshape(B, T, n_heads, E // n_heads)
    v = (h @ params["wv"]).reshape(B, T, n_heads, E // n_heads)
    attn = ring_attention(
        q, k, v, DATA_AXIS, causal=False, n_valid=n_valid, flash=flash
    )
    a = attn.reshape(B, T, E) @ params["wo"] + h  # residual
    # masked mean-pool over real positions (global position = shard offset +
    # local index, exactly ring_attention's convention)
    my_idx = jax.lax.axis_index(DATA_AXIS)
    pos = my_idx * T + jnp.arange(T)
    valid = (pos < n_valid).astype(a.dtype)  # [T]
    pooled = jax.lax.psum(jnp.sum(a * valid[None, :, None], axis=1), DATA_AXIS)
    pooled = pooled / jnp.asarray(n_valid, a.dtype)
    return pooled @ params["w_cls"] + params["b_cls"]  # [B, C]


@functools.cache
def _train_step(mesh, n_heads: int, lr: float, flash: bool = False):
    optimizer = optax.adam(lr)
    seq = P(None, DATA_AXIS)

    def per_shard(params, opt_state, tok, y, w, n_valid):
        def loss_fn(p):
            logits = _forward(p, tok, n_valid, n_heads, flash)
            losses = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            # w zero-weights clamped tail re-reads (the SGD.java:265 short
            # tail batch, same scheme as _sgd_epoch_math's tail_valid)
            return jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1e-30)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # Params are replicated while activations vary over the sequence
        # axis; every shard computes the identical loss (the pool is psum'd),
        # but each shard's grads carry only its sequence slice's
        # contribution — one psum makes the adam update identical everywhere.
        grads = jax.lax.psum(grads, DATA_AXIS)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return optimizer, jax.jit(
        jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(P(), P(), seq, P(), P(), P()),
            out_specs=(P(), P(), P()),
        ),
        donate_argnums=(0, 1),
    )


@functools.cache
def _predict_step(mesh, n_heads: int, flash: bool = False):
    seq = P(None, DATA_AXIS)

    def per_shard(params, tok, n_valid):
        logits = _forward(params, tok, n_valid, n_heads, flash)
        return logits, jax.nn.softmax(logits, axis=-1)

    return jax.jit(
        jax.shard_map(
            per_shard, mesh=mesh, in_specs=(P(), seq, P()), out_specs=(P(), P())
        )
    )


def _use_flash(ctx: MeshContext, tok: np.ndarray, emb: int, n_heads: int) -> bool:
    """Fused-fold gate for serving this (mesh, sequence) shape — the
    activations on this path are f32, so only the tiling/VMEM/device
    conditions apply."""
    from flink_ml_tpu.parallel.flash import flash_available

    return flash_available(
        tok.shape[1] // ctx.n_data, emb // n_heads, list(ctx.mesh.devices.flat)
    )


def _use_flash_train(
    ctx: MeshContext, tok: np.ndarray, emb: int, n_heads: int, batch: int
) -> bool:
    """Fused-fold gate for the TRAINING step: the fused backward's pallas
    outputs scale with batch*heads and hit the scoped-VMEM envelope before
    the forward does (flash.flash_train_available); past it the step trains
    on the jnp fold — identical numbers through HBM, never a compile
    failure."""
    from flink_ml_tpu.parallel.flash import flash_train_available

    return flash_train_available(
        tok.shape[1] // ctx.n_data,
        emb // n_heads,
        batch,
        n_heads,
        list(ctx.mesh.devices.flat),
    )


def _pad_tokens(tok: np.ndarray, ctx: MeshContext):
    """Pad the sequence axis to the mesh's data-axis size; token 0 is safe
    padding because every padded position is masked from attention keys and
    the pool by ``n_valid``."""
    T = tok.shape[1]
    pad = (-T) % ctx.n_data
    if pad:
        tok = np.concatenate([tok, np.zeros((tok.shape[0], pad), tok.dtype)], axis=1)
    return tok, T


class SelfAttentionClassifierModel(Model, _AttnParams):
    """Serving side: the same sequence-sharded forward, one jit per mesh."""

    def __init__(self):
        super().__init__()
        self.params: Optional[dict] = None
        self.labels: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        ctx = get_mesh_context()
        tok = np.asarray(df.vectors(self.get_features_col()), np.int32)
        vocab = int(self.params["emb"].shape[0])
        if tok.size and (tok.min() < 0 or tok.max() >= vocab):
            # without this, out-of-range ids would silently clamp through
            # JAX's out-of-bounds gather and predict from the wrong embedding
            raise ValueError(
                f"token ids must be in [0, {vocab}); got "
                f"[{tok.min()}, {tok.max()}]"
            )
        tok, t_real = _pad_tokens(tok, ctx)
        params = {k: jnp.asarray(v) for k, v in self.params.items()}
        n_heads = self.get_num_heads()
        emb = int(self.params["emb"].shape[1])
        logits, probs = _predict_step(
            ctx.mesh, n_heads, _use_flash(ctx, tok, emb, n_heads)
        )(
            params, jax.device_put(tok, ctx.sharding(None, DATA_AXIS)),
            jnp.asarray(t_real, jnp.int32),
        )
        pred = self.labels[np.asarray(jnp.argmax(logits, axis=-1), np.int64)]
        out = df.clone()
        out.add_column(
            self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64)
        )
        out.add_column(
            self.get_raw_prediction_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(probs, np.float64),
        )
        return out

    # --- persistence ---------------------------------------------------------
    def save(self, path: str) -> None:
        rw.save_metadata(self, path)
        rw.save_model_arrays(path, {"labels": self.labels, **self.params})

    @classmethod
    def load(cls, path: str):
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        arrays = rw.load_model_arrays(path)
        model.labels = arrays.pop("labels")
        model.params = dict(arrays)
        return model

    def get_model_data(self):
        from flink_ml_tpu.api.dataframe import DataFrame

        return [DataFrame(["params", "labels"], None, [[self.params], [self.labels]])]

    def set_model_data(self, *model_data):
        df = model_data[0]
        self.params = df.column("params")[0]
        self.labels = np.asarray(df.column("labels")[0])
        return self


class SelfAttentionClassifier(Estimator, _AttnParams):
    """Adam training with the sequence axis sharded over the mesh."""

    def fit(self, *inputs) -> SelfAttentionClassifierModel:
        (df,) = inputs
        ctx = get_mesh_context()
        emb, n_heads = self.get_embedding_dim(), self.get_num_heads()
        if emb % n_heads:
            raise ValueError(
                f"embeddingDim {emb} must divide evenly by numHeads {n_heads}"
            )
        tok = np.asarray(df.vectors(self.get_features_col()), np.int32)
        if tok.min() < 0:
            raise ValueError("token ids must be non-negative")
        labels = np.unique(np.asarray(df.scalars(self.get_label_col())))
        y_idx = np.searchsorted(labels, np.asarray(df.scalars(self.get_label_col())))
        vocab = self.get_vocab_size() or int(tok.max()) + 1
        if tok.max() >= vocab:
            raise ValueError(f"token id {tok.max()} >= vocabSize {vocab}")

        tok, t_real = _pad_tokens(tok, ctx)
        rng = np.random.default_rng(self.get_seed())
        params = jax.tree_util.tree_map(
            jnp.asarray, _init_params(rng, vocab, emb, len(labels))
        )
        n = tok.shape[0]
        batch = min(self.get_global_batch_size(), n)
        optimizer, step = _train_step(
            ctx.mesh,
            n_heads,
            self.get_learning_rate(),
            _use_flash_train(ctx, tok, emb, n_heads, batch),
        )
        opt_state = optimizer.init(params)
        tok_dev = jax.device_put(tok, ctx.sharding(None, DATA_AXIS))
        y_dev = ctx.replicate(y_idx.astype(np.int32))
        nv = jnp.asarray(t_real, jnp.int32)
        offset = 0
        windows = {}  # (lo, offset) -> device tensors; the cycle is short
        for _ in range(self.get_max_iter()):
            # contiguous example window per epoch, cycling like SGD.java:265;
            # at the clamped tail, rows before the logical offset are re-reads
            # and get zero weight (the reference's short tail batch). Window
            # tensors are built once per distinct (lo, offset) — at most
            # ceil(n/batch) of them — so steady-state epochs do no host work.
            lo = min(offset, n - batch)
            key = (lo, offset)
            if key not in windows:
                windows[key] = (
                    jax.lax.slice_in_dim(tok_dev, lo, lo + batch, axis=0),
                    jax.lax.slice_in_dim(y_dev, lo, lo + batch, axis=0),
                    ctx.replicate(
                        (np.arange(batch) + lo >= offset).astype(np.float32)
                    ),
                )
            tok_w, y_w, w_w = windows[key]
            params, opt_state, _loss = step(
                params, opt_state, tok_w, y_w, w_w, nv
            )
            offset = 0 if offset + batch >= n else offset + batch

        model = SelfAttentionClassifierModel()
        update_existing_params(model, self)
        model.set_vocab_size(vocab)
        model.params = {
            k: np.asarray(jax.device_get(v)) for k, v in params.items()
        }
        model.labels = labels.astype(np.float64)
        return model
