"""Classification algorithms. Ref flink-ml-lib/.../ml/classification/."""
