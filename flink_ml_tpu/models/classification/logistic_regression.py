"""Logistic regression.

Reference: ``flink-ml-lib/.../classification/logisticregression/`` —
``LogisticRegression.java:60-124`` (fit = SGD + BinaryLogisticLoss),
``LogisticRegressionModel.java`` / ``LogisticRegressionModelServable.java:62``
(prediction = dot ≥ 0, rawPrediction = [1−p, p] with p = sigmoid(dot)),
``LogisticRegressionModelData`` (one coefficient vector).

Labels must be {0, 1} (binomial; the reference's ``multiClass`` param only supports
"auto"/"binomial" in practice). Training runs the distributed SGD of
``ops/optimizer.py``; inference is one jit'd matmul + sigmoid over the whole batch.
"""
from __future__ import annotations


import numpy as np

from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.models.linear import LinearEstimatorBase, LinearModelBase
from flink_ml_tpu.ops.lossfunc import BinaryLogisticLoss
from flink_ml_tpu.params.shared import HasMultiClass, HasRawPredictionCol

__all__ = ["LogisticRegression", "LogisticRegressionModel"]


class LogisticRegressionModel(LinearModelBase, HasRawPredictionCol, HasMultiClass):
    """Ref LogisticRegressionModel.java."""

    @classmethod
    def load_servable(cls, path: str):
        """Runtime-free replica from this model's save dir (ref
        LogisticRegressionModel → LogisticRegressionModelServable pairing)."""
        from flink_ml_tpu.servable.lib import LogisticRegressionModelServable

        return LogisticRegressionModelServable.load_servable(path)

    def transform(self, *inputs):
        from flink_ml_tpu.models.linear import compute_dots
        from flink_ml_tpu.ops.kernels import logistic_from_dots_kernel

        (df,) = inputs
        dots = compute_dots(df, self.get_features_col(), self.coefficient)
        pred, raw = logistic_from_dots_kernel()(dots)
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64))
        out.add_column(
            self.get_raw_prediction_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(raw, np.float64),
        )
        return out


class LogisticRegression(LinearEstimatorBase, HasRawPredictionCol, HasMultiClass):
    """Ref LogisticRegression.java:106-115."""

    _LOSS = BinaryLogisticLoss.INSTANCE
    _MODEL_CLASS = LogisticRegressionModel

    def _validate_labels(self, labels: np.ndarray) -> None:
        uniques = np.unique(labels)
        if not np.all(np.isin(uniques, [0.0, 1.0])):
            raise ValueError(
                f"LogisticRegression requires binary labels in {{0, 1}}, got {uniques[:10]}"
            )
