"""Logistic regression.

Reference: ``flink-ml-lib/.../classification/logisticregression/`` —
``LogisticRegression.java:60-124`` (fit = SGD + BinaryLogisticLoss),
``LogisticRegressionModel.java`` / ``LogisticRegressionModelServable.java:62``
(prediction = dot ≥ 0, rawPrediction = [1−p, p] with p = sigmoid(dot)),
``LogisticRegressionModelData`` (one coefficient vector).

Labels must be {0, 1} (binomial; the reference's ``multiClass`` param only supports
"auto"/"binomial" in practice). Training runs the distributed SGD of
``ops/optimizer.py``; inference is one jit'd matmul + sigmoid over the whole batch.
"""
from __future__ import annotations


import numpy as np

from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.models.linear import LinearEstimatorBase, LinearModelBase
from flink_ml_tpu.ops.lossfunc import BinaryLogisticLoss
from flink_ml_tpu.params.shared import HasMultiClass, HasRawPredictionCol

__all__ = ["LogisticRegression", "LogisticRegressionModel"]


class LogisticRegressionModel(LinearModelBase, HasRawPredictionCol, HasMultiClass):
    """Ref LogisticRegressionModel.java."""

    @classmethod
    def load_servable(cls, path: str):
        """Runtime-free replica from this model's save dir (ref
        LogisticRegressionModel → LogisticRegressionModelServable pairing)."""
        from flink_ml_tpu.servable.lib import LogisticRegressionModelServable

        return LogisticRegressionModelServable.load_servable(path)

    def transform(self, *inputs):
        import jax.numpy as jnp

        from flink_ml_tpu.ops.kernels import (
            dot_kernel,
            logistic_from_dots_kernel,
            sparse_dot_kernel,
        )
        from flink_ml_tpu.servable.sparse import pack_sparse_column, sparse_names

        (df,) = inputs
        features_col = self.get_features_col()
        if len(df) == 0:
            # An empty features column carries no width to check or dot.
            out = df.clone()
            out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, np.zeros(0))
            out.add_column(
                self.get_raw_prediction_col(),
                DataTypes.vector(BasicType.DOUBLE),
                np.zeros((0, 2)),
            )
            return out
        coef = jnp.asarray(np.asarray(self.coefficient), jnp.float32)
        if df.is_sparse(features_col):
            # Padded-CSR margins through the same ``sparse_dot`` body the
            # fused sparse spec composes — the sequential segment-sum fold
            # makes the margin bit-invariant to the packed nnz cap, so the
            # per-stage and fused paths agree bit for bit (docs/sparse.md).
            arrays, _cap, _dim, _nnz = pack_sparse_column(
                df, features_col, dim=int(coef.shape[0])
            )
            in_v, in_i, _ = sparse_names(features_col)
            dots = sparse_dot_kernel()(arrays[in_i], arrays[in_v], coef)
        else:
            X = df.vectors(features_col).astype(np.float32)
            dots = dot_kernel()(X, coef)
        pred, raw = logistic_from_dots_kernel()(dots)
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64))
        out.add_column(
            self.get_raw_prediction_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(raw, np.float64),
        )
        return out

    def sparse_kernel_spec(self, known):
        """Sparse-convention head for the batch fast path (docs/sparse.md):
        identical spec to the servable's — ``transform``'s sparse branch
        jits the same ``sparse_dot`` gather-scale-segment-sum body the spec
        composes, so the fused chain and the per-stage ``transform`` agree
        bit for bit at every nnz cap (the segment-sum fold is cap-invariant)."""
        from flink_ml_tpu.ops.kernels import logistic_from_dots_fn, sparse_dot_fn
        from flink_ml_tpu.servable.kernel_spec import KernelSpec
        from flink_ml_tpu.servable.sparse import sparse_names

        if self.coefficient is None:
            raise RuntimeError("set_model_data must be called before kernel_spec")
        features_col = self.get_features_col()
        dim = int(np.asarray(self.coefficient).shape[0])
        if known.get(features_col) != dim:
            return None
        in_v, in_i, _in_z = sparse_names(features_col)

        def kernel_fn(model, cols):
            pred, raw = logistic_from_dots_fn(
                sparse_dot_fn(cols[in_v], cols[in_i], model["coefficient"])
            )
            return {
                self.get_prediction_col(): pred,
                self.get_raw_prediction_col(): raw,
            }

        return KernelSpec(
            input_cols=(features_col,),
            outputs=(
                (self.get_prediction_col(), DataTypes.DOUBLE),
                (self.get_raw_prediction_col(), DataTypes.vector(BasicType.DOUBLE)),
            ),
            model_arrays={"coefficient": np.asarray(self.coefficient, np.float32)},
            kernel_fn=kernel_fn,
            input_kinds={features_col: "sparse"},
            sparse_input_dims={features_col: dim},
            fusion_op="sparse_logistic",
        )


class LogisticRegression(LinearEstimatorBase, HasRawPredictionCol, HasMultiClass):
    """Ref LogisticRegression.java:106-115."""

    _LOSS = BinaryLogisticLoss.INSTANCE
    _MODEL_CLASS = LogisticRegressionModel

    def _validate_labels(self, labels: np.ndarray) -> None:
        uniques = np.unique(labels)
        if not np.all(np.isin(uniques, [0.0, 1.0])):
            raise ValueError(
                f"LogisticRegression requires binary labels in {{0, 1}}, got {uniques[:10]}"
            )
