"""K-nearest-neighbors classifier.

Reference: ``flink-ml-lib/.../classification/knn/`` — the model IS the dataset
(features + labels + cached norms, KnnModelData); prediction broadcasts the model
(KnnModel.java:87) and for each query finds the k nearest by euclidean distance
(|a|²+|b|²−2ab with cached norm squares) and takes the majority label
(KnnModel.java:133-180). ``k`` default 5.

TPU-native: the whole query batch against the whole model is one [n,d]×[d,m]
matmul + top-k — the per-row PriorityQueue disappears into ``lax.top_k``.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.models.common import ModelArraysMixin, extract_labeled_data
from flink_ml_tpu.params.param import IntParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import HasFeaturesCol, HasLabelCol, HasPredictionCol

__all__ = ["Knn", "KnnModel"]


class _KnnParams(HasFeaturesCol, HasPredictionCol):
    K = IntParam("k", "The number of nearest neighbors.", 5, ParamValidators.gt(0))

    def get_k(self) -> int:
        return self.get(self.K)

    def set_k(self, value: int):
        return self.set(self.K, value)


@functools.cache
def _neighbors_kernel(k: int):
    @jax.jit
    def nearest(X, model_x, model_norm2):
        d2 = jnp.sum(X * X, axis=1, keepdims=True) + model_norm2[None, :] - 2.0 * X @ model_x.T
        neg_dist, idx = jax.lax.top_k(-d2, k)
        return idx

    return nearest


class KnnModel(ModelArraysMixin, Model, _KnnParams):
    """Ref KnnModel.java."""

    _MODEL_ARRAY_NAMES = ("model_features", "model_labels")

    def __init__(self):
        super().__init__()
        self.model_features: Optional[np.ndarray] = None
        self.model_labels: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float32)
        mx = np.asarray(self.model_features, np.float32)
        k = min(self.get_k(), mx.shape[0])
        idx = np.asarray(
            _neighbors_kernel(k)(X, mx, (mx * mx).sum(axis=1).astype(np.float32))
        )
        neighbor_labels = self.model_labels[idx]  # [n, k]
        pred = np.empty(len(X))
        for i, row in enumerate(neighbor_labels):
            vals, counts = np.unique(row, return_counts=True)
            pred[i] = vals[np.argmax(counts)]
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, pred)
        return out


class Knn(Estimator, _KnnParams, HasLabelCol):
    """Ref Knn.java — fit materializes the dataset as model data."""

    def fit(self, *inputs) -> KnnModel:
        (df,) = inputs
        data = extract_labeled_data(
            df, self.get_features_col(), self.get_label_col(), None, dtype=np.float64
        )
        model = KnnModel()
        update_existing_params(model, self)
        model.model_features = data["features"]
        model.model_labels = data["labels"]
        return model
