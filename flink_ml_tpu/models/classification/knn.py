"""K-nearest-neighbors classifier.

Reference: ``flink-ml-lib/.../classification/knn/`` — the model IS the dataset
(features + labels + cached norms, KnnModelData); prediction broadcasts the model
(KnnModel.java:87) and for each query finds the k nearest by euclidean distance
(|a|²+|b|²−2ab with cached norm squares) and takes the majority label
(KnnModel.java:133-180). ``k`` default 5.

TPU-native: the whole query batch against the whole model is one [n,d]×[d,m]
matmul + top-k — the per-row PriorityQueue disappears into ``lax.top_k``. For
reference sets large enough that the [q, m] distance matrix would not fit
(m > _BLOCK_ROWS), a streaming variant scans the model in blocks carrying a
running top-k per query — O(q·(k + block)) memory, same results. The
majority vote is a vectorized one-hot count (ties break to the smallest
label, like the reference's sorted-unique argmax).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.models.common import ModelArraysMixin, extract_labeled_data
from flink_ml_tpu.params.param import IntParam, ParamValidators, update_existing_params
from flink_ml_tpu.params.shared import HasFeaturesCol, HasLabelCol, HasPredictionCol

__all__ = ["Knn", "KnnModel"]


class _KnnParams(HasFeaturesCol, HasPredictionCol):
    K = IntParam("k", "The number of nearest neighbors.", 5, ParamValidators.gt(0))

    def get_k(self) -> int:
        return self.get(self.K)

    def set_k(self, value: int):
        return self.set(self.K, value)


_BLOCK_ROWS = 8192  # reference rows per streamed block (and the switch point)


@functools.cache
def _neighbors_kernel(k: int):
    @jax.jit
    def nearest(X, model_x, model_norm2):
        d2 = jnp.sum(X * X, axis=1, keepdims=True) + model_norm2[None, :] - 2.0 * X @ model_x.T
        neg_dist, idx = jax.lax.top_k(-d2, k)
        return idx

    return nearest


@functools.cache
def _blockwise_neighbors_kernel(k: int, block: int):
    """Streaming top-k: scan the reference set block-by-block, merging each
    block's distances into a running per-query top-k — never materializes the
    [q, m] distance matrix. ``model_norm2`` must be +inf on padding rows (they
    then sort behind every real neighbor)."""

    @jax.jit
    def nearest(X, model_x, model_norm2):
        q = X.shape[0]
        n_blocks = model_x.shape[0] // block
        xnorm = jnp.sum(X * X, axis=1, keepdims=True)

        def body(carry, i):
            best_d, best_i = carry
            mx = jax.lax.dynamic_slice_in_dim(model_x, i * block, block)
            mn = jax.lax.dynamic_slice_in_dim(model_norm2, i * block, block)
            d2 = xnorm + mn[None, :] - 2.0 * X @ mx.T
            cand_d = jnp.concatenate([best_d, -d2], axis=1)
            cand_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(i * block + jnp.arange(block), (q, block))],
                axis=1,
            )
            nd, pos = jax.lax.top_k(cand_d, k)
            ni = jnp.take_along_axis(cand_i, pos, axis=1)
            return (nd, ni), None

        init = (
            jnp.full((q, k), -jnp.inf, jnp.float32),
            jnp.zeros((q, k), jnp.int32),
        )
        (best_d, best_i), _ = jax.lax.scan(body, init, jnp.arange(n_blocks))
        return best_i

    return nearest


def _nearest_indices(X: np.ndarray, mx: np.ndarray, k: int) -> np.ndarray:
    norm2 = (mx * mx).sum(axis=1).astype(np.float32)
    m = mx.shape[0]
    if m <= _BLOCK_ROWS:
        return np.asarray(_neighbors_kernel(k)(X, mx, norm2))
    pad = (-m) % _BLOCK_ROWS
    if pad:
        mx = np.concatenate([mx, np.zeros((pad, mx.shape[1]), np.float32)])
        norm2 = np.concatenate([norm2, np.full(pad, np.inf, np.float32)])
    return np.asarray(_blockwise_neighbors_kernel(k, _BLOCK_ROWS)(X, mx, norm2))


class KnnModel(ModelArraysMixin, Model, _KnnParams):
    """Ref KnnModel.java."""

    _MODEL_ARRAY_NAMES = ("model_features", "model_labels")

    def __init__(self):
        super().__init__()
        self.model_features: Optional[np.ndarray] = None
        self.model_labels: Optional[np.ndarray] = None

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float32)
        mx = np.asarray(self.model_features, np.float32)
        k = min(self.get_k(), mx.shape[0])
        idx = _nearest_indices(X, mx, k)
        neighbor_labels = self.model_labels[idx]  # [n, k]
        # Vectorized k-bounded majority vote (each row has only k candidate
        # labels, so memory stays O(n·k²) regardless of global label
        # cardinality); first argmax over the sorted row breaks ties to the
        # smallest label, matching the per-row sorted-unique argmax.
        sorted_lab = np.sort(neighbor_labels, axis=1)
        votes = (sorted_lab[:, :, None] == sorted_lab[:, None, :]).sum(axis=2)
        best = votes.argmax(axis=1)
        pred = sorted_lab[np.arange(len(X)), best].astype(np.float64)
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, pred)
        return out


class Knn(Estimator, _KnnParams, HasLabelCol):
    """Ref Knn.java — fit materializes the dataset as model data."""

    def fit(self, *inputs) -> KnnModel:
        (df,) = inputs
        data = extract_labeled_data(
            df, self.get_features_col(), self.get_label_col(), None, dtype=np.float64
        )
        model = KnnModel()
        update_existing_params(model, self)
        model.model_features = data["features"]
        model.model_labels = data["labels"]
        return model
