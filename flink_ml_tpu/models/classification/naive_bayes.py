"""Naive Bayes (multinomial over categorical feature values).

Reference: ``flink-ml-lib/.../classification/naivebayes/`` — each feature
dimension is treated as categorical: theta[label][dim] maps feature value →
log((count + smoothing) / (count_label + smoothing·|values_dim|));
pi[label] = log(count_label·d + smoothing) − log(n·d + numLabels·smoothing)
(GenerateModelFunction, NaiveBayes.java:253-322); prediction = argmax of
pi + Σ_dim theta lookup (NaiveBayesModel.calculateProb:126-137). ``smoothing``
default 1.0; ``modelType`` only "multinomial".

Deviation: a feature value unseen for a label scores the smoothed floor
log(smoothing) − log(count_label + smoothing·|values|); the reference NPEs on
values absent from ALL labels (theta map lookup returns null).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.params.param import FloatParam, ParamValidators, StringParam, update_existing_params
from flink_ml_tpu.params.shared import HasFeaturesCol, HasLabelCol, HasPredictionCol
from flink_ml_tpu.utils import read_write as rw

__all__ = ["NaiveBayes", "NaiveBayesModel"]


class _NbParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    MODEL_TYPE = StringParam(
        "modelType",
        "The model type.",
        "multinomial",
        ParamValidators.in_array(["multinomial"]),
    )
    SMOOTHING = FloatParam(
        "smoothing", "The smoothing parameter.", 1.0, ParamValidators.gt_eq(0)
    )

    def get_model_type(self) -> str:
        return self.get(self.MODEL_TYPE)

    def set_model_type(self, value: str):
        return self.set(self.MODEL_TYPE, value)

    def get_smoothing(self) -> float:
        return self.get(self.SMOOTHING)

    def set_smoothing(self, value: float):
        return self.set(self.SMOOTHING, value)


class NaiveBayesModel(Model, _NbParams):
    """Ref NaiveBayesModel.java."""

    def __init__(self):
        super().__init__()
        self.labels: Optional[np.ndarray] = None  # [L]
        self.pi: Optional[np.ndarray] = None  # [L]
        self.theta: Optional[List[List[Dict[float, float]]]] = None  # [L][d] value→logp
        self.default_log: Optional[np.ndarray] = None  # [L, d] unseen-value floor

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float64)
        n, d = X.shape
        L = len(self.labels)
        scores = np.tile(self.pi[None, :], (n, 1))
        for li in range(L):
            for j in range(d):
                table = self.theta[li][j]
                col = X[:, j]
                scores[:, li] += np.asarray(
                    [table.get(v, self.default_log[li, j]) for v in col]
                )
        pred = self.labels[np.argmax(scores, axis=1)]
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, pred)
        return out

    # --- persistence (theta maps serialized as JSON) --------------------------
    def save(self, path: str) -> None:
        theta_json = [
            [{repr(k): v for k, v in table.items()} for table in row] for row in self.theta
        ]
        rw.save_metadata(self, path, {"theta": theta_json})
        rw.save_model_arrays(
            path, {"labels": self.labels, "pi": self.pi, "default_log": self.default_log}
        )

    @classmethod
    def load(cls, path: str):
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        arrays = rw.load_model_arrays(path)
        model.labels, model.pi = arrays["labels"], arrays["pi"]
        model.default_log = arrays["default_log"]
        model.theta = [
            [{float(k): v for k, v in table.items()} for table in row]
            for row in metadata["theta"]
        ]
        return model

    def get_model_data(self):
        from flink_ml_tpu.api.dataframe import DataFrame

        # "defaultLog" extends the reference's (theta, piArray, labels) tuple: the
        # unseen-value floor log(smoothing) − log(count_l + smoothing·|values|) is
        # not derivable from a theta table alone (Σ exp(theta) = 1 for every table),
        # so it rides along to keep every construction path scoring identically.
        return [
            DataFrame(
                ["theta", "piArray", "labels", "defaultLog"],
                None,
                [[self.theta], [self.pi], [self.labels], [self.default_log]],
            )
        ]

    def set_model_data(self, *model_data):
        df = model_data[0]
        self.theta = df.column("theta")[0]
        self.pi = np.asarray(df.column("piArray")[0])
        self.labels = np.asarray(df.column("labels")[0])
        if "defaultLog" in df.column_names:
            self.default_log = np.asarray(df.column("defaultLog")[0])
        else:
            # Legacy 3-column model data: approximate the floor by the smallest
            # smoothed log-prob per (label, dim) table — exact whenever some value
            # has zero count for that label.
            self.default_log = np.asarray(
                [[min(t.values()) if t else -np.inf for t in row] for row in self.theta]
            )
        return self


class NaiveBayes(Estimator, _NbParams):
    """Ref NaiveBayes.java."""

    def fit(self, *inputs) -> NaiveBayesModel:
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float64)
        y = df.scalars(self.get_label_col())
        if not np.all(y == np.floor(y)):
            raise ValueError("Label value should be indexed number.")
        smoothing = self.get_smoothing()
        n, d = X.shape
        labels = np.unique(y)
        L = len(labels)

        value_sets = [np.unique(X[:, j]) for j in range(d)]
        theta: List[List[Dict[float, float]]] = []
        pi = np.zeros(L)
        default_log = np.zeros((L, d))
        pi_log = np.log(n * d + L * smoothing)
        for li, label in enumerate(labels):
            Xl = X[y == label]
            count_l = Xl.shape[0]
            row = []
            for j in range(d):
                vals, counts = np.unique(Xl[:, j], return_counts=True)
                count_map = dict(zip(vals, counts))
                theta_log = np.log(count_l + smoothing * len(value_sets[j]))
                row.append(
                    {
                        float(v): float(np.log(count_map.get(v, 0.0) + smoothing) - theta_log)
                        for v in value_sets[j]
                    }
                )
                with np.errstate(divide="ignore"):
                    default_log[li, j] = np.log(smoothing) - theta_log
            theta.append(row)
            pi[li] = np.log(count_l * d + smoothing) - pi_log

        model = NaiveBayesModel()
        update_existing_params(model, self)
        model.labels = labels
        model.pi = pi
        model.theta = theta
        model.default_log = default_log
        return model
