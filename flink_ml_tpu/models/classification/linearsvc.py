"""Linear support vector classifier.

Reference: ``flink-ml-lib/.../classification/linearsvc/`` — ``LinearSVC.java`` (fit =
SGD + HingeLoss), ``LinearSVCModel.java:177-180`` (prediction = dot ≥ threshold,
rawPrediction = [dot, −dot]), ``LinearSVCModelParams`` (threshold, default 0.0).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.models.linear import LinearEstimatorBase, LinearModelBase
from flink_ml_tpu.ops.lossfunc import HingeLoss
from flink_ml_tpu.params.param import FloatParam, WithParams
from flink_ml_tpu.params.shared import HasRawPredictionCol

__all__ = ["LinearSVC", "LinearSVCModel"]


class HasThreshold(WithParams):
    """Ref LinearSVCModelParams.THRESHOLD."""

    THRESHOLD = FloatParam(
        "threshold",
        "Threshold in binary classification applied to the raw prediction.",
        0.0,
    )

    def get_threshold(self) -> float:
        return self.get(self.THRESHOLD)

    def set_threshold(self, value: float):
        return self.set(self.THRESHOLD, value)


@functools.cache
def _from_dots_kernel():
    @jax.jit
    def kernel(dots, threshold):
        pred = (dots >= threshold).astype(dots.dtype)
        raw = jnp.stack([dots, -dots], axis=1)
        return pred, raw

    return kernel


class LinearSVCModel(LinearModelBase, HasRawPredictionCol, HasThreshold):
    """Ref LinearSVCModel.java:177-180; margins via the shared dense/sparse
    ``compute_dots`` so padded-CSR input never densifies."""

    def transform(self, *inputs):
        from flink_ml_tpu.models.linear import compute_dots

        (df,) = inputs
        dots = compute_dots(df, self.get_features_col(), self.coefficient)
        pred, raw = _from_dots_kernel()(
            dots, jnp.asarray(self.get_threshold(), jnp.float32)
        )
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64))
        out.add_column(
            self.get_raw_prediction_col(),
            DataTypes.vector(BasicType.DOUBLE),
            np.asarray(raw, np.float64),
        )
        return out


class LinearSVC(LinearEstimatorBase, HasRawPredictionCol, HasThreshold):
    """Ref LinearSVC.java."""

    _LOSS = HingeLoss.INSTANCE
    _MODEL_CLASS = LinearSVCModel

    def _validate_labels(self, labels: np.ndarray) -> None:
        uniques = np.unique(labels)
        if not np.all(np.isin(uniques, [0.0, 1.0])):
            raise ValueError(
                f"LinearSVC requires binary labels in {{0, 1}}, got {uniques[:10]}"
            )
