"""Swing item-item similarity.

Reference: ``flink-ml-lib/.../recommendation/swing/Swing.java`` — for each item i,
over every pair of its purchasers (u, v):
    sim(i, j) += w_u · w_v / (alpha2 + |I_u ∩ I_v|)   for each j ≠ i in I_u ∩ I_v
with user weight w_u = 1/(alpha1 + |I_u|)^beta (Swing.java:367-369). Users with
fewer than ``minUserBehavior`` or more than ``maxUserBehavior`` items are
dropped; each item's purchaser list is reservoir-sampled down to
``maxUserNumPerItem``. Output row per item: (itemCol: long,
outputCol: "item,score;item,score;…" for the top ``k``) — same string encoding
(Swing.java:344-361). Defaults: k=100, maxUserNumPerItem=1000,
minUserBehavior=10, maxUserBehavior=1000, alpha1=15, alpha2=0, beta=0.3.

TPU mapping — the reference's per-item purchaser-pair loops (keyed
co-occurrence over a shuffled stream) become batched linear algebra. With
``B_i`` the {0,1} purchaser×item incidence of item ``i``'s (capped)
purchasers and ``M_i = w·wᵀ / (alpha2 + B_i·B_iᵀ)`` their pair-weight matrix
(zero diagonal, zero where no common item), the whole inner loop nest is

    sim(i, j) = ½ Σ_{u,v ∈ purchasers(i)} M_uv · B_uj · B_vj
              = ½ · colsum( B_i ⊙ (M_i @ B_i) )_j

i.e. one one-hot scatter + two [P,P]/[P,I] matmuls + an elementwise reduce
per item. ``B_i`` is built *on device* from the padded per-user item lists
(an ELL layout, O(interactions) host memory) — no global user×item dense
matrix ever exists. Items are bucketed by purchaser count into power-of-two
widths so a heavy-tailed catalog doesn't pay the most popular item's [P,P]
cost everywhere, and each bucket is sharded over the mesh's data axis
(shard_map) and scored with ``lax.map`` + ``lax.top_k`` inside one cached
jit program. Host work is only the O(interactions) grouping/capping and the
final string formatting; padding uses a sentinel user (zero weight, empty
item list) so every shape is static.
"""
from __future__ import annotations

from typing import List

import numpy as np

from flink_ml_tpu.api.core import AlgoOperator
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.params.param import (
    BoolParam,
    FloatParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from flink_ml_tpu.params.shared import HasOutputCol, HasSeed

__all__ = ["Swing", "encode_topk", "structured_topk"]


def encode_topk(i_ids: np.ndarray, vals: np.ndarray, inds: np.ndarray):
    """Vectorized Swing output encoding (ref Swing.java:344-361):
    ``"id,score;id,score;..."`` per item, items with no positive-scored
    neighbor omitted.

    The per-pair formatting runs as numpy string kernels (int/float ->
    unicode casts + ``np.char.add``) instead of a Python f-string per pair —
    at a 1M-item catalog that is the difference between seconds and minutes
    of host time. Float formatting matches ``str(np.float64)`` (the shortest
    round-trip repr), which is what the f-string produced.

    ``i_ids [I]``: item ids; ``vals/inds [I, k]``: top-k scores and item-row
    indices from the device scoring. Returns ``(items [M] int64, strs
    list[str])``.
    """
    pos = vals > 0.0
    rows = np.flatnonzero(pos.any(axis=1))
    if rows.size == 0:
        return np.empty(0, np.int64), []
    # one "id,score" token per positive pair, built columnar
    neigh_ids = np.asarray(i_ids, np.int64)[inds[pos]].astype("U20")
    scores = vals[pos].astype("U32")
    pair = np.char.add(np.char.add(neigh_ids, ","), scores)
    counts = pos.sum(axis=1)[rows]
    bounds = np.concatenate([[0], np.cumsum(counts)])
    strs = [
        ";".join(pair[a:b]) for a, b in zip(bounds[:-1], bounds[1:])
    ]
    return np.asarray(i_ids, np.int64)[rows], strs


def structured_topk(i_ids: np.ndarray, vals: np.ndarray, inds: np.ndarray):
    """Typed companion of :func:`encode_topk` — same kept rows, same order,
    but the top-k as ``[M, k]`` matrices instead of an encoded string:
    neighbor item ids (int64, padded −1 past a row's positive neighbors) and
    scores (f64, padded 0). Row m here describes the same item as row m of
    ``encode_topk``'s output, so the two encodings can ride one DataFrame.
    Returns ``(ids_mat [M, k] int64, scores_mat [M, k] f64)``."""
    pos = vals > 0.0
    rows = np.flatnonzero(pos.any(axis=1))
    k = vals.shape[1] if vals.ndim == 2 else 0
    ids_mat = np.full((rows.size, k), -1, np.int64)
    scores_mat = np.zeros((rows.size, k), np.float64)
    keep = pos[rows]
    ids_mat[keep] = np.asarray(i_ids, np.int64)[inds[rows][keep]]
    scores_mat[keep] = vals[rows][keep]
    return ids_mat, scores_mat


_SWING_CACHE: dict = {}


def _swing_program(ctx, alpha2: float, k: int, n_items: int):
    """The jit'd item-sharded scoring program, FIFO-cached per
    (mesh, alpha2, k, n_items) like the optimizer's fused programs (jit
    re-specializes on the bucket width / shard shapes itself)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from flink_ml_tpu.ops.optimizer import _cache_put
    from flink_ml_tpu.parallel.mesh import DATA_AXIS

    key = (ctx.mesh, alpha2, k, n_items)
    cached = _SWING_CACHE.get(key)
    if cached is not None:
        return cached

    def per_shard(idx_s, item_ids_s, L, w):
        def one(args):
            idx_i, item_i = args
            P_w = idx_i.shape[0]
            Li = L[idx_i]  # [P, D] the capped purchasers' item lists
            wi = w[idx_i]  # [P]    their weights (sentinel rows 0)
            # One-hot scatter builds this item's purchaser×item incidence on
            # device (sentinel item id = n_items lands in the dropped column),
            # so no global user×item dense matrix ever exists.
            Bi = (
                jnp.zeros((P_w, n_items + 1), jnp.float32)
                .at[jnp.arange(P_w)[:, None], Li]
                .add(1.0)[:, :n_items]
            )
            # Pair weights among this item's purchasers only — [P, P]. Ci
            # counts common items; pairs with none contribute nothing (the
            # reference skips them — this also guards the 0/0 when
            # alpha2 == 0), and u == v is not a pair.
            Ci = Bi @ Bi.T
            Mi = jnp.where(Ci > 0, (wi[:, None] * wi[None, :]) / (alpha2 + Ci), 0.0)
            Mi = Mi * (1.0 - jnp.eye(P_w, dtype=Mi.dtype))
            S = 0.5 * jnp.sum(Bi * (Mi @ Bi), axis=0)  # [I]
            S = S.at[item_i].set(0.0)  # j != i
            top_vals, top_inds = jax.lax.top_k(S, k)
            return top_vals, top_inds

        vals, inds = jax.lax.map(one, (idx_s, item_ids_s))
        return vals, inds

    program = jax.jit(
        jax.shard_map(
            per_shard,
            mesh=ctx.mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P()),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        )
    )
    _cache_put(_SWING_CACHE, key, program)
    return program


class Swing(AlgoOperator, HasOutputCol, HasSeed):
    """Ref Swing.java."""

    USER_COL = StringParam("userCol", "User column name.", "user", ParamValidators.not_null())
    ITEM_COL = StringParam("itemCol", "Item column name.", "item", ParamValidators.not_null())
    MAX_USER_NUM_PER_ITEM = IntParam(
        "maxUserNumPerItem",
        "The max number of users (purchasers) sampled per item.",
        1000,
        ParamValidators.gt(0),
    )
    K = IntParam(
        "k", "The max number of similar items to output for each item.", 100, ParamValidators.gt(0)
    )
    MIN_USER_BEHAVIOR = IntParam(
        "minUserBehavior",
        "The min number of items that a user purchases to be included.",
        10,
        ParamValidators.gt(0),
    )
    MAX_USER_BEHAVIOR = IntParam(
        "maxUserBehavior",
        "The max number of items that a user purchases to be included.",
        1000,
        ParamValidators.gt(0),
    )
    ALPHA1 = IntParam(
        "alpha1", "Smooth factor for the user weight.", 15, ParamValidators.gt_eq(0)
    )
    ALPHA2 = IntParam(
        "alpha2", "Smooth factor for the common-item count.", 0, ParamValidators.gt_eq(0)
    )
    BETA = FloatParam(
        "beta", "Decay factor for the user weight.", 0.3, ParamValidators.gt_eq(0)
    )
    STRUCTURED_OUTPUT = BoolParam(
        "structuredOutput",
        "Also emit the typed top-K columns <outputCol>_ids / <outputCol>_scores "
        "alongside the reference's string encoding (the retrieval-index input "
        "format, docs/retrieval.md).",
        False,
    )

    def get_user_col(self) -> str:
        return self.get(self.USER_COL)

    def set_user_col(self, value: str):
        return self.set(self.USER_COL, value)

    def get_item_col(self) -> str:
        return self.get(self.ITEM_COL)

    def set_item_col(self, value: str):
        return self.set(self.ITEM_COL, value)

    def get_max_user_num_per_item(self) -> int:
        return self.get(self.MAX_USER_NUM_PER_ITEM)

    def set_max_user_num_per_item(self, value: int):
        return self.set(self.MAX_USER_NUM_PER_ITEM, value)

    def get_k(self) -> int:
        return self.get(self.K)

    def set_k(self, value: int):
        return self.set(self.K, value)

    def get_min_user_behavior(self) -> int:
        return self.get(self.MIN_USER_BEHAVIOR)

    def set_min_user_behavior(self, value: int):
        return self.set(self.MIN_USER_BEHAVIOR, value)

    def get_max_user_behavior(self) -> int:
        return self.get(self.MAX_USER_BEHAVIOR)

    def set_max_user_behavior(self, value: int):
        return self.set(self.MAX_USER_BEHAVIOR, value)

    def get_alpha1(self) -> int:
        return self.get(self.ALPHA1)

    def set_alpha1(self, value: int):
        return self.set(self.ALPHA1, value)

    def get_alpha2(self) -> int:
        return self.get(self.ALPHA2)

    def set_alpha2(self, value: int):
        return self.set(self.ALPHA2, value)

    def get_beta(self) -> float:
        return self.get(self.BETA)

    def set_beta(self, value: float):
        return self.set(self.BETA, value)

    def get_structured_output(self) -> bool:
        return self.get(self.STRUCTURED_OUTPUT)

    def set_structured_output(self, value: bool):
        return self.set(self.STRUCTURED_OUTPUT, value)

    @classmethod
    def load_servable(cls, path: str):
        """Load a published retrieval index distilled from this operator's
        output as its serving head (``CandidateIndex.from_swing_output`` →
        ``publish_servable``); the training stack stays unimported on the
        serving side — this hook is for symmetry with model classes."""
        from flink_ml_tpu.servable.retrieval import SwingTopKServable

        return SwingTopKServable.load_servable(path)

    def _output_frame(self, out_items, out_strs, vals=None, inds=None, i_ids=None):
        """The output DataFrame in the configured encoding(s): the reference
        string column always; when ``structuredOutput`` the typed
        ``_ids``/``_scores`` matrices for the same kept rows ride along."""
        names = [self.get_item_col(), self.get_output_col()]
        cols = [out_items, out_strs]
        if self.get_structured_output():
            out = self.get_output_col()
            if vals is None:  # the empty early-returns
                k = self.get_k()
                ids_mat = np.empty((0, k), np.int64)
                scores_mat = np.empty((0, k), np.float64)
            else:
                ids_mat, scores_mat = structured_topk(i_ids, vals, inds)
            df = DataFrame(names, None, cols)
            df.add_column(f"{out}_ids", DataTypes.vector(BasicType.LONG), ids_mat)
            df.add_column(
                f"{out}_scores", DataTypes.vector(BasicType.DOUBLE), scores_mat
            )
            return df
        return DataFrame(names, None, cols)

    def transform(self, *inputs):
        from flink_ml_tpu.parallel.mesh import get_mesh_context

        (df,) = inputs
        if self.get_max_user_behavior() < self.get_min_user_behavior():
            raise ValueError(
                "The maxUserBehavior must be greater than or equal to minUserBehavior."
            )
        users = np.asarray(df.column(self.get_user_col()), np.int64)
        items = np.asarray(df.column(self.get_item_col()), np.int64)
        empty = self._output_frame(np.asarray([], np.int64), [])
        if users.size == 0:
            return empty

        # --- host: dedup, behavior-bound filter, cap (O(interactions)) --------
        pairs = np.unique(np.stack([users, items], axis=1), axis=0)
        u_ids, u_inv = np.unique(pairs[:, 0], return_inverse=True)
        i_ids, i_inv = np.unique(pairs[:, 1], return_inverse=True)
        deg = np.bincount(u_inv)
        keep = (deg >= self.get_min_user_behavior()) & (deg <= self.get_max_user_behavior())
        kept_rows = keep[u_inv]
        if not np.any(kept_rows):
            return empty
        # dense re-index of retained users; sentinel row U for padding
        new_uid = np.full(len(u_ids), -1, np.int64)
        new_uid[keep] = np.arange(int(keep.sum()))
        ku = new_uid[u_inv[kept_rows]]
        ki = i_inv[kept_rows]
        U, I = int(keep.sum()), len(i_ids)

        alpha1, alpha2, beta = self.get_alpha1(), self.get_alpha2(), self.get_beta()
        w = np.zeros(U + 1, np.float32)
        w[:U] = 1.0 / (alpha1 + deg[keep].astype(np.float64)) ** beta

        # Padded per-user item lists (ELL, O(interactions) memory) — the
        # device scatters these into per-item incidence; sentinel item id = I.
        # Built with the sorted-rank trick (no per-user loop): sort by user,
        # rank each interaction within its user group, fancy-index once.
        from flink_ml_tpu.utils.arrays import group_ranks, next_pow2

        u_order = np.argsort(ku, kind="stable")
        sku = ku[u_order]
        rank_u = group_ranks(sku)
        D_max = max(1, int(rank_u.max()) + 1) if sku.size else 1
        L = np.full((U + 1, D_max), I, np.int32)
        L[sku, rank_u] = ki[u_order]

        # item → capped purchaser lists (sentinel user U pads: zero weight,
        # empty item list ⇒ contributes nothing). The reference reservoir-
        # samples each item's purchasers down to the cap (Swing.java:176-184);
        # ordering interactions by (item, random key) and keeping each item's
        # first ``cap`` is the same uniform without-replacement sample, done
        # for every item in one sort.
        rng = np.random.default_rng(self.get_seed())
        cap = self.get_max_user_num_per_item()
        i_order = np.lexsort((rng.random(ki.size), ki))
        ski = ki[i_order]
        rank_i = group_ranks(ski)
        capped = rank_i < cap
        ski, cap_users, rank_i = ski[capped], ku[i_order][capped], rank_i[capped]
        counts = np.bincount(ski, minlength=I)

        # --- device: score items bucketed by purchaser count ------------------
        # Power-of-two width buckets: a heavy-tailed catalog must not pay the
        # most popular item's [P, P] pair cost for every item.
        ctx = get_mesh_context()
        k = min(self.get_k(), I)
        widths = np.maximum(8, next_pow2(counts))
        vals = np.zeros((I, k), np.float64)
        inds = np.zeros((I, k), np.int64)
        member_row = np.empty(I, np.int64)
        for width in np.unique(widths):
            members = np.flatnonzero(widths == width)
            member_row[members] = np.arange(members.size)
            sel = widths[ski] == width
            idx_b = np.full((members.size, width), U, np.int32)
            idx_b[member_row[ski[sel]], rank_i[sel]] = cap_users[sel]
            idx_dev, _ = ctx.shard_batch(idx_b, pad_value=U)
            ids_dev, _ = ctx.shard_batch(np.asarray(members, np.int32))
            b_vals, b_inds = _swing_program(ctx, float(alpha2), k, I)(
                idx_dev, ids_dev, L, w
            )
            vals[members] = np.asarray(b_vals, np.float64)[: len(members)]
            inds[members] = np.asarray(b_inds)[: len(members)]

        # --- host: decode + format (Swing.java:344-361 string encoding) -------
        out_items, out_strs = encode_topk(i_ids, vals, inds)
        return self._output_frame(out_items, out_strs, vals=vals, inds=inds, i_ids=i_ids)
