"""Swing item-item similarity.

Reference: ``flink-ml-lib/.../recommendation/swing/Swing.java`` — for each item i,
over every pair of its purchasers (u, v):
    sim(i, j) += w_u · w_v / (alpha2 + |I_u ∩ I_v|)   for each j ≠ i in I_u ∩ I_v
with user weight w_u = 1/(alpha1 + |I_u|)^beta (Swing.java:367-369). Users with
fewer than ``minUserBehavior`` or more than ``maxUserBehavior`` items are
dropped; each item's purchaser list is reservoir-sampled down to
``maxUserNumPerItem``. Output row per item: (itemCol: long,
outputCol: "item,score;item,score;…" for the top ``k``) — same string encoding
(Swing.java:344-361). Defaults: k=100, maxUserNumPerItem=1000,
minUserBehavior=10, maxUserBehavior=1000, alpha1=15, alpha2=0, beta=0.3.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from flink_ml_tpu.api.core import AlgoOperator
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.params.param import FloatParam, IntParam, ParamValidators, StringParam
from flink_ml_tpu.params.shared import HasOutputCol, HasSeed

__all__ = ["Swing"]


class Swing(AlgoOperator, HasOutputCol, HasSeed):
    """Ref Swing.java."""

    USER_COL = StringParam("userCol", "User column name.", "user", ParamValidators.not_null())
    ITEM_COL = StringParam("itemCol", "Item column name.", "item", ParamValidators.not_null())
    MAX_USER_NUM_PER_ITEM = IntParam(
        "maxUserNumPerItem",
        "The max number of users (purchasers) sampled per item.",
        1000,
        ParamValidators.gt(0),
    )
    K = IntParam(
        "k", "The max number of similar items to output for each item.", 100, ParamValidators.gt(0)
    )
    MIN_USER_BEHAVIOR = IntParam(
        "minUserBehavior",
        "The min number of items that a user purchases to be included.",
        10,
        ParamValidators.gt(0),
    )
    MAX_USER_BEHAVIOR = IntParam(
        "maxUserBehavior",
        "The max number of items that a user purchases to be included.",
        1000,
        ParamValidators.gt(0),
    )
    ALPHA1 = IntParam(
        "alpha1", "Smooth factor for the user weight.", 15, ParamValidators.gt_eq(0)
    )
    ALPHA2 = IntParam(
        "alpha2", "Smooth factor for the common-item count.", 0, ParamValidators.gt_eq(0)
    )
    BETA = FloatParam(
        "beta", "Decay factor for the user weight.", 0.3, ParamValidators.gt_eq(0)
    )

    def get_user_col(self) -> str:
        return self.get(self.USER_COL)

    def set_user_col(self, value: str):
        return self.set(self.USER_COL, value)

    def get_item_col(self) -> str:
        return self.get(self.ITEM_COL)

    def set_item_col(self, value: str):
        return self.set(self.ITEM_COL, value)

    def get_max_user_num_per_item(self) -> int:
        return self.get(self.MAX_USER_NUM_PER_ITEM)

    def set_max_user_num_per_item(self, value: int):
        return self.set(self.MAX_USER_NUM_PER_ITEM, value)

    def get_k(self) -> int:
        return self.get(self.K)

    def set_k(self, value: int):
        return self.set(self.K, value)

    def get_min_user_behavior(self) -> int:
        return self.get(self.MIN_USER_BEHAVIOR)

    def set_min_user_behavior(self, value: int):
        return self.set(self.MIN_USER_BEHAVIOR, value)

    def get_max_user_behavior(self) -> int:
        return self.get(self.MAX_USER_BEHAVIOR)

    def set_max_user_behavior(self, value: int):
        return self.set(self.MAX_USER_BEHAVIOR, value)

    def get_alpha1(self) -> int:
        return self.get(self.ALPHA1)

    def set_alpha1(self, value: int):
        return self.set(self.ALPHA1, value)

    def get_alpha2(self) -> int:
        return self.get(self.ALPHA2)

    def set_alpha2(self, value: int):
        return self.set(self.ALPHA2, value)

    def get_beta(self) -> float:
        return self.get(self.BETA)

    def set_beta(self, value: float):
        return self.set(self.BETA, value)

    def transform(self, *inputs):
        (df,) = inputs
        if self.get_max_user_behavior() < self.get_min_user_behavior():
            raise ValueError(
                "The maxUserBehavior must be greater than or equal to minUserBehavior."
            )
        users = np.asarray(df.column(self.get_user_col()), np.int64)
        items = np.asarray(df.column(self.get_item_col()), np.int64)

        # user → sorted unique purchased items, filtered by behavior bounds
        user_items: Dict[int, np.ndarray] = {}
        for u in np.unique(users):
            its = np.unique(items[users == u])
            if self.get_min_user_behavior() <= len(its) <= self.get_max_user_behavior():
                user_items[int(u)] = its
        alpha1, alpha2, beta = self.get_alpha1(), self.get_alpha2(), self.get_beta()
        weights = {u: 1.0 / (alpha1 + len(its)) ** beta for u, its in user_items.items()}

        # item → purchasers (only retained users), reservoir-capped
        rng = np.random.default_rng(self.get_seed())
        item_users: Dict[int, List[int]] = {}
        for u, its in user_items.items():
            for i in its:
                item_users.setdefault(int(i), []).append(u)
        cap = self.get_max_user_num_per_item()
        for i, us in item_users.items():
            if len(us) > cap:
                item_users[i] = list(rng.choice(us, cap, replace=False))

        k = self.get_k()
        out_items: List[int] = []
        out_strs: List[str] = []
        for item, purchasers in item_users.items():
            scores: Dict[int, float] = {}
            for a in range(len(purchasers)):
                u = purchasers[a]
                for b in range(a + 1, len(purchasers)):
                    v = purchasers[b]
                    common = np.intersect1d(user_items[u], user_items[v], assume_unique=True)
                    if len(common) == 0:
                        continue
                    sim = weights[u] * weights[v] / (alpha2 + len(common))
                    for j in common:
                        if int(j) != item:
                            scores[int(j)] = scores.get(int(j), 0.0) + sim
            if not scores:
                continue
            top = sorted(scores.items(), key=lambda t: -t[1])[:k]
            out_items.append(item)
            out_strs.append(";".join(f"{j},{s}" for j, s in top))
        return DataFrame(
            [self.get_item_col(), self.get_output_col()],
            None,
            [np.asarray(out_items, np.int64), out_strs],
        )
