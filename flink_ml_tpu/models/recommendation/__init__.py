"""Recommendation algorithms. Ref flink-ml-lib/.../ml/recommendation/."""
