"""Agglomerative (hierarchical) clustering.

Reference: ``flink-ml-lib/.../clustering/agglomerativeclustering/
AgglomerativeClustering.java`` — an AlgoOperator (single-node computation over a
window of points): bottom-up merging under ``linkage`` ∈ {ward (default),
complete, single, average} with the chosen ``distanceMeasure``; stop at
``numClusters`` (default 2) or ``distanceThreshold`` (mutually exclusive);
outputs the input with a cluster-id column plus a second table of merge records
(clusterId1, clusterId2, distance, sizeOfMergedCluster) when
``computeFullTree``.

Implementation: Lance-Williams updates over a dense distance matrix — O(n³)
like the reference's in-memory HAC; fine for the windowed single-node scope.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from flink_ml_tpu.api.core import AlgoOperator
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.ops.distance import DistanceMeasure
from flink_ml_tpu.params.param import BoolParam, FloatParam, IntParam, ParamValidators, StringParam
from flink_ml_tpu.params.shared import (
    HasDistanceMeasure,
    HasFeaturesCol,
    HasPredictionCol,
    HasWindows,
)

__all__ = ["AgglomerativeClustering"]

LINKAGE_WARD = "ward"
LINKAGE_COMPLETE = "complete"
LINKAGE_SINGLE = "single"
LINKAGE_AVERAGE = "average"


class AgglomerativeClustering(
    AlgoOperator, HasFeaturesCol, HasPredictionCol, HasDistanceMeasure, HasWindows
):
    """Ref AgglomerativeClustering.java."""

    NUM_CLUSTERS = IntParam("numClusters", "The max number of clusters to create.", 2)
    DISTANCE_THRESHOLD = FloatParam(
        "distanceThreshold",
        "Threshold above which clusters will not be merged.",
        None,
    )
    LINKAGE = StringParam(
        "linkage",
        "Criterion for computing distance between two clusters.",
        LINKAGE_WARD,
        ParamValidators.in_array(
            [LINKAGE_WARD, LINKAGE_COMPLETE, LINKAGE_AVERAGE, LINKAGE_SINGLE]
        ),
    )
    COMPUTE_FULL_TREE = BoolParam(
        "computeFullTree", "Whether to compute the full merge tree.", False
    )

    def get_num_clusters(self):
        return self.get(self.NUM_CLUSTERS)

    def set_num_clusters(self, value: int):
        return self.set(self.NUM_CLUSTERS, value)

    def get_distance_threshold(self):
        return self.get(self.DISTANCE_THRESHOLD)

    def set_distance_threshold(self, value: float):
        return self.set(self.DISTANCE_THRESHOLD, value)

    def get_linkage(self) -> str:
        return self.get(self.LINKAGE)

    def set_linkage(self, value: str):
        return self.set(self.LINKAGE, value)

    def get_compute_full_tree(self) -> bool:
        return self.get(self.COMPUTE_FULL_TREE)

    def set_compute_full_tree(self, value: bool):
        return self.set(self.COMPUTE_FULL_TREE, value)

    def transform(self, *inputs):
        (df,) = inputs
        num_clusters = self.get_num_clusters()
        threshold = self.get_distance_threshold()
        if (num_clusters is None) == (threshold is None):
            raise ValueError(
                "Exactly one of numClusters and distanceThreshold must be set."
            )
        X = df.vectors(self.get_features_col()).astype(np.float64)
        n = len(X)
        linkage = self.get_linkage()
        measure = DistanceMeasure.get_instance(self.get_distance_measure())
        if linkage == LINKAGE_WARD and self.get_distance_measure() != "euclidean":
            raise ValueError("Ward linkage requires the euclidean distance measure.")

        D = np.asarray(measure.pairwise(X, X), np.float64)
        np.fill_diagonal(D, np.inf)
        if linkage == LINKAGE_WARD:
            # initial ward distance between singletons = sqrt(2)*d/√2 ≡ d; use
            # squared form internally via Lance-Williams on d²
            D = D**2

        active = list(range(n))
        sizes = {i: 1 for i in range(n)}
        members = {i: [i] for i in range(n)}
        merges: List[Tuple[int, int, float, int]] = []
        next_id = n
        stop_at = num_clusters if num_clusters is not None else 1
        full_tree = self.get_compute_full_tree() or threshold is not None

        labels_when_stopped: Optional[dict] = None
        while len(active) > 1:
            sub = D[np.ix_(active, active)]
            flat = np.argmin(sub)
            ai, bi = divmod(flat, len(active))
            if ai == bi:
                break
            a, b = active[ai], active[bi]
            dist = sub[ai, bi]
            out_dist = np.sqrt(dist) if linkage == LINKAGE_WARD else dist
            if threshold is not None and out_dist > threshold and labels_when_stopped is None:
                labels_when_stopped = {c: list(members[c]) for c in active}
                if not self.get_compute_full_tree():
                    break
            if num_clusters is not None and len(active) <= stop_at and not full_tree:
                break

            # Lance-Williams update of distances to the merged cluster
            new_row = np.empty(len(active))
            for ci, c in enumerate(active):
                if c in (a, b):
                    new_row[ci] = np.inf
                    continue
                dac, dbc = D[a, c], D[b, c]
                if linkage == LINKAGE_SINGLE:
                    new_d = min(dac, dbc)
                elif linkage == LINKAGE_COMPLETE:
                    new_d = max(dac, dbc)
                elif linkage == LINKAGE_AVERAGE:
                    new_d = (sizes[a] * dac + sizes[b] * dbc) / (sizes[a] + sizes[b])
                else:  # ward on squared distances
                    sa, sb, sc = sizes[a], sizes[b], sizes[c]
                    tot = sa + sb + sc
                    new_d = (
                        (sa + sc) * dac + (sb + sc) * dbc - sc * D[a, b]
                    ) / tot
                new_row[ci] = new_d

            merged_size = sizes[a] + sizes[b]
            merges.append((a, b, float(out_dist), merged_size))
            D = np.pad(D, ((0, 1), (0, 1)), constant_values=np.inf)
            for ci, c in enumerate(active):
                D[next_id, c] = D[c, next_id] = new_row[ci]
            sizes[next_id] = merged_size
            members[next_id] = members.pop(a) + members.pop(b)
            active.remove(a)
            active.remove(b)
            active.append(next_id)
            next_id += 1

            if num_clusters is not None and len(active) == stop_at:
                labels_when_stopped = {c: list(members[c]) for c in active}
                if not self.get_compute_full_tree():
                    break

        if labels_when_stopped is None:
            labels_when_stopped = {c: list(members[c]) for c in active}

        labels = np.zeros(n)
        for cluster_idx, (_, pts) in enumerate(sorted(labels_when_stopped.items())):
            labels[pts] = cluster_idx
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, labels)
        merge_df = DataFrame(
            ["clusterId1", "clusterId2", "distance", "sizeOfMergedCluster"],
            None,
            [
                np.asarray([m[0] for m in merges], np.int64),
                np.asarray([m[1] for m in merges], np.int64),
                np.asarray([m[2] for m in merges]),
                np.asarray([m[3] for m in merges], np.int64),
            ],
        )
        return [out, merge_df]
