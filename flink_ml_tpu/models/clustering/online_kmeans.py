"""Online k-means with decayed centroid updates.

Reference: ``flink-ml-lib/.../clustering/kmeans/OnlineKMeans.java`` — per global
batch (``ModelDataLocalUpdater.alignAndComputeModelData:200-254``): assign points to
the closest current centroid; decay previous weights by ``decayFactor`` (the
reference scales by decayFactor/parallelism per worker, then the global reducer
weight-averages — globally equivalent to one decay); for each non-empty cluster
    weight_i ← weight_i·decay + count_i
    centroid_i ← (1 − λ)·centroid_i + λ·mean(points_i),  λ = count_i / weight_i
Empty clusters keep their centroid (and decayed weight). ``OnlineKMeansModel``
serves closest-centroid predictions with the latest arrived model version and
exports the model-version gauge (OnlineKMeansModel.java:165).

The per-batch update is one jit program: one-hot matmuls for counts/sums (the same
MXU shape as batch KMeans) plus the fused decay/blend elementwise update.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Estimator
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.models.clustering.kmeans import HasK, _predict_step, _sharded_partial
from flink_ml_tpu.models.online import (
    HasCheckpointing,
    OnlineModelBase,
    array_digest,
    as_batch_stream,
)
from flink_ml_tpu.ops.distance import DistanceMeasure
from flink_ml_tpu.parallel.train_sharding import resolve_train_sharding
from flink_ml_tpu.params.param import update_existing_params
from flink_ml_tpu.params.shared import (
    HasBatchStrategy,
    HasDecayFactor,
    HasDistanceMeasure,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasPredictionCol,
    HasSeed,
)

__all__ = ["OnlineKMeans", "OnlineKMeansModel"]


@functools.cache
def _update_step(measure_name: str, k: int, decay: float):
    measure = DistanceMeasure.get_instance(measure_name)

    @jax.jit
    def step(centroids, weights, X):
        assign = measure.find_closest(X, centroids)
        hot = jax.nn.one_hot(assign, k, dtype=X.dtype)
        counts = jnp.sum(hot, axis=0)  # [k]
        sums = hot.T @ X  # [k, d]
        decayed = weights * decay
        new_weights = decayed + counts
        lam = jnp.where(new_weights > 0, counts / jnp.maximum(new_weights, 1e-16), 0.0)
        means = sums / jnp.maximum(counts, 1.0)[:, None]
        blended = (1.0 - lam[:, None]) * centroids + lam[:, None] * means
        new_centroids = jnp.where(counts[:, None] > 0, blended, centroids)
        return new_centroids, new_weights

    return step


@functools.cache
def _blend_step(k: int, decay: float):
    """Decay/blend applied to the mapreduced ``tot`` of one global batch.

    The elementwise half of the online update, split out so the sharded tier
    can feed it the deterministic ``_sharded_partial`` reduction: all inputs
    and outputs are replicated on the train mesh, so the program is identical
    at every mesh width — bit-stability of the online trajectory reduces to
    bit-stability of ``tot``, which the collectives tier guarantees.
    """

    @jax.jit
    def blend(centroids, weights, tot):
        counts = tot[:, -1]
        sums = tot[:, :-1]
        decayed = weights * decay
        new_weights = decayed + counts
        lam = jnp.where(new_weights > 0, counts / jnp.maximum(new_weights, 1e-16), 0.0)
        means = sums / jnp.maximum(counts, 1.0)[:, None]
        blended = (1.0 - lam[:, None]) * centroids + lam[:, None] * means
        new_centroids = jnp.where(counts[:, None] > 0, blended, centroids)
        return new_centroids, new_weights

    return blend


class OnlineKMeansModel(
    OnlineModelBase, HasFeaturesCol, HasPredictionCol, HasDistanceMeasure, HasK
):
    """Ref OnlineKMeansModel.java."""

    _MODEL_ARRAY_NAMES = ("centroids", "weights")

    def __init__(self):
        super().__init__()
        self.centroids: Optional[np.ndarray] = None
        self.weights: Optional[np.ndarray] = None

    def _apply_snapshot(self, payload) -> None:
        self.centroids, self.weights = (np.asarray(a) for a in payload)

    def transform(self, *inputs):
        (df,) = inputs
        if self.centroids is None:
            raise RuntimeError("no model version has arrived yet; advance() the model")
        X = df.vectors(self.get_features_col()).astype(np.float32)
        pred = _predict_step(self.get_distance_measure())(
            X, jnp.asarray(self.centroids, jnp.float32)
        )
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64))
        return out


class OnlineKMeans(
    Estimator,
    HasFeaturesCol,
    HasPredictionCol,
    HasDistanceMeasure,
    HasK,
    HasSeed,
    HasDecayFactor,
    HasGlobalBatchSize,
    HasBatchStrategy,
    HasCheckpointing,
):
    """Ref OnlineKMeans.java — requires an initial model (random or from batch KMeans)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._initial_model: Optional[tuple] = None

    def set_initial_model_data(self, model_data: DataFrame) -> "OnlineKMeans":
        centroids = np.asarray(model_data.column("centroids")[0], np.float64)
        weights = np.asarray(model_data.column("weights")[0], np.float64)
        self._initial_model = (centroids, weights)
        return self

    def set_random_initial_model_data(self, dim: int) -> "OnlineKMeans":
        """Ref KMeansModelData.generateRandomModelData — random init centroids with
        weight 0."""
        rng = np.random.default_rng(self.get_seed())
        k = self.get_k()
        self._initial_model = (rng.normal(size=(k, dim)), np.zeros(k))
        return self

    def fit(self, *inputs) -> OnlineKMeansModel:
        (data,) = inputs
        if self._initial_model is None:
            raise RuntimeError("OnlineKMeans requires initial model data")
        k = self.get_k()
        centroids0, weights0 = self._initial_model
        if centroids0.shape[0] != k:
            raise ValueError(f"initial model has {centroids0.shape[0]} centroids, k={k}")
        features_col = self.get_features_col()
        stream, bounded = as_batch_stream(data, self.get_global_batch_size())

        ts = resolve_train_sharding()
        if ts is not None and ts.n_model != 1:
            ts = None  # deterministic tier covers the data-parallel layout only
        if ts is not None:
            # Sharded per-batch update: the deterministic chunk reduction
            # batch KMeans streams through, followed by the replicated
            # decay/blend — state stays mesh-resident between batches, and the
            # published (host) snapshot per batch is the same readback the
            # legacy path pays.
            sharded = _sharded_partial(self.get_distance_measure(), k, ts)
            blend = _blend_step(k, self.get_decay_factor())

            def train_step(state, batch):
                centroids, weights = state
                window = ts.deal_cache(
                    {"x": np.asarray(batch[features_col], np.float32)}
                )
                tot = sharded(centroids, window["x"], window.mask)
                centroids, weights = blend(centroids, weights, tot)
                return (centroids, weights), (
                    np.asarray(centroids),
                    np.asarray(weights),
                )

            state0 = (
                ts.replicate(np.asarray(centroids0, np.float32)),
                ts.replicate(np.asarray(weights0, np.float32)),
            )
            metrics.counter(MLMetrics.TRAIN_GROUP, MLMetrics.TRAIN_SHARDED_FITS)
        else:
            step = _update_step(
                self.get_distance_measure(), k, self.get_decay_factor()
            )

            def train_step(state, batch):
                centroids, weights = state
                X = jnp.asarray(np.asarray(batch[features_col], np.float32))
                centroids, weights = step(centroids, weights, X)
                return (centroids, weights), (
                    np.asarray(centroids),
                    np.asarray(weights),
                )

            state0 = (
                jnp.asarray(centroids0, jnp.float32),
                jnp.asarray(weights0, jnp.float32),
            )

        driver = self._snapshot_driver(
            stream,
            train_step,
            state0,
            payload_from_state=lambda s: (np.asarray(s[0]), np.asarray(s[1])),
            dim=int(centroids0.shape[1]),
            init=array_digest(centroids0, weights0),
        )
        model = OnlineKMeansModel()
        update_existing_params(model, self)
        model._apply_snapshot((centroids0, weights0))
        driver.resume_into(model)  # continue at the checkpointed version, if any
        model._attach_stream(driver)
        if bounded:
            model.advance()
        return model
