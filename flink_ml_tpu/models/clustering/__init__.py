"""Clustering algorithms. Ref flink-ml-lib/.../ml/clustering/."""
