"""K-means clustering.

Reference: ``flink-ml-lib/.../clustering/kmeans/KMeans.java:87-183`` — random-sample
init (:96), per epoch broadcast centroids → per-partition assign + partial sums
(``CentroidsUpdateAccumulator:214``, points cached in ListStateWithCache:224) →
``countWindowAll(p).reduce`` (:168) → new centroids = sum/count with per-centroid
counts as model weights (``ModelDataGenerator``), ``TerminateOnMaxIter``;
``KMeansModelData`` = centroids[] + weights; ``KMeansModel`` predicts the closest
centroid index.

TPU-native: points live sharded in HBM (DeviceDataCache), centroids replicated; one
epoch is one jit'd SPMD program — pairwise distances ([n,d]×[d,k] MXU matmul for
euclidean/cosine), argmin assignment, and the partial-sum reduce expressed as
``one_hot(assign).T @ points``, another matmul whose cross-shard sum XLA turns into
the psum that replaces the reference's countWindowAll shuffle.

Deviation: a centroid with zero assigned points keeps its previous position (the
reference divides by zero yielding non-finite centroids; keeping the centroid is the
standard fix and never changes results when all clusters stay populated).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.iteration import DeviceDataCache
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.ops.distance import DistanceMeasure
from flink_ml_tpu.params.param import IntParam, ParamValidators, StringParam, WithParams, update_existing_params
from flink_ml_tpu.params.shared import (
    HasDistanceMeasure,
    HasFeaturesCol,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
)
from flink_ml_tpu.parallel.mesh import get_mesh_context

__all__ = ["KMeans", "KMeansModel"]


class HasK(WithParams):
    """Ref KMeansModelParams.K — number of clusters, default 2."""

    K = IntParam("k", "The max number of clusters to create.", 2, ParamValidators.gt(1))

    def get_k(self) -> int:
        return self.get(self.K)

    def set_k(self, value: int):
        return self.set(self.K, value)


def _epoch_update(measure, k: int, centroids, X, mask):
    """One KMeans epoch: assign + one-hot matmul partial sums + centroid update.
    Shared by the single-step program (multi-chip dryrun) and the fused loop."""
    assign = measure.find_closest(X, centroids)
    hot = jax.nn.one_hot(assign, k, dtype=X.dtype) * mask[:, None]
    sums = hot.T @ X  # [k, d]; cross-shard reduce inserted by XLA
    counts = jnp.sum(hot, axis=0)  # [k]
    safe = jnp.maximum(counts, 1.0)[:, None]
    new_centroids = jnp.where(counts[:, None] > 0, sums / safe, centroids)
    return new_centroids, counts


@functools.cache
def _train_step(measure_name: str, k: int):
    measure = DistanceMeasure.get_instance(measure_name)
    return jax.jit(lambda centroids, X, mask: _epoch_update(measure, k, centroids, X, mask))


@functools.cache
def _train_loop(measure_name: str, k: int, n_epochs: int):
    """All ``n_epochs`` epochs fused into ONE XLA program via ``lax.scan``.

    KMeans' only criteria is maxIter (TerminateOnMaxIter — a pure epoch count), so
    nothing needs the host between epochs: one dispatch per fit instead of one per
    epoch, which removes the host dispatch latency that dominated small steps."""
    measure = DistanceMeasure.get_instance(measure_name)

    @jax.jit
    def loop(centroids, X, mask):
        def epoch(carry, _):
            c, _counts = carry
            return _epoch_update(measure, k, c, X, mask), None

        init = (centroids, jnp.zeros((k,), X.dtype))
        (c, counts), _ = jax.lax.scan(epoch, init, None, length=n_epochs)
        return c, counts

    return loop


@functools.cache
def _predict_step(measure_name: str):
    measure = DistanceMeasure.get_instance(measure_name)
    return jax.jit(lambda X, centroids: measure.find_closest(X, centroids))


class KMeansModel(ModelArraysMixin, Model, HasFeaturesCol, HasPredictionCol, HasDistanceMeasure, HasK):
    """Ref KMeansModel.java — prediction = index of closest centroid."""

    _MODEL_ARRAY_NAMES = ("centroids", "weights")

    def __init__(self):
        super().__init__()
        self.centroids = None  # [k, d]
        self.weights = None  # [k]

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float32)
        pred = _predict_step(self.get_distance_measure())(
            X, jnp.asarray(self.centroids, jnp.float32)
        )
        out = df.clone()
        out.add_column(
            self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64)
        )
        return out


class KMeans(
    Estimator, HasFeaturesCol, HasPredictionCol, HasDistanceMeasure, HasK, HasSeed, HasMaxIter
):
    """Ref KMeans.java."""

    INIT_MODE = StringParam(
        "initMode",
        "The initialization algorithm. Supported options: 'random'.",
        "random",
        ParamValidators.in_array(["random"]),
    )

    def get_init_mode(self) -> str:
        return self.get(self.INIT_MODE)

    def set_init_mode(self, value: str):
        return self.set(self.INIT_MODE, value)

    def fit(self, *inputs) -> KMeansModel:
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float32)
        k = self.get_k()
        if X.shape[0] < k:
            raise ValueError(f"KMeans needs at least k={k} points, got {X.shape[0]}")
        # Random-sample init (ref KMeans.selectRandomCentroids:96 / DataStreamUtils.sample)
        rng = np.random.default_rng(self.get_seed())
        init = X[rng.choice(X.shape[0], size=k, replace=False)]

        ctx = get_mesh_context()
        cache = DeviceDataCache({"x": X}, ctx=ctx)
        # TerminateOnMaxIter is a pure epoch count, so the whole loop fuses into
        # one scan program — the host-loop driver (iterate_bounded_until_termination)
        # is only needed when a criteria requires a host scalar between epochs.
        loop = _train_loop(self.get_distance_measure(), k, self.get_max_iter())
        centroids, counts = loop(ctx.replicate(init), cache["x"], cache.mask)
        model = KMeansModel()
        update_existing_params(model, self)
        model.centroids = np.asarray(jax.device_get(centroids), np.float64)
        model.weights = np.asarray(jax.device_get(counts), np.float64)
        return model
