"""K-means clustering.

Reference: ``flink-ml-lib/.../clustering/kmeans/KMeans.java:87-183`` — random-sample
init (:96), per epoch broadcast centroids → per-partition assign + partial sums
(``CentroidsUpdateAccumulator:214``, points cached in ListStateWithCache:224) →
``countWindowAll(p).reduce`` (:168) → new centroids = sum/count with per-centroid
counts as model weights (``ModelDataGenerator``), ``TerminateOnMaxIter``;
``KMeansModelData`` = centroids[] + weights; ``KMeansModel`` predicts the closest
centroid index.

TPU-native: points live sharded in HBM (DeviceDataCache), centroids replicated; one
epoch is one jit'd SPMD program — pairwise distances ([n,d]×[d,k] MXU matmul for
euclidean/cosine), argmin assignment, and the partial-sum reduce expressed as
``one_hot(assign).T @ points``, another matmul whose cross-shard sum XLA turns into
the psum that replaces the reference's countWindowAll shuffle.

Deviation: a centroid with zero assigned points keeps its previous position (the
reference divides by zero yielding non-finite centroids; keeping the centroid is the
standard fix and never changes results when all clusters stay populated).
"""
from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.iteration import DeviceDataCache
from flink_ml_tpu.models.common import ModelArraysMixin
from flink_ml_tpu.ops.distance import DistanceMeasure
from flink_ml_tpu.params.param import ParamValidators, StringParam, update_existing_params
from flink_ml_tpu.params.shared import (
    HasDistanceMeasure,
    HasFeaturesCol,
    HasK,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
)
from flink_ml_tpu.parallel.collectives import mapreduce_sum
from flink_ml_tpu.parallel.mesh import get_mesh_context
from flink_ml_tpu.parallel.train_sharding import TrainSharding, resolve_train_sharding

__all__ = ["KMeans", "KMeansModel", "HasK"]


def _assign_partials(measure, k: int, centroids, X, mask):
    """assign + one-hot matmul partial sums — the single source for both the
    whole-epoch update and the streamed per-chunk accumulator."""
    assign = measure.find_closest(X, centroids)
    hot = jax.nn.one_hot(assign, k, dtype=X.dtype) * mask[:, None]
    sums = hot.T @ X  # [k, d]; cross-shard reduce inserted by XLA
    counts = jnp.sum(hot, axis=0)  # [k]
    return sums, counts


def _epoch_update(measure, k: int, centroids, X, mask):
    """One KMeans epoch: partial sums + centroid update. Shared by the
    single-step program (multi-chip dryrun) and the fused loop."""
    sums, counts = _assign_partials(measure, k, centroids, X, mask)
    safe = jnp.maximum(counts, 1.0)[:, None]
    new_centroids = jnp.where(counts[:, None] > 0, sums / safe, centroids)
    return new_centroids, counts


@functools.cache
def _train_step(measure_name: str, k: int):
    measure = DistanceMeasure.get_instance(measure_name)
    return jax.jit(lambda centroids, X, mask: _epoch_update(measure, k, centroids, X, mask))


@functools.cache
def _partial_step(measure_name: str, k: int):
    """Per-chunk partial (sums [k, d], counts [k]) for streamed training —
    the CentroidsUpdateAccumulator role; chunks combine on the host like the
    reference's countWindowAll reduce."""
    measure = DistanceMeasure.get_instance(measure_name)
    return jax.jit(
        lambda centroids, X, mask: _assign_partials(measure, k, centroids, X, mask)
    )


def _sharded_epoch_tot(measure, k: int, centroids, X, mask, axis_name, n_data):
    """Per-shard deterministic epoch reduction: per-row ``[k, d+1]``
    assignment contributions (``[one_hot·x | one_hot]`` — sums and counts in
    one tensor) folded with ``collectives.mapreduce_sum``'s width-invariant
    block/tree association instead of the matmul+psum. Returns the replicated
    totals ``tot [k, d+1]`` (``tot[:, :-1]`` sums, ``tot[:, -1]`` counts) —
    bit-identical at every mesh width for the same global point order
    (docs/distributed_training.md). Costs a transient ``[B_local, k, d+1]``
    contribution tensor, so streamed callers keep chunks modest."""
    assign = measure.find_closest(X, centroids)
    hot = jax.nn.one_hot(assign, k, dtype=X.dtype) * mask[:, None]
    aug = jnp.concatenate([X, jnp.ones((X.shape[0], 1), X.dtype)], axis=1)
    contrib = hot[:, :, None] * aug[:, None, :]  # [B_local, k, d+1]
    return mapreduce_sum(contrib, axis_name if n_data > 1 else None, n_data)


def _tot_update(tot, centroids):
    """Centroid update from replicated totals — the same zero-count-keeps-
    centroid rule as ``_epoch_update``."""
    sums, counts = tot[:, :-1], tot[:, -1]
    safe = jnp.maximum(counts, 1.0)[:, None]
    return jnp.where(counts[:, None] > 0, sums / safe, centroids), counts


# Keyed on the mesh (hashable) rather than the TrainSharding instance so two
# equal-width shardings share compiled programs, like _FUSED_CACHE in ops/.
_SHARDED_PROGRAMS: Dict[tuple, object] = {}


def _sharded_train_loop(measure_name: str, k: int, n_epochs: int, ts: TrainSharding):
    """The deterministic (train.mesh) analogue of ``_train_loop``: the whole
    fused fit as one shard_map'd scan, reducing through the width-invariant
    mapreduce tier so the fit is bit-identical across mesh widths."""
    key = ("loop", measure_name, k, n_epochs, ts.mesh)
    prog = _SHARDED_PROGRAMS.get(key)
    if prog is not None:
        return prog
    measure = DistanceMeasure.get_instance(measure_name)
    axis, n_data = ts.data_axes, ts.n_data

    def per_shard(centroids, X, mask):  # graftcheck: hot-root
        def epoch(carry, _):
            c, _counts = carry
            tot = _sharded_epoch_tot(measure, k, c, X, mask, axis, n_data)
            return _tot_update(tot, c), None

        init = (centroids, jnp.zeros((k,), X.dtype))
        (c, counts), _ = jax.lax.scan(epoch, init, None, length=n_epochs)
        return c, counts

    prog = jax.jit(
        jax.shard_map(
            per_shard,
            mesh=ts.mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=(P(), P()),
        )
    )
    _SHARDED_PROGRAMS[key] = prog
    return prog


def _sharded_partial(measure_name: str, k: int, ts: TrainSharding):
    """Per-chunk deterministic totals for the streamed fit — the
    CentroidsUpdateAccumulator role, but the chunk's cross-shard reduce
    happens ON DEVICE (replicated ``tot``), so the epoch accumulates chunk
    totals with device adds and syncs the host exactly once per epoch."""
    key = ("partial", measure_name, k, ts.mesh)
    prog = _SHARDED_PROGRAMS.get(key)
    if prog is not None:
        return prog
    measure = DistanceMeasure.get_instance(measure_name)
    axis, n_data = ts.data_axes, ts.n_data

    def per_shard(centroids, X, mask):  # graftcheck: hot-root
        return _sharded_epoch_tot(measure, k, centroids, X, mask, axis, n_data)

    prog = jax.jit(
        jax.shard_map(
            per_shard,
            mesh=ts.mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=P(),
        )
    )
    _SHARDED_PROGRAMS[key] = prog
    return prog


@functools.cache
def _train_loop(measure_name: str, k: int, n_epochs: int):
    """All ``n_epochs`` epochs fused into ONE XLA program via ``lax.scan``.

    KMeans' only criteria is maxIter (TerminateOnMaxIter — a pure epoch count), so
    nothing needs the host between epochs: one dispatch per fit instead of one per
    epoch, which removes the host dispatch latency that dominated small steps."""
    measure = DistanceMeasure.get_instance(measure_name)

    @jax.jit
    def loop(centroids, X, mask):
        def epoch(carry, _):
            c, _counts = carry
            return _epoch_update(measure, k, c, X, mask), None

        init = (centroids, jnp.zeros((k,), X.dtype))
        (c, counts), _ = jax.lax.scan(epoch, init, None, length=n_epochs)
        return c, counts

    return loop


# Shared with OnlineKMeansModel and the runtime-free KMeansModelServable —
# one jit cache entry per distance measure across all three surfaces.
from flink_ml_tpu.ops.kernels import kmeans_predict_kernel as _predict_step


class KMeansModel(ModelArraysMixin, Model, HasFeaturesCol, HasPredictionCol, HasDistanceMeasure, HasK):
    """Ref KMeansModel.java — prediction = index of closest centroid."""

    _MODEL_ARRAY_NAMES = ("centroids", "weights")

    def __init__(self):
        super().__init__()
        self.centroids = None  # [k, d]
        self.weights = None  # [k]

    @classmethod
    def load_servable(cls, path: str):
        """Runtime-free replica from this model's save dir (ref the
        LogisticRegressionModel → LogisticRegressionModelServable pairing)."""
        from flink_ml_tpu.servable.lib import KMeansModelServable

        return KMeansModelServable.load_servable(path)

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float32)
        pred = _predict_step(self.get_distance_measure())(
            X, jnp.asarray(self.centroids, jnp.float32)
        )
        out = df.clone()
        out.add_column(
            self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64)
        )
        return out


class KMeans(
    Estimator, HasFeaturesCol, HasPredictionCol, HasDistanceMeasure, HasK, HasSeed, HasMaxIter
):
    """Ref KMeans.java."""

    INIT_MODE = StringParam(
        "initMode",
        "The initialization algorithm. Supported options: 'random'.",
        "random",
        ParamValidators.in_array(["random"]),
    )

    def get_init_mode(self) -> str:
        return self.get(self.INIT_MODE)

    def set_init_mode(self, value: str):
        return self.set(self.INIT_MODE, value)

    def fit(self, *inputs) -> KMeansModel:
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float32)
        k = self.get_k()
        if X.shape[0] < k:
            raise ValueError(f"KMeans needs at least k={k} points, got {X.shape[0]}")
        # Random-sample init (ref KMeans.selectRandomCentroids:96 / DataStreamUtils.sample)
        rng = np.random.default_rng(self.get_seed())
        init = X[rng.choice(X.shape[0], size=k, replace=False)]

        ts = resolve_train_sharding()
        if ts is not None and ts.n_model == 1:
            # The deterministic sharded tier (train.mesh): block-cyclic deal
            # ingest + width-invariant mapreduce — the fit is bit-identical
            # at every mesh width (docs/distributed_training.md).
            from flink_ml_tpu.metrics import MLMetrics, metrics

            cache = ts.deal_cache({"x": X})
            loop = _sharded_train_loop(
                self.get_distance_measure(), k, self.get_max_iter(), ts
            )
            centroids, counts = loop(ts.replicate(init), cache["x"], cache.mask)
            metrics.counter(MLMetrics.TRAIN_GROUP, MLMetrics.TRAIN_SHARDED_FITS)
        else:
            ctx = get_mesh_context()
            cache = DeviceDataCache({"x": X}, ctx=ctx)
            # TerminateOnMaxIter is a pure epoch count, so the whole loop fuses
            # into one scan program — the host-loop driver
            # (iterate_bounded_until_termination) is only needed when a criteria
            # requires a host scalar between epochs.
            loop = _train_loop(self.get_distance_measure(), k, self.get_max_iter())
            centroids, counts = loop(ctx.replicate(init), cache["x"], cache.mask)
        model = KMeansModel()
        update_existing_params(model, self)
        model.centroids = np.asarray(jax.device_get(centroids), np.float64)
        model.weights = np.asarray(jax.device_get(counts), np.float64)
        return model

    def fit_stream(
        self,
        cache,
        chunk_rows: int = 65_536,
        checkpoint_manager=None,
        checkpoint_interval: int = 0,
        listeners=(),
    ) -> KMeansModel:
        """Larger-than-HBM KMeans: the point set replays from a capacity-tier
        cache (column ``features``) every epoch through the iteration driver's
        ``ReplayableDataStreamList`` — the ``ListStateWithCache:224`` role.
        Each epoch streams device-sized chunks through the partial-sum kernel
        and combines them on the host (the countWindowAll reduce). Same seed
        ⇒ same random-sample init as the in-HBM ``fit``, and matching results
        up to chunked summation order.

        ``checkpoint_manager``/``checkpoint_interval`` give the fit the same
        kill/resume contract as SGD (docs/fault_tolerance.md): the snapshot is
        ``(epoch, [centroids])`` and a rerun — e.g. a supervised restart via
        ``execution.Supervisor`` — resumes at the last snapshotted epoch and
        lands on the identical model.
        """
        from flink_ml_tpu.iteration import (
            IterationBodyResult,
            IterationConfig,
            ReplayableDataStreamList,
            iterate_bounded_until_termination,
        )
        from flink_ml_tpu.iteration.stream import rebatch

        ctx = get_mesh_context()
        ts = resolve_train_sharding()
        if ts is not None and ts.n_model != 1:
            ts = None  # the deterministic tier is data-parallel only
        k = self.get_k()
        n = int(cache.num_rows)
        if n < k:
            raise ValueError(f"KMeans needs at least k={k} points, got {n}")
        rng = np.random.default_rng(self.get_seed())
        pick = rng.choice(n, size=k, replace=False)
        init = np.concatenate(
            [np.asarray(cache.rows(int(i), int(i) + 1)["features"], np.float32) for i in pick]
        )
        if checkpoint_manager is not None:
            import hashlib
            import json as _json

            sig = {
                "algo": "KMeans.fit_stream",
                "k": k,
                "seed": self.get_seed(),
                "max_iter": self.get_max_iter(),
                "distance": self.get_distance_measure(),
                "rows": n,
                "dim": int(init.shape[1]),
            }
            if ts is not None:
                # The deterministic tier's epoch math is width-invariant, so
                # the fingerprint records the TIER, not the width: a run
                # killed at mesh=2 resumes on mesh=4 and lands on the
                # identical model. Legacy host-fold runs keep their hash.
                sig["tier"] = "deterministic"
            checkpoint_manager.set_fingerprint(
                hashlib.sha256(
                    _json.dumps(sig, sort_keys=True).encode()
                ).hexdigest()[:16]
            )
        partial = _partial_step(self.get_distance_measure(), k)
        sharded = (
            _sharded_partial(self.get_distance_measure(), k, ts)
            if ts is not None
            else None
        )
        data = ReplayableDataStreamList(replay={"points": cache})
        final_counts = np.zeros(k, np.float32)

        def _sharded_body(centroids, points):
            """One deterministic epoch: per-chunk replicated [k, d+1] totals
            accumulate ON DEVICE in fixed chunk order — no host sync per
            chunk (dispatches pipeline behind each chunk's H2D deal); the
            host reads the epoch's totals exactly once. Chunk boundaries are
            host-side and width-invariant, so the epoch is bit-identical
            across mesh widths."""
            c_dev = ts.replicate(np.asarray(centroids, np.float32))
            total = None
            for chunk in rebatch(points, chunk_rows):
                window = ts.deal_cache(
                    {"x": np.asarray(chunk["features"], np.float32)}
                )
                tot = sharded(c_dev, window["x"], window.mask)
                total = tot if total is None else total + tot
            return np.asarray(jax.device_get(total), np.float32)

        def body(variables, epoch, streams):
            nonlocal final_counts
            (centroids,) = variables
            if ts is not None:
                tot = _sharded_body(centroids, streams["points"])
                sums, counts = tot[:, :-1], tot[:, -1]
                new = np.where(
                    counts[:, None] > 0,
                    sums / np.maximum(counts, 1.0)[:, None],
                    np.asarray(centroids, np.float32),
                ).astype(np.float32)
                final_counts = counts.astype(np.float64)
                return IterationBodyResult([new], outputs=[new])
            c_dev = ctx.replicate(np.asarray(centroids, np.float32))
            sums = np.zeros((k, init.shape[1]), np.float64)
            counts = np.zeros(k, np.float64)
            # One-ahead pipelining: enqueue the chunk's (async) partials, stage
            # the NEXT chunk onto the device, and only then block on the
            # partials — H2D transfer overlaps the kernel. (The window-schedule
            # machinery in iteration/streaming.py drives minibatch trainers,
            # not full-pass accumulators, so it does not fit here.)
            pending = None
            for chunk in rebatch(streams["points"], chunk_rows):
                window = DeviceDataCache(
                    {"x": np.asarray(chunk["features"], np.float32)}, ctx=ctx
                )
                issued = partial(c_dev, window["x"], window.mask)
                if pending is not None:
                    sums += np.asarray(jax.device_get(pending[0]), np.float64)
                    counts += np.asarray(jax.device_get(pending[1]), np.float64)
                pending = issued
            if pending is not None:
                sums += np.asarray(jax.device_get(pending[0]), np.float64)
                counts += np.asarray(jax.device_get(pending[1]), np.float64)
            new = np.where(
                counts[:, None] > 0,
                sums / np.maximum(counts, 1.0)[:, None],
                centroids,
            ).astype(np.float32)
            final_counts = counts
            return IterationBodyResult([new], outputs=[new])

        outputs = iterate_bounded_until_termination(
            [init],
            body,
            config=IterationConfig(
                max_epochs=self.get_max_iter(),
                checkpoint_manager=checkpoint_manager,
                checkpoint_interval=checkpoint_interval,
            ),
            data=data,
            listeners=listeners,
        )
        if outputs:
            (centroids,) = outputs
        else:
            # Resumed at the terminal epoch: the body never ran, so the
            # snapshot IS the final model; recompute assignment counts with
            # the final centroids (one streamed pass, no centroid update).
            _, (centroids,) = checkpoint_manager.restore_latest()
            if ts is not None:
                tot = _sharded_body(centroids, cache.iter_rows())
                final_counts = tot[:, -1].astype(np.float64)
            else:
                sums = np.zeros(k, np.float64)
                c_dev = ctx.replicate(np.asarray(centroids, np.float32))
                for chunk in rebatch(cache.iter_rows(), chunk_rows):
                    window = DeviceDataCache(
                        {"x": np.asarray(chunk["features"], np.float32)}, ctx=ctx
                    )
                    _, counts = partial(c_dev, window["x"], window.mask)
                    sums += np.asarray(jax.device_get(counts), np.float64)
                final_counts = sums
        if ts is not None:
            from flink_ml_tpu.metrics import MLMetrics, metrics

            metrics.counter(MLMetrics.TRAIN_GROUP, MLMetrics.TRAIN_SHARDED_FITS)
        model = KMeansModel()
        update_existing_params(model, self)
        model.centroids = np.asarray(centroids, np.float64)
        model.weights = np.asarray(final_counts, np.float64)
        return model
