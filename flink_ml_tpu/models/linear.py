"""Shared base for the SGD-trained linear family (LogisticRegression, LinearSVC,
LinearRegression).

The reference repeats the same fit shape in three places (e.g.
``LogisticRegression.java:60-124``): map the Table to LabeledPointWithWeight, build an
initial zero coefficient, run ``SGD.optimize`` with the model-specific loss, wrap the
resulting coefficient table in the model class. This base factors that once; each
concrete estimator supplies the loss and its model class.
"""
from __future__ import annotations

from typing import Optional, Type

import numpy as np

from flink_ml_tpu.api.core import Estimator, Model
from flink_ml_tpu.models.common import ModelArraysMixin, extract_labeled_data
from flink_ml_tpu.ops.lossfunc import LossFunc
from flink_ml_tpu.ops.optimizer import SGD
from flink_ml_tpu.params.param import update_existing_params
from flink_ml_tpu.params.shared import (
    HasElasticNet,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasReg,
    HasTol,
    HasWeightCol,
)

from flink_ml_tpu.ops.kernels import compute_dots  # canonical home: ops/kernels.py
# (re-exported here for backward compatibility — the servable tier must reach
# it without importing models/, the L1 "runtime-free" guarantee)

__all__ = ["LinearEstimatorBase", "LinearModelBase", "compute_dots"]


class LinearModelBase(ModelArraysMixin, Model, HasFeaturesCol, HasPredictionCol):
    """A fitted linear model: state is the ``coefficient`` vector."""

    _MODEL_ARRAY_NAMES = ("coefficient",)

    def __init__(self):
        super().__init__()
        self.coefficient: Optional[np.ndarray] = None


class LinearEstimatorBase(
    Estimator,
    HasFeaturesCol,
    HasLabelCol,
    HasWeightCol,
    HasPredictionCol,
    HasMaxIter,
    HasLearningRate,
    HasGlobalBatchSize,
    HasTol,
    HasReg,
    HasElasticNet,
):
    """fit = extract columns → SGD with the subclass loss → model carrying coef."""

    _LOSS: LossFunc = None
    _MODEL_CLASS: Type[LinearModelBase] = None

    def _make_optimizer(self) -> SGD:
        return SGD(
            max_iter=self.get_max_iter(),
            learning_rate=self.get_learning_rate(),
            global_batch_size=self.get_global_batch_size(),
            tol=self.get_tol(),
            reg=self.get_reg(),
            elastic_net=self.get_elastic_net(),
        )

    def fit(self, *inputs) -> LinearModelBase:
        (df,) = inputs
        data = extract_labeled_data(
            df,
            self.get_features_col(),
            self.get_label_col(),
            self.get_weight_col(),
            allow_sparse=True,
        )
        self._validate_labels(data["labels"])
        dim = data.pop("dim", None) or data["features"].shape[1]
        optimizer = self._make_optimizer()
        coefficient = optimizer.optimize(np.zeros(dim, np.float32), data, self._LOSS)
        # per-epoch observability for the benchmark harness / callers
        self.loss_history = list(optimizer.loss_history)
        model = self._MODEL_CLASS()
        update_existing_params(model, self)
        model.coefficient = np.asarray(coefficient)
        return model

    def _validate_labels(self, labels: np.ndarray) -> None:
        pass
