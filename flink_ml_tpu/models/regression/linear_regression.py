"""Linear regression.

Reference: ``flink-ml-lib/.../regression/linearregression/`` — ``LinearRegression.java``
(fit = SGD + LeastSquareLoss), ``LinearRegressionModel.java`` (prediction = dot).
"""
from __future__ import annotations


import numpy as np

from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.models.linear import LinearEstimatorBase, LinearModelBase
from flink_ml_tpu.ops.lossfunc import LeastSquareLoss

__all__ = ["LinearRegression", "LinearRegressionModel"]


class LinearRegressionModel(LinearModelBase):
    """Ref LinearRegressionModel.java — prediction is the margin itself,
    computed via the shared dense/sparse ``compute_dots``."""

    def transform(self, *inputs):
        from flink_ml_tpu.models.linear import compute_dots

        (df,) = inputs
        pred = compute_dots(df, self.get_features_col(), self.coefficient)
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64))
        return out


class LinearRegression(LinearEstimatorBase):
    """Ref LinearRegression.java."""

    _LOSS = LeastSquareLoss.INSTANCE
    _MODEL_CLASS = LinearRegressionModel
