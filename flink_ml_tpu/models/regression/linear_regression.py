"""Linear regression.

Reference: ``flink-ml-lib/.../regression/linearregression/`` — ``LinearRegression.java``
(fit = SGD + LeastSquareLoss), ``LinearRegressionModel.java`` (prediction = dot).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.types import DataTypes
from flink_ml_tpu.models.linear import LinearEstimatorBase, LinearModelBase
from flink_ml_tpu.ops.lossfunc import LeastSquareLoss

__all__ = ["LinearRegression", "LinearRegressionModel"]


@functools.cache
def _predict_kernel():
    return jax.jit(lambda X, coef: X @ coef)


class LinearRegressionModel(LinearModelBase):
    """Ref LinearRegressionModel.java."""

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float32)
        pred = _predict_kernel()(X, jnp.asarray(self.coefficient, jnp.float32))
        out = df.clone()
        out.add_column(self.get_prediction_col(), DataTypes.DOUBLE, np.asarray(pred, np.float64))
        return out


class LinearRegression(LinearEstimatorBase):
    """Ref LinearRegression.java."""

    _LOSS = LeastSquareLoss.INSTANCE
    _MODEL_CLASS = LinearRegressionModel
