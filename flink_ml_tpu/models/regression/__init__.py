"""Regression algorithms. Ref flink-ml-lib/.../ml/regression/."""
