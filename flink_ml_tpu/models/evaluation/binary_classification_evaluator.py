"""Binary-classification evaluator.

Reference: ``flink-ml-lib/.../evaluation/binaryclassification/
BinaryClassificationEvaluator.java:76`` — an AlgoOperator computing, over
(label, rawPrediction[, weight]) rows sorted globally by score: areaUnderROC,
areaUnderPR, ks, areaUnderLorenz. Output: one row with the requested metrics
(default [areaUnderROC, areaUnderPR]).

Distribution mirrors the reference (sort :178, partition summaries :178, merge
:226): ``parallel.distributed_sort`` range-partitions rows by score into
per-shard buckets (ties confined to one bucket) and sorts every bucket in one
device program; each bucket then contributes a (positive, negative, total)
summary, an exclusive prefix over the summaries aligns the buckets' cumulative
curves, and the per-bucket partial curves concatenate into the global one.

Metric definitions (matching the reference's accumulation):
  - ROC AUC via the rank-sum (trapezoid over TPR/FPR with score ties grouped);
  - PR AUC via trapezoid over (recall, precision);
  - KS = max |TPR − FPR|;
  - areaUnderLorenz = trapezoid of the Lorenz curve (cumulative positive rate
    vs cumulative population rate, descending score order).
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import AlgoOperator
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import Vector
from flink_ml_tpu.params.param import StringArrayParam, ParamValidators
from flink_ml_tpu.params.shared import HasLabelCol, HasRawPredictionCol, HasWeightCol

__all__ = ["BinaryClassificationEvaluator"]

AREA_UNDER_ROC = "areaUnderROC"
AREA_UNDER_PR = "areaUnderPR"
AREA_UNDER_LORENZ = "areaUnderLorenz"
KS = "ks"


class BinaryClassificationEvaluator(
    AlgoOperator, HasLabelCol, HasRawPredictionCol, HasWeightCol
):
    """Ref BinaryClassificationEvaluator.java:76."""

    METRICS_NAMES = StringArrayParam(
        "metricsNames",
        "Names of the output metrics.",
        [AREA_UNDER_ROC, AREA_UNDER_PR],
        ParamValidators.is_sub_set([AREA_UNDER_ROC, AREA_UNDER_PR, KS, AREA_UNDER_LORENZ]),
    )

    def get_metrics_names(self):
        return self.get(self.METRICS_NAMES)

    def set_metrics_names(self, *values: str):
        return self.set(self.METRICS_NAMES, list(values))

    def transform(self, *inputs):
        (df,) = inputs
        y = df.scalars(self.get_label_col())
        raw = df.column(self.get_raw_prediction_col())
        if isinstance(raw, np.ndarray) and raw.ndim == 2:
            scores = raw[:, -1].astype(np.float64)  # P(positive) column
        elif isinstance(raw, np.ndarray):
            scores = raw.astype(np.float64)
        else:
            scores = np.asarray(
                [v.to_array()[-1] if isinstance(v, Vector) else float(v) for v in raw]
            )
        w = (
            df.scalars(self.get_weight_col())
            if self.get_weight_col()
            else np.ones(len(y))
        )

        from flink_ml_tpu.parallel.datastream_utils import distributed_sort

        # Range-partitioned global sort, descending by score; ties share a bucket.
        buckets = distributed_sort(scores, {"y": y, "w": w}, descending=True)
        buckets = [b for b in buckets if len(b["__key__"])]
        if not buckets:
            raise ValueError("Both positive and negative samples are required.")

        # Per-bucket summaries (ref partition summaries :178) and their
        # exclusive prefix (ref merge :226) align each bucket's local curve.
        sums = np.asarray(
            [
                [
                    np.sum(b["w"] * (b["y"] == 1.0)),
                    np.sum(b["w"] * (b["y"] != 1.0)),
                    np.sum(b["w"]),
                ]
                for b in buckets
            ]
        )
        pos, neg = float(sums[:, 0].sum()), float(sums[:, 1].sum())
        if pos == 0 or neg == 0:
            raise ValueError("Both positive and negative samples are required.")
        prefix = np.concatenate([np.zeros((1, 3)), np.cumsum(sums, axis=0)[:-1]])

        # Per-bucket cumulative curves at tie-group boundaries, offset by the
        # prefix; concatenation yields the global boundary curve (ties never
        # span buckets, so bucket edges are always group boundaries).
        tp_parts, fp_parts, tot_parts = [], [], []
        for b, off in zip(buckets, prefix):
            s_b = b["__key__"]
            boundary = np.nonzero(np.diff(s_b))[0]
            cut = np.concatenate([boundary, [len(s_b) - 1]])
            tp_parts.append(off[0] + np.cumsum(b["w"] * (b["y"] == 1.0))[cut])
            fp_parts.append(off[1] + np.cumsum(b["w"] * (b["y"] != 1.0))[cut])
            tot_parts.append(off[2] + np.cumsum(b["w"])[cut])
        tp = np.concatenate(tp_parts)
        fp = np.concatenate(fp_parts)
        tot = np.concatenate(tot_parts)
        tpr = np.concatenate([[0.0], tp / pos])
        fpr = np.concatenate([[0.0], fp / neg])
        recall = tpr
        precision = np.concatenate([[1.0], tp / (tp + fp)])
        pop = np.concatenate([[0.0], tot / (pos + neg)])

        values = {
            AREA_UNDER_ROC: float(np.trapezoid(tpr, fpr)),
            AREA_UNDER_PR: float(np.trapezoid(precision, recall)),
            KS: float(np.max(np.abs(tpr - fpr))),
            AREA_UNDER_LORENZ: float(np.trapezoid(tpr, pop)),
        }
        names = list(self.get_metrics_names())
        return DataFrame(names, None, [np.asarray([values[n]]) for n in names])

    def evaluate_stream(
        self, cache, bucket_rows: int = 1 << 20, spill_dir=None
    ) -> DataFrame:
        """The same metrics over a host-tier cache larger than RAM.

        Mirrors the reference's streamed shape (sort spilled via managed
        memory ``DataStreamUtils.java:409``; partition summaries :178 merged
        :226): one streaming pass computes the global (pos, neg, total)
        summary, ``distributed_sort_cache`` range-partitions and sorts by
        score out of core, and the curve trapezoids accumulate bucket by
        bucket with O(bucket) memory — the carried state is just the last
        boundary point. Result is identical to ``transform`` on the same rows
        (the curve's tie-group boundary points are bucketing-invariant).

        ``cache`` columns: the label / rawPrediction / (optional) weight
        columns named by this stage's params; rawPrediction may be [n] scores
        or [n, c] probabilities (last column used, like ``transform``).
        """
        from flink_ml_tpu.parallel.datastream_utils import distributed_sort_cache

        label_col = self.get_label_col()
        score_col = self.get_raw_prediction_col()
        weight_col = self.get_weight_col()

        def row_weights(chunk, m):
            if weight_col:
                return np.asarray(chunk[weight_col], np.float64).ravel()
            return np.ones(m, np.float64)

        # Pass A (unsorted — totals are order-free): global summary.
        pos = neg = 0.0
        for chunk in cache.iter_rows():
            y = np.asarray(chunk[label_col], np.float64).ravel()
            w = row_weights(chunk, len(y))
            pos += float(np.sum(w * (y == 1.0)))
            neg += float(np.sum(w * (y != 1.0)))
        if pos == 0 or neg == 0:
            raise ValueError("Both positive and negative samples are required.")
        tot = pos + neg

        value_cols = [label_col] + ([weight_col] if weight_col else [])
        sorted_buckets = distributed_sort_cache(
            cache,
            score_col,
            value_cols,
            descending=True,
            bucket_rows=bucket_rows,
            spill_dir=spill_dir,
            key_fn=lambda a: a[:, -1] if a.ndim == 2 else a,
        )

        # Carried state: raw cumulative sums and the last emitted curve point
        # (origin conventions match transform: tpr/fpr/pop 0, precision 1).
        tp_run = fp_run = ct_run = 0.0
        tpr_l, fpr_l, prec_l, pop_l = 0.0, 0.0, 1.0, 0.0
        auc_roc = auc_pr = lorenz = ks = 0.0
        for b in sorted_buckets:
            s_b = b["__key__"]
            y_b = np.asarray(b[label_col], np.float64).ravel()
            w_b = row_weights(b, len(y_b))
            boundary = np.nonzero(np.diff(s_b))[0]
            cut = np.concatenate([boundary, [len(s_b) - 1]])
            tp = tp_run + np.cumsum(w_b * (y_b == 1.0))[cut]
            fp = fp_run + np.cumsum(w_b * (y_b != 1.0))[cut]
            ct = ct_run + np.cumsum(w_b)[cut]
            tpr = np.concatenate([[tpr_l], tp / pos])
            fpr = np.concatenate([[fpr_l], fp / neg])
            prec = np.concatenate([[prec_l], tp / (tp + fp)])
            pop = np.concatenate([[pop_l], ct / tot])
            auc_roc += float(np.trapezoid(tpr, fpr))
            auc_pr += float(np.trapezoid(prec, tpr))
            lorenz += float(np.trapezoid(tpr, pop))
            ks = max(ks, float(np.max(np.abs(tpr - fpr))))
            tp_run, fp_run, ct_run = float(tp[-1]), float(fp[-1]), float(ct[-1])
            tpr_l, fpr_l, prec_l, pop_l = tpr[-1], fpr[-1], prec[-1], pop[-1]

        values = {
            AREA_UNDER_ROC: auc_roc,
            AREA_UNDER_PR: auc_pr,
            KS: ks,
            AREA_UNDER_LORENZ: lorenz,
        }
        names = list(self.get_metrics_names())
        return DataFrame(names, None, [np.asarray([values[n]]) for n in names])
