"""Binary-classification evaluator.

Reference: ``flink-ml-lib/.../evaluation/binaryclassification/
BinaryClassificationEvaluator.java:76`` — an AlgoOperator computing, over
(label, rawPrediction[, weight]) rows sorted globally by score: areaUnderROC,
areaUnderPR, ks, areaUnderLorenz (the reference distributes the sort and merges
partition summaries; here the sort is a single device/host sort, SURVEY.md §7's
"sort-based primitives" note). Output: one row with the requested metrics
(default [areaUnderROC, areaUnderPR]).

Metric definitions (matching the reference's accumulation):
  - ROC AUC via the rank-sum (trapezoid over TPR/FPR with score ties grouped);
  - PR AUC via trapezoid over (recall, precision);
  - KS = max |TPR − FPR|;
  - areaUnderLorenz = trapezoid of the Lorenz curve (cumulative positive rate
    vs cumulative population rate, descending score order).
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import AlgoOperator
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import Vector
from flink_ml_tpu.params.param import StringArrayParam, ParamValidators
from flink_ml_tpu.params.shared import HasLabelCol, HasRawPredictionCol, HasWeightCol

__all__ = ["BinaryClassificationEvaluator"]

AREA_UNDER_ROC = "areaUnderROC"
AREA_UNDER_PR = "areaUnderPR"
AREA_UNDER_LORENZ = "areaUnderLorenz"
KS = "ks"


class BinaryClassificationEvaluator(
    AlgoOperator, HasLabelCol, HasRawPredictionCol, HasWeightCol
):
    """Ref BinaryClassificationEvaluator.java:76."""

    METRICS_NAMES = StringArrayParam(
        "metricsNames",
        "Names of the output metrics.",
        [AREA_UNDER_ROC, AREA_UNDER_PR],
        ParamValidators.is_sub_set([AREA_UNDER_ROC, AREA_UNDER_PR, KS, AREA_UNDER_LORENZ]),
    )

    def get_metrics_names(self):
        return self.get(self.METRICS_NAMES)

    def set_metrics_names(self, *values: str):
        return self.set(self.METRICS_NAMES, list(values))

    def transform(self, *inputs):
        (df,) = inputs
        y = df.scalars(self.get_label_col())
        raw = df.column(self.get_raw_prediction_col())
        if isinstance(raw, np.ndarray) and raw.ndim == 2:
            scores = raw[:, -1].astype(np.float64)  # P(positive) column
        elif isinstance(raw, np.ndarray):
            scores = raw.astype(np.float64)
        else:
            scores = np.asarray(
                [v.to_array()[-1] if isinstance(v, Vector) else float(v) for v in raw]
            )
        w = (
            df.scalars(self.get_weight_col())
            if self.get_weight_col()
            else np.ones(len(y))
        )

        order = np.argsort(-scores, kind="stable")
        y_s, w_s, s_s = y[order], w[order], scores[order]
        pos = np.sum(w_s * (y_s == 1.0))
        neg = np.sum(w_s * (y_s != 1.0))
        if pos == 0 or neg == 0:
            raise ValueError("Both positive and negative samples are required.")

        # group score ties: evaluate curve only at group boundaries
        boundary = np.nonzero(np.diff(s_s))[0]
        cut = np.concatenate([boundary, [len(s_s) - 1]])
        tp = np.cumsum(w_s * (y_s == 1.0))[cut]
        fp = np.cumsum(w_s * (y_s != 1.0))[cut]
        tot = np.cumsum(w_s)[cut]
        tpr = np.concatenate([[0.0], tp / pos])
        fpr = np.concatenate([[0.0], fp / neg])
        recall = tpr
        precision = np.concatenate([[1.0], tp / (tp + fp)])
        pop = np.concatenate([[0.0], tot / (pos + neg)])

        values = {
            AREA_UNDER_ROC: float(np.trapezoid(tpr, fpr)),
            AREA_UNDER_PR: float(np.trapezoid(precision, recall)),
            KS: float(np.max(np.abs(tpr - fpr))),
            AREA_UNDER_LORENZ: float(np.trapezoid(tpr, pop)),
        }
        names = list(self.get_metrics_names())
        return DataFrame(names, None, [np.asarray([values[n]]) for n in names])
