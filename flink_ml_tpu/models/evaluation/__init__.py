"""Evaluation operators. Ref flink-ml-lib/.../ml/evaluation/."""
