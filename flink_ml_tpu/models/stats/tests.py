"""ChiSqTest, ANOVATest, FValueTest.

Reference: ``flink-ml-lib/.../stats/`` — AlgoOperators testing each feature
dimension against the label column:
  - ``chisqtest/ChiSqTest.java``: Pearson chi-square independence
    (contingency-table aggregation); output flattened rows
    (featureIndex, pValue, degreeOfFreedom, statistic) or one row
    (pValues, degreesOfFreedom, statistics).
  - ``anovatest/ANOVATest.java``: one-way ANOVA F vs a categorical label;
    columns (featureIndex, pValue, degreeOfFreedom, fValue) / (pValues,
    degreesOfFreedom, fValues).
  - ``fvaluetest/FValueTest.java``: F = r²/(1−r²)·(n−2) vs a continuous label;
    same output shape as ANOVATest.
The distribution tails come from ops/stats.py (jax.scipy.special).
"""
from __future__ import annotations

import numpy as np

from flink_ml_tpu.api.core import AlgoOperator
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import DenseVector
from flink_ml_tpu.ops.stats import anova_f_classification, chi_square_test, f_regression
from flink_ml_tpu.params.param import BoolParam
from flink_ml_tpu.params.shared import HasFeaturesCol, HasLabelCol

__all__ = ["ChiSqTest", "ANOVATest", "FValueTest"]


class _TestParams(HasFeaturesCol, HasLabelCol):
    FLATTEN = BoolParam(
        "flatten",
        "If false, one row with vector results; if true, one row per feature.",
        False,
    )

    def get_flatten(self) -> bool:
        return self.get(self.FLATTEN)

    def set_flatten(self, value: bool):
        return self.set(self.FLATTEN, value)


def _format(flatten: bool, p, dof, stat, stat_name: str) -> DataFrame:
    p, dof, stat = np.asarray(p), np.asarray(dof), np.asarray(stat)
    if flatten:
        return DataFrame(
            ["featureIndex", "pValue", "degreeOfFreedom", stat_name],
            None,
            [np.arange(len(p)), p, dof, stat],
        )
    plural = stat_name + "s" if not stat_name.endswith("s") else stat_name
    return DataFrame(
        ["pValues", "degreesOfFreedom", plural],
        None,
        [[DenseVector(p)], [dof], [DenseVector(stat)]],
    )


class ChiSqTest(AlgoOperator, _TestParams):
    """Ref ChiSqTest.java."""

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float64)
        y = df.scalars(self.get_label_col())
        stats, dofs, ps = [], [], []
        for j in range(X.shape[1]):
            s, dof, p = chi_square_test(X[:, j], y)
            stats.append(s)
            dofs.append(dof)
            ps.append(p)
        return _format(self.get_flatten(), ps, dofs, stats, "statistic")


class ANOVATest(AlgoOperator, _TestParams):
    """Ref ANOVATest.java."""

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float64)
        y = df.scalars(self.get_label_col())
        f, p = anova_f_classification(X, y)
        # Ref ANOVATest.java: degreeOfFreedom = dfBetween + dfWithin
        # = (numClasses − 1) + (n − numClasses) = n − 1.
        dof = np.full(X.shape[1], X.shape[0] - 1, np.int64)
        return _format(self.get_flatten(), p, dof, f, "fValue")


class FValueTest(AlgoOperator, _TestParams):
    """Ref FValueTest.java."""

    def transform(self, *inputs):
        (df,) = inputs
        X = df.vectors(self.get_features_col()).astype(np.float64)
        y = df.scalars(self.get_label_col())
        f, p = f_regression(X, y)
        dof = np.full(X.shape[1], X.shape[0] - 2, np.int64)
        return _format(self.get_flatten(), p, dof, f, "fValue")
