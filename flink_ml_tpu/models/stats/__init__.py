"""Statistical test operators. Ref flink-ml-lib/.../ml/stats/."""
