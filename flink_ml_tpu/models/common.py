"""Shared model-building infrastructure for the algorithm library.

Covers what the reference spreads across ``LabeledPointWithWeight``, per-model
ModelData classes and the broadcast-the-model transform pattern (KnnModel.java:87,
LogisticRegressionModel.transform): here a fitted model holds small host/device
arrays, transform pulls a columnar batch from the DataFrame, runs one jit'd kernel,
and appends prediction columns.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.api.types import BasicType, DataTypes
from flink_ml_tpu.utils import read_write as rw

__all__ = ["extract_labeled_data", "ModelArraysMixin"]


def extract_labeled_data(
    df: DataFrame,
    features_col: str,
    label_col: Optional[str],
    weight_col: Optional[str],
    dtype=np.float32,
    allow_sparse: bool = False,
) -> Dict[str, np.ndarray]:
    """DataFrame → columnar {features [n,d], labels [n], weights [n]} host batch.

    The analogue of the reference's ``tEnv.toDataStream(...).map(new
    LabeledPointWithWeight(...))`` boundary (LogisticRegression.java:60-80), minus the
    per-row object: columns come out as whole arrays.

    With ``allow_sparse`` and a SparseVector column, features come out in the
    padded-CSR layout instead — ``indices``/``values`` [n, K] plus ``dim`` —
    so wide sparse training (the SparseVector.java path) never densifies.
    """
    if allow_sparse and df.is_sparse(features_col):
        batch = df.sparse_batch(features_col)
        out = {
            "indices": batch.indices,
            "values": batch.values.astype(dtype),
            "dim": batch.dim,
        }
        n = batch.n
    else:
        out = {"features": df.vectors(features_col).astype(dtype)}
        n = out["features"].shape[0]
    if label_col:
        out["labels"] = df.scalars(label_col, dtype)
    out["weights"] = (
        df.scalars(weight_col, dtype) if weight_col else np.ones(n, dtype)
    )
    return out


class ModelArraysMixin:
    """Save/load + get/set model data for models whose state is named arrays.

    Persistence layout matches the framework contract (metadata JSON +
    ``data/model_data.npz``, see utils/read_write.py); ``get_model_data`` exposes the
    same arrays as a single-row DataFrame — the reference's model-data Table.
    """

    _MODEL_ARRAY_NAMES: Tuple[str, ...] = ()

    def _model_arrays(self) -> Dict[str, np.ndarray]:
        missing = [n for n in self._MODEL_ARRAY_NAMES if getattr(self, n, None) is None]
        if missing:
            raise RuntimeError(
                f"{type(self).__name__} has no model data yet (missing {missing}); "
                "fit or set_model_data first"
            )
        return {n: np.asarray(getattr(self, n)) for n in self._MODEL_ARRAY_NAMES}

    def _set_model_arrays(self, arrays: Dict[str, np.ndarray]):
        for n in self._MODEL_ARRAY_NAMES:
            setattr(self, n, np.asarray(arrays[n]))
        return self

    # --- Model API (Model.java:38,48) ---------------------------------------
    def get_model_data(self):
        arrays = self._model_arrays()
        names = list(arrays)
        return [
            DataFrame(
                names,
                [DataTypes.vector(BasicType.DOUBLE)] * len(names),
                [[_to_row_value(arrays[n])] for n in names],
            )
        ]

    def set_model_data(self, *model_data: DataFrame):
        df = model_data[0]
        arrays = {}
        for name in self._MODEL_ARRAY_NAMES:
            col = df.column(name)
            value = col[0] if not isinstance(col, np.ndarray) else col[0]
            arrays[name] = _from_row_value(value)
        return self._set_model_arrays(arrays)

    # --- persistence ---------------------------------------------------------
    def save(self, path: str) -> None:
        rw.save_metadata(self, path)
        rw.save_model_arrays(path, self._model_arrays())

    @classmethod
    def load(cls, path: str):
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        model = cls()
        model.load_param_map_from_json(metadata["paramMap"])
        model._set_model_arrays(rw.load_model_arrays(path))
        return model


def _to_row_value(array: np.ndarray):
    from flink_ml_tpu.linalg.vectors import DenseVector

    if array.ndim == 1:
        return DenseVector(array)
    return array  # matrices stay raw arrays inside the cell


def _from_row_value(value) -> np.ndarray:
    from flink_ml_tpu.linalg.vectors import Vector

    if isinstance(value, Vector):
        return value.to_array()
    return np.asarray(value)
