"""graftscope — end-to-end structured tracing with goodput attribution.

The runtime's four execution tiers (serving fast path, batch plans,
iteration, continuous loop) are instrumented with nested **spans**: where a
request's milliseconds go, phase by phase, and what fraction of traced wall
time is *productive* in the sense of the ML Productivity Goodput accounting
(PAPERS.md) — user rows moving through compiled programs — versus padding,
compiles, swaps, queueing, recovery and readback stalls.

Span model (docs/observability.md):

- ``tracer.span(name, category, scope=...)`` is a context manager; spans nest
  via a per-thread stack, so a warmup span opened inside a swap turn becomes
  its child with no plumbing.
- ``tracer.begin``/``tracer.end`` are the manual form for spans whose start
  and finish live on different code paths (a micro-batch dispatched on one
  loop turn and finalized on a later one). Parent IDs cross thread
  boundaries by carrying the parent span on a request object — the
  ``MicroBatcher`` handoff stores the request's root span on the
  ``PendingRequest`` and the batcher thread parents its queue/batch spans to
  it.
- ``tracer.record`` retro-records a completed span from already-measured
  monotonic timestamps (the queue-wait span is known only at claim time).

**Disabled is free**: ``tracer.enabled`` is a plain attribute, and every
instrumented site either checks it or calls ``tracer.span(...)``, whose
disabled path is that single attribute check followed by returning one shared
no-op span — no allocation, no lock, no clock read. Tier-1 asserts this
structurally (tests/test_trace.py).

Goodput categories partition each scope's traced wall time by **self time**
(a span's duration minus its same-scope children), so per-scope category
totals sum exactly to the scope's root-span wall time. A span carrying
``rows``/``bucket`` attrs additionally splits its self time between its own
category and ``padding`` in the pad-rows proportion — the bucket-padding
waste the serving tier's power-of-two shapes trade for compile stability.

Exporters: :meth:`SpanRecorder.export_chrome_trace` writes Chrome
trace-event JSON (load in Perfetto / chrome://tracing; one pid per scope,
one tid per thread), ``metrics.render_prometheus()`` exposes the whole
metrics registry, and with ``observability.trace.xprof`` enabled spans
mirror into ``jax.profiler.TraceAnnotation`` so they nest inside XLA
profiler dumps captured around the region (the ``benchmark --profile``
wiring). ``tools/traceview.py`` is the offline half: per-category and
per-span latency breakdowns plus the goodput fraction from an exported
trace.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional

from flink_ml_tpu.config import Options, config
from flink_ml_tpu.metrics import MLMetrics, metrics

__all__ = [
    "CAT_PRODUCTIVE",
    "CAT_QUEUE",
    "CAT_PADDING",
    "CAT_COMPILE",
    "CAT_SWAP",
    "CAT_RECOVERY",
    "CAT_READBACK",
    "CATEGORIES",
    "Span",
    "SpanRecorder",
    "GoodputReport",
    "Tracer",
    "tracer",
    "enable",
    "disable",
    "capture",
]

#: The goodput categories — a fixed vocabulary so reports aggregate across
#: tiers (the ML Productivity Goodput buckets, docs/observability.md).
CAT_PRODUCTIVE = "productive"  # user rows moving through compiled programs
CAT_QUEUE = "queue"  # admitted but waiting (batcher queue, backpressure)
CAT_PADDING = "padding"  # bucket pad rows + host-side pad work
CAT_COMPILE = "compile"  # trace/lower/compile + AOT warmup
CAT_SWAP = "swap"  # version publish / flip / checkpoint persistence
CAT_RECOVERY = "recovery"  # restart backoff, rollback, restore
CAT_READBACK = "readback"  # blocking device->host readback
CATEGORIES = (
    CAT_PRODUCTIVE,
    CAT_QUEUE,
    CAT_PADDING,
    CAT_COMPILE,
    CAT_SWAP,
    CAT_RECOVERY,
    CAT_READBACK,
)

#: Process-wide monotonically increasing span ids (itertools.count.__next__
#: is a single C call — atomic under the GIL, no lock needed).
_next_id = itertools.count(1).__next__


class Span:
    """One timed region. Created by the tracer; finished either by the
    ``with`` protocol (stack-managed) or by ``tracer.end`` (manual)."""

    __slots__ = (
        "name",
        "category",
        "scope",
        "start",
        "end",
        "span_id",
        "parent_id",
        "thread_id",
        "thread_name",
        "attrs",
        "_tracer",
        "_annotation",
    )

    def __init__(
        self,
        name: str,
        category: str,
        scope: str,
        start: float,
        span_id: int,
        parent_id: Optional[int],
        thread_id: int,
        thread_name: str,
        tracer_: Optional["Tracer"] = None,
    ):
        self.name = name
        self.category = category
        self.scope = scope
        self.start = start
        self.end: Optional[float] = None
        self.span_id = span_id
        self.parent_id = parent_id
        self.thread_id = thread_id
        self.thread_name = thread_name
        self.attrs: Optional[Dict[str, Any]] = None
        self._tracer = tracer_
        self._annotation = None

    @property
    def duration(self) -> float:
        """Seconds from start to end (0.0 while unfinished)."""
        return 0.0 if self.end is None else max(0.0, self.end - self.start)

    def set_attr(self, key: str, value: Any) -> "Span":
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    # -- stack-managed lifetime -----------------------------------------------
    def __enter__(self) -> "Span":
        self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.set_attr("error", exc_type.__name__)
        self._tracer._pop(self)
        return False

    def __repr__(self) -> str:
        return (
            f"Span({self.name!r}, category={self.category!r}, scope={self.scope!r}, "
            f"id={self.span_id}, parent={self.parent_id}, "
            f"ms={self.duration * 1000.0:.3f})"
        )


class _NoopSpan:
    """The shared disabled-path span: every method is a no-op taking only
    positional arguments, so an instrumented hot site pays one attribute
    check and zero allocation when tracing is off."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def set_attr(self, key: str, value: Any) -> "_NoopSpan":
        return self


_NOOP_SPAN = _NoopSpan()


class SpanRecorder:
    """Thread-safe bounded ring of finished spans: the newest ``capacity``
    spans are retained, older ones fall off (``dropped`` counts them). One
    recorder serves all scopes — exporters group by scope."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(config.get(Options.OBSERVABILITY_TRACE_CAPACITY))
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._spans: Deque[Span] = deque(maxlen=capacity)
        self._recorded = 0
        self._lock = threading.Lock()

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            self._recorded += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    @property
    def recorded(self) -> int:
        """Total spans ever recorded (retained + dropped)."""
        with self._lock:
            return self._recorded

    @property
    def dropped(self) -> int:
        """Spans that fell off the ring."""
        with self._lock:
            return self._recorded - len(self._spans)

    def snapshot(self) -> List[Span]:
        """The retained spans, oldest first (a consistent copy)."""
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._recorded = 0

    # -- exporters (offline/cold surface) -------------------------------------
    def goodput_report(self) -> "GoodputReport":  # graftcheck: cold
        """Aggregate the retained spans into per-scope category totals."""
        return GoodputReport.from_spans(self.snapshot())

    def export_chrome_trace(self, path: str) -> int:  # graftcheck: cold
        """Write the retained spans as Chrome trace-event JSON (loadable in
        Perfetto / chrome://tracing): one pid per scope (named via
        ``process_name`` metadata), one tid per recording thread, category on
        the event's ``cat`` plus span/parent ids and attrs under ``args``.
        Returns the number of span events written."""
        spans = self.snapshot()
        pids: Dict[str, int] = {}
        events: List[Dict[str, Any]] = []
        threads_seen: set = set()
        for span in spans:
            pid = pids.setdefault(span.scope, len(pids) + 1)
            if (pid, span.thread_id) not in threads_seen:
                threads_seen.add((pid, span.thread_id))
                events.append(
                    {
                        "ph": "M",
                        "pid": pid,
                        "tid": span.thread_id,
                        "name": "thread_name",
                        "args": {"name": span.thread_name},
                    }
                )
            args: Dict[str, Any] = {"span_id": span.span_id}
            if span.parent_id is not None:
                args["parent_id"] = span.parent_id
            if span.attrs:
                args.update(span.attrs)
            events.append(
                {
                    "ph": "X",
                    "pid": pid,
                    "tid": span.thread_id,
                    "name": span.name,
                    "cat": span.category,
                    "ts": span.start * 1e6,  # trace-event timestamps are µs
                    "dur": span.duration * 1e6,
                    "args": args,
                }
            )
        for scope, pid in pids.items():
            events.append(
                {
                    "ph": "M",
                    "pid": pid,
                    "name": "process_name",
                    "args": {"name": scope},
                }
            )
        payload = {"traceEvents": events, "displayTimeUnit": "ms"}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        return len(spans)


class GoodputReport:
    """Per-scope goodput category totals (seconds), built either from spans
    (:meth:`from_spans` — self-time attribution) or from an externally kept
    ledger of category seconds (:class:`ContinuousLearningLoop` keeps one so
    its ``ml.loop.goodput.fraction`` works with tracing off).

    Within one scope the category totals sum to the scope's root-span wall
    time — the invariant tests assert and ``tools/traceview.py`` prints.
    Scopes are accounted independently: a cross-scope child (a serving warmup
    span under a loop swap span) counts fully in BOTH scopes, because each
    scope's report answers "where did *this* scope's wall time go".
    """

    def __init__(self, totals: Dict[str, Dict[str, float]]):
        self.totals = {
            scope: {cat: s for cat, s in cats.items() if s > 0.0}
            for scope, cats in totals.items()
        }

    @classmethod
    def from_spans(cls, spans: Iterable[Span]) -> "GoodputReport":  # graftcheck: cold
        by_scope: Dict[str, List[Span]] = {}
        for span in spans:
            if span.end is not None:
                by_scope.setdefault(span.scope, []).append(span)
        totals: Dict[str, Dict[str, float]] = {}
        for scope, group in by_scope.items():
            ids = {s.span_id for s in group}
            child_s: Dict[int, float] = {}
            for s in group:
                if s.parent_id is not None and s.parent_id in ids:
                    child_s[s.parent_id] = child_s.get(s.parent_id, 0.0) + s.duration
            cats = totals.setdefault(scope, {})
            for s in group:
                self_s = max(0.0, s.duration - child_s.get(s.span_id, 0.0))
                if self_s == 0.0:
                    continue
                pad_share = _padding_share(s)
                if pad_share > 0.0:
                    cats[CAT_PADDING] = cats.get(CAT_PADDING, 0.0) + self_s * pad_share
                    self_s *= 1.0 - pad_share
                cats[s.category] = cats.get(s.category, 0.0) + self_s
        return cls(totals)

    def scopes(self) -> List[str]:
        return sorted(self.totals)

    def category_s(self, scope: str, category: str) -> float:
        return self.totals.get(scope, {}).get(category, 0.0)

    def wall_s(self, scope: str) -> float:
        """Total attributed seconds for ``scope`` (== its root-span wall)."""
        return sum(self.totals.get(scope, {}).values())

    def fraction(self, scope: Optional[str] = None) -> Optional[float]:
        """Goodput fraction — productive / total attributed — for one scope,
        or over every scope when ``scope`` is None. None when nothing is
        attributed."""
        if scope is not None:
            cats = self.totals.get(scope, {})
            total = sum(cats.values())
            return cats.get(CAT_PRODUCTIVE, 0.0) / total if total > 0.0 else None
        productive = total = 0.0
        for cats in self.totals.values():
            productive += cats.get(CAT_PRODUCTIVE, 0.0)
            total += sum(cats.values())
        return productive / total if total > 0.0 else None

    def publish(self, registry=metrics) -> None:
        """Write the ``ml.goodput.*`` gauges: per scope, one
        ``ml.goodput.<category>.ms`` gauge per attributed category plus
        ``ml.goodput.fraction``."""
        for scope, cats in self.totals.items():
            for category, seconds in cats.items():
                registry.gauge(scope, MLMetrics.goodput_ms(category), seconds * 1000.0)
            fraction = self.fraction(scope)
            if fraction is not None:
                registry.gauge(scope, MLMetrics.GOODPUT_FRACTION, fraction)

    def __repr__(self) -> str:
        return f"GoodputReport(scopes={self.scopes()}, fraction={self.fraction()})"


def _padding_share(span: Span) -> float:
    """Fraction of a span's self time attributed to bucket padding: spans
    carrying ``rows``/``bucket`` attrs executed a padded batch, and
    ``(bucket - rows) / bucket`` of their work fed pad rows.

    Spans that additionally carry ``nnz``/``nnz_cap`` attrs executed a
    sparse-convention batch (docs/sparse.md): the program computed
    ``bucket × nnz_cap`` entry cells of which only ``nnz`` (the true
    entries of the true rows) were real. That single ratio covers BOTH the
    row round-up and the ELL slot padding, and REPLACES the rows/bucket
    split for such spans — each padded cell is counted exactly once, the
    same discipline as the PR 9 DP round-up accounting."""
    attrs = span.attrs or {}
    nnz = attrs.get("nnz")
    cap = attrs.get("nnz_cap")
    bucket = attrs.get("bucket")
    if (
        isinstance(nnz, int)
        and isinstance(cap, int)
        and isinstance(bucket, int)
        and cap > 0
        and bucket > 0
    ):
        cells = bucket * cap
        if nnz < 0 or nnz >= cells:
            return 0.0
        return (cells - nnz) / cells
    attrs = span.attrs
    if not attrs:
        return 0.0
    rows = attrs.get("rows")
    bucket = attrs.get("bucket")
    if not isinstance(rows, int) or not isinstance(bucket, int) or bucket <= 0:
        return 0.0
    if rows >= bucket or rows < 0:
        return 0.0
    return (bucket - rows) / bucket


class Tracer:
    """The process tracer: one recorder, one enabled flag, per-thread span
    stacks. ``enabled`` is read on every instrumented site — keep it a plain
    attribute (the whole point of the no-op contract)."""

    #: Injectable monotonic clock; MUST share a timebase with
    #: ``time.perf_counter`` because retro-recorded spans (queue wait) reuse
    #: timestamps the serving tier already took from it.
    clock: Callable[[], float] = staticmethod(time.perf_counter)

    def __init__(self, recorder: Optional[SpanRecorder] = None, enabled: bool = False):
        # Deliberately single-writer fields: only the main (caller/API) role
        # flips them via enable()/disable(); every instrumented thread reads
        # them raw — a benign-stale read costs at most one span. Keeping
        # `enabled` a plain unlocked attribute IS the disabled-path contract.
        self.enabled = bool(enabled)  # graftcheck: owned-by=main
        self.xprof = bool(config.get(Options.OBSERVABILITY_TRACE_XPROF))  # graftcheck: owned-by=main
        self.recorder = recorder if recorder is not None else SpanRecorder()  # graftcheck: owned-by=main
        self._tls = threading.local()

    # -- span stack -----------------------------------------------------------
    def _stack(self) -> List[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current(self) -> Optional[Span]:
        """The innermost open stack-managed span on this thread."""
        stack = self._stack()
        return stack[-1] if stack else None

    def _push(self, span: Span) -> None:
        self._stack().append(span)
        if self.xprof:
            span._annotation = _enter_annotation(span.name)

    def _pop(self, span: Span) -> None:
        if span._annotation is not None:
            _exit_annotation(span._annotation)
            span._annotation = None
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:  # mis-nested exit: drop it and everything above
            del stack[stack.index(span) :]
        span.end = self.clock()
        self.recorder.record(span)

    # -- creating spans -------------------------------------------------------
    def _make(self, name: str, category: str, scope: str, parent: Optional[Span]) -> Span:
        if parent is not None:
            parent_id = parent.span_id
        else:
            top = self.current()
            parent_id = top.span_id if top is not None else None
        current_thread = threading.current_thread()
        return Span(
            name,
            category,
            scope,
            self.clock(),
            _next_id(),
            parent_id,
            current_thread.ident or 0,
            current_thread.name,
            tracer_=self,
        )

    def span(self, name: str, category: str = CAT_PRODUCTIVE, scope: str = "ml", parent: Optional[Span] = None):
        """Context-manager span. THE hot-path entry point: when disabled this
        is one attribute check returning the shared no-op span."""
        if not self.enabled:
            return _NOOP_SPAN
        return self._make(name, category, scope, parent)

    def begin(self, name: str, category: str = CAT_PRODUCTIVE, scope: str = "ml", parent: Optional[Span] = None) -> Optional[Span]:
        """Manual span: starts now, is NOT pushed on the thread stack, and
        must be finished with :meth:`end` (possibly on another thread). None
        when disabled, so call sites store-and-forward the handle blindly."""
        if not self.enabled:
            return None
        return self._make(name, category, scope, parent)

    def end(self, span: Optional[Span]) -> None:
        """Finish a manual span (None-safe — pairs with :meth:`begin`)."""
        if span is None or span.end is not None:
            return
        span.end = self.clock()
        self.recorder.record(span)

    def record(
        self,
        name: str,
        category: str,
        scope: str,
        start: float,
        end: float,
        parent: Optional[Span] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Retro-record a completed span from already-measured monotonic
        timestamps (``time.perf_counter`` timebase). The span inherits the
        parent's thread identity when given — a queue-wait span belongs to
        the thread that enqueued, not the batcher thread recording it."""
        if not self.enabled:
            return
        if parent is not None:
            parent_id, thread_id, thread_name = parent.span_id, parent.thread_id, parent.thread_name
        else:
            current_thread = threading.current_thread()
            parent_id, thread_id, thread_name = None, current_thread.ident or 0, current_thread.name
        span = Span(name, category, scope, start, _next_id(), parent_id, thread_id, thread_name)
        span.end = max(start, end)
        if attrs:
            span.attrs = dict(attrs)
        self.recorder.record(span)

    # -- lifecycle ------------------------------------------------------------
    def enable(self, capacity: Optional[int] = None, xprof: Optional[bool] = None) -> "Tracer":
        if capacity is not None:
            self.recorder = SpanRecorder(capacity)
        if xprof is not None:
            self.xprof = bool(xprof)
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    def goodput_report(self) -> GoodputReport:  # graftcheck: cold
        return self.recorder.goodput_report()


def _enter_annotation(name: str):  # graftcheck: cold
    """Open a jax.profiler.TraceAnnotation (spans nest inside XLA profiler
    dumps when a profile is active). Import is lazy and failures are
    swallowed — tracing must not require a working jax profiler."""
    try:
        from jax.profiler import TraceAnnotation

        annotation = TraceAnnotation(name)
        annotation.__enter__()
        return annotation
    except Exception:
        return None


#: jax.profiler.TraceAnnotation failures (broken profiler build): counted,
#: never raised — tracing must not take down the traced workload.
_annotation_errors = 0


def _exit_annotation(annotation) -> None:
    global _annotation_errors
    try:
        annotation.__exit__(None, None, None)
    except Exception:
        _annotation_errors += 1


#: The process tracer. ``observability.trace`` (env:
#: FLINK_ML_TPU_OBSERVABILITY_TRACE=1) arms it at import; ``enable()`` /
#: ``disable()`` flip it at runtime.
tracer = Tracer(enabled=bool(config.get(Options.OBSERVABILITY_TRACE)))


def enable(capacity: Optional[int] = None, xprof: Optional[bool] = None) -> Tracer:
    """Turn the process tracer on (optionally with a fresh ring of
    ``capacity`` and/or xprof mirroring)."""
    return tracer.enable(capacity=capacity, xprof=xprof)


def disable() -> Tracer:
    return tracer.disable()


@contextlib.contextmanager
def capture(capacity: Optional[int] = None, xprof: Optional[bool] = None):
    """Trace a region into a fresh recorder and restore the previous tracer
    state after — the test/bench/smoke harness entry point:

        with trace.capture() as recorder:
            server.predict(df)
        recorder.export_chrome_trace("/tmp/trace.json")
    """
    prev_enabled, prev_recorder, prev_xprof = tracer.enabled, tracer.recorder, tracer.xprof
    tracer.recorder = SpanRecorder(capacity)
    if xprof is not None:
        tracer.xprof = bool(xprof)
    tracer.enabled = True
    try:
        yield tracer.recorder
    finally:
        tracer.enabled = prev_enabled
        tracer.recorder = prev_recorder
        tracer.xprof = prev_xprof
